"""Autotuning CLI entry (reference ``deepspeed --autotuning`` path,
``launcher/runner.py:407``): ``dstpu --autotuning tune job.json``.

Job spec (JSON)::

    {"model": {"family": "llama", "config": {...Config kwargs...}},
     "config": {...base deepspeed_tpu config (train_batch_size etc.)...},
     "model_info": {"num_params": ..., "hidden_size": ..., ...},  # optional
     "tuner": "model_based" | "gridsearch" | "random",
     "micro_batches": [1, 2, 4], "zero_stages": [0, 1, 2, 3],
     "max_trials": 8, "trial_steps": 3, "seq_len": 128,
     "output": "autotune_best.json"}

Every trial runs in its own worker process (``trial_worker``) — fresh XLA
client/jit cache, OOM-survivable, per-trial timeout. The best full config is
written to ``output`` and printed as one JSON line.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from ..utils.logging import log_dist
from .autotuner import Autotuner


def autotune_main(job_path: str, extra_args: Optional[List[str]] = None) -> int:
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # a CPU-pinned environment (tests/CI) must not probe the
        # accelerator; the axon sitecustomize overrides the env var, so
        # only this in-process update honors it
        jax.config.update("jax_platforms", "cpu")
    if extra_args:
        raise ValueError(
            f"unexpected arguments after the job JSON: {extra_args} — all "
            f"autotuning options (max_trials, tuner, ...) live in the job "
            f"file")
    with open(job_path) as f:
        job = json.load(f)
    if "model" not in job or "family" not in job["model"]:
        raise ValueError(
            "autotuning job needs model.family (+ model.config) so trials "
            "can rebuild the model in isolated worker processes")
    kw = {}
    for src, dst in (("tuner", "tuner_type"), ("micro_batches", None),
                     ("zero_stages", None), ("trial_steps", None),
                     ("seq_len", None), ("model_info", None),
                     ("trial_timeout_s", None)):
        if src in job:
            kw[dst or src] = job[src]
    tuner = Autotuner(None, job.get("config", {}),
                      model_desc=job["model"], **kw)
    best = tuner.tune(max_trials=job.get("max_trials"))
    best_cfg = tuner.best_ds_config()
    out_path = job.get("output", "autotune_best.json")
    report = {
        "best_config": best_cfg,
        "best_point": best.config,
        "samples_per_sec": best.samples_per_sec,
        "trials": [{"point": r.config, "samples_per_sec": r.samples_per_sec,
                    "error": r.error} for r in tuner.results],
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    log_dist(f"autotuning: best config written to {out_path}")
    print(json.dumps({"best": best.config,
                      "samples_per_sec": best.samples_per_sec,
                      "output": out_path}))
    return 0


if __name__ == "__main__":
    sys.exit(autotune_main(sys.argv[1]))
