"""Autotuner: memory-model pruning + trial runs over sharding/micro-batch
configurations.

Reference parity: ``deepspeed/autotuning/autotuner.py:42`` — profiles the
model (param/activation memory, ``autotuning_profile_model_info``), prunes the
ZeRO-stage search space with a memory model, then runs grid/random/model-based
tuners over (micro_batch, GAS, zero_stage) with each trial a real short run.
TPU-first: a "trial" is N ``train_batch`` steps of a freshly-initialized
engine on the CURRENT devices (jit caching makes repeat trials cheap), the
memory model counts HBM bytes per chip under each ZeRO stage's sharding specs,
and the search adds TPU-specific knobs (remat policy) to the space.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..tuning.registry import config_set, default_registry
from ..utils.logging import log_dist, logger
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


@dataclasses.dataclass
class TrialResult:
    config: Dict[str, Any]
    samples_per_sec: float
    step_time_s: float
    error: Optional[str] = None


def estimate_memory_per_chip(num_params: int, zero_stage: int, n_chips: int,
                             micro_batch: int, seq_len: int, hidden: int,
                             num_layers: int, remat: bool = False,
                             optimizer_factor: int = 2,
                             compute_bytes: int = 2) -> int:
    """HBM bytes/chip under a ZeRO stage (reference memory model
    ``autotuning/utils.py`` + ZeRO stage arithmetic):

    - master params fp32 + optimizer states (Adam: 2 slots fp32)
    - compute-dtype param copy (bf16) at use time
    - gradients fp32
    - activations ≈ micro_batch × seq × hidden × layers × compute_bytes
      (× ~4 ops/layer without remat, ×1 with remat — scan keeps one block)
    """
    fp32 = 4
    opt = num_params * fp32 * optimizer_factor
    master = num_params * fp32
    grads = num_params * fp32
    if zero_stage >= 1:
        opt //= n_chips
    if zero_stage >= 2:
        grads //= n_chips
    live_params = num_params * compute_bytes
    if zero_stage >= 3:
        master //= n_chips
        live_params //= max(1, n_chips // 2)  # gathered layer-by-layer
    act_factor = 1 if remat else 4
    acts = micro_batch * seq_len * hidden * num_layers * compute_bytes * act_factor
    return int(master + opt + grads + live_params + acts)


# Search-space ladders come from the shared tunable catalog
# (tuning/registry.py) so the offline grid and the online tuner search the
# SAME space — hand-rolled tuples here are deprecated; register/adjust
# knobs in the catalog instead.
DEFAULT_MICRO_BATCHES = default_registry().choices("train.micro_batch")
DEFAULT_STAGES = default_registry().choices("train.zero_stage")


class Autotuner:
    """Find the fastest feasible (zero_stage, micro_batch, gas, remat) for a
    model + target global batch on the current devices."""

    def __init__(self, model_spec, base_config: Dict[str, Any], *,
                 model_info: Optional[Dict[str, int]] = None,
                 hbm_bytes_per_chip: Optional[int] = None,
                 trial_steps: int = 3,
                 tuner_type: str = "model_based",
                 micro_batches: Sequence[int] = DEFAULT_MICRO_BATCHES,
                 zero_stages: Sequence[int] = DEFAULT_STAGES,
                 remat_options: Sequence[bool] = (False,),
                 model_desc: Optional[Dict[str, Any]] = None,
                 trial_timeout_s: float = 900.0,
                 seq_len: Optional[int] = None):
        self.model_spec = model_spec
        self.base_config = dict(base_config)
        self.trial_steps = trial_steps
        self.tuner_type = tuner_type
        self.n_chips = len(jax.devices())
        self.hbm = hbm_bytes_per_chip or self._detect_hbm()
        self.model_info = model_info or {}
        self.micro_batches = micro_batches
        self.zero_stages = zero_stages
        self.remat_options = remat_options
        # model_desc = {"family": ..., "config": {...}}: when given, each
        # trial runs in a SUBPROCESS (trial_worker) — fresh XLA client and
        # jit cache per trial, an OOM kills only that trial, and timings
        # are not skewed by cross-trial cache warmth (reference
        # autotuning/scheduler.py launches real jobs for the same reasons)
        self.model_desc = model_desc
        self.trial_timeout_s = trial_timeout_s
        self.seq_len = seq_len
        self.results: List[TrialResult] = []
        if model_spec is None and model_desc is None:
            raise ValueError("need model_spec (in-process trials) or "
                             "model_desc (subprocess trials)")

    def _detect_hbm(self) -> int:
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
        return 16 << 30  # v5e-class default

    # ------------------------------------------------------------------ #
    def build_space(self) -> List[Dict[str, Any]]:
        """Enumerate + memory-prune (reference prunes ZeRO stages whose
        estimated requirement exceeds available memory)."""
        gbs = int(self.base_config.get("train_batch_size", 8))
        info = self.model_info
        space = []
        for mb, stage, remat in itertools.product(self.micro_batches,
                                                  self.zero_stages,
                                                  self.remat_options):
            dp = self.n_chips  # trials run data-parallel over local chips
            if gbs % (mb * dp) != 0:
                continue
            if info.get("num_params"):
                est = estimate_memory_per_chip(
                    info["num_params"], stage, self.n_chips, mb,
                    info.get("seq_len", 2048), info.get("hidden_size", 4096),
                    info.get("num_layers", 32), remat=remat)
                if est > self.hbm:
                    continue
            space.append({"zero_stage": stage, "micro_batch": mb,
                          "gas": gbs // (mb * dp), "remat": remat})
        return space

    def _trial_config(self, point: Dict[str, Any]) -> Dict[str, Any]:
        cfg = json.loads(json.dumps(self.base_config))  # deep copy
        # knob writes go through the catalog's declared dot-paths —
        # config_set walks/creates nested dict blocks the same way the
        # online tuner walks the live typed config tree
        reg = default_registry()
        config_set(cfg, reg.get("train.micro_batch").path,
                   point["micro_batch"])
        cfg["gradient_accumulation_steps"] = point["gas"]
        cfg.pop("train_batch_size", None)
        config_set(cfg, reg.get("train.zero_stage").path,
                   point["zero_stage"])
        config_set(cfg, reg.get("train.remat_policy").path,
                   "full" if point["remat"] else "none")
        cfg["steps_per_print"] = 0
        return cfg

    def run_trial_subprocess(self, point: Dict[str, Any]) -> TrialResult:
        """One trial in an isolated worker process (fresh jit cache; an OOM
        or wedge is contained by the process boundary + timeout)."""
        import subprocess
        import sys
        import tempfile

        job = {"model": self.model_desc,
               "trial_config": self._trial_config(point),
               "trial_steps": self.trial_steps}
        if self.seq_len:
            job["seq_len"] = self.seq_len
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(job, f)
            job_path = f.name
        try:
            r = subprocess.run(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.trial_worker", job_path],
                capture_output=True, text=True,
                timeout=self.trial_timeout_s)
            tail = r.stdout.strip().splitlines()[-1] if r.stdout.strip() \
                else "{}"
            d = json.loads(tail)
            res = TrialResult(point, float(d.get("samples_per_sec", 0.0)),
                              float(d.get("step_time_s", float("inf"))),
                              error=d.get("error") or (
                                  None if r.returncode == 0
                                  else f"rc={r.returncode} "
                                       f"{r.stderr[-300:]}"))
        except subprocess.TimeoutExpired:
            res = TrialResult(point, 0.0, float("inf"),
                              error=f"timeout after {self.trial_timeout_s}s")
        except Exception as e:
            res = TrialResult(point, 0.0, float("inf"), error=str(e)[-300:])
        finally:
            try:
                os.unlink(job_path)
            except OSError:
                pass
        self.results.append(res)
        log_dist(f"autotuning trial {point} [subprocess]: "
                 f"{res.samples_per_sec:.2f} samples/s"
                 + (f" ({res.error})" if res.error else ""))
        return res

    def run_trial(self, point: Dict[str, Any],
                  data_fn: Callable[[int], Any]) -> TrialResult:
        import deepspeed_tpu as dst
        from ..comm.mesh import set_mesh

        cfg = self._trial_config(point)
        try:
            set_mesh(None)  # each trial builds its mesh fresh
            engine, *_ = dst.initialize(model=self.model_spec, config=cfg)
            batch = data_fn(engine.train_batch_size())
            engine.train_batch(batch)  # compile + warmup
            t0 = time.perf_counter()
            for _ in range(self.trial_steps):
                out = engine.train_batch(batch)
            jax.block_until_ready(out.loss)
            dt = (time.perf_counter() - t0) / self.trial_steps
            res = TrialResult(point, engine.train_batch_size() / dt, dt)
        except Exception as e:  # OOM / bad config — score 0, keep tuning
            logger.warning(f"autotuning trial {point} failed: {e}")
            res = TrialResult(point, 0.0, float("inf"), error=str(e))
        self.results.append(res)
        log_dist(f"autotuning trial {point}: "
                 f"{res.samples_per_sec:.2f} samples/s")
        return res

    def tune(self, data_fn: Optional[Callable[[int], Any]] = None,
             max_trials: Optional[int] = None) -> TrialResult:
        space = self.build_space()
        if not space:
            raise ValueError("autotuning space is empty after memory pruning")
        if self.model_desc is not None:
            trial = lambda p: self.run_trial_subprocess(p).samples_per_sec  # noqa: E731
        else:
            if data_fn is None:
                raise ValueError("in-process tuning needs a data_fn")
            trial = lambda p: self.run_trial(p, data_fn).samples_per_sec  # noqa: E731
        tuner = TUNERS[self.tuner_type](space, trial)
        best_cfg, best_metric = tuner.tune(max_trials)
        best = next(r for r in self.results
                    if r.config == best_cfg and r.samples_per_sec == best_metric)
        log_dist(f"autotuning best: {best.config} "
                 f"({best.samples_per_sec:.2f} samples/s over "
                 f"{len(self.results)} trials)")
        return best

    def best_ds_config(self) -> Dict[str, Any]:
        best = max(self.results, key=lambda r: r.samples_per_sec)
        return self._trial_config(best.config)
