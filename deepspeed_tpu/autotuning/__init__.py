from .autotuner import Autotuner, TrialResult  # noqa: F401
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner  # noqa: F401
