"""Search strategies over the tuning space.

Reference parity: ``deepspeed/autotuning/tuner/`` — ``GridSearchTuner`` /
``RandomTuner`` (``index_based_tuner.py:27/:11``) and ``ModelBasedTuner`` with
``XGBoostCostModel`` (``model_based_tuner.py:19``, ``cost_model.py:14``).
The model-based tuner here fits a least-squares cost model over one-hot
encoded config features (numpy only — no xgboost in image), exploring
highest-predicted-throughput configs first after a random warmup.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Config = Dict[str, Any]


class BaseTuner:
    def __init__(self, space: Sequence[Config], metric_fn: Callable[[Config], float]):
        self.space = list(space)
        self.metric_fn = metric_fn
        self.records: List[Tuple[Config, float]] = []

    @property
    def best(self) -> Optional[Tuple[Config, float]]:
        return max(self.records, key=lambda r: r[1]) if self.records else None

    def _measure(self, cfg: Config) -> float:
        m = self.metric_fn(cfg)
        self.records.append((cfg, m))
        return m

    def tune(self, max_trials: Optional[int] = None) -> Tuple[Config, float]:
        raise NotImplementedError


class GridSearchTuner(BaseTuner):
    def tune(self, max_trials: Optional[int] = None) -> Tuple[Config, float]:
        for cfg in self.space[:max_trials]:
            self._measure(cfg)
        return self.best


class RandomTuner(BaseTuner):
    def __init__(self, space, metric_fn, seed: int = 0):
        super().__init__(space, metric_fn)
        self.rng = random.Random(seed)

    def tune(self, max_trials: Optional[int] = None) -> Tuple[Config, float]:
        n = min(max_trials or len(self.space), len(self.space))
        for cfg in self.rng.sample(self.space, n):
            self._measure(cfg)
        return self.best


class ModelBasedTuner(BaseTuner):
    """Random warmup → least-squares surrogate → greedy exploration."""

    def __init__(self, space, metric_fn, seed: int = 0, warmup: int = 3):
        super().__init__(space, metric_fn)
        self.rng = random.Random(seed)
        self.warmup = warmup
        # one-hot feature map over every (key, value) seen in the space
        keys = sorted({(k, repr(v)) for cfg in self.space for k, v in cfg.items()})
        self._feat_index = {kv: i for i, kv in enumerate(keys)}

    def _features(self, cfg: Config) -> np.ndarray:
        x = np.zeros((len(self._feat_index) + 1,))
        x[-1] = 1.0  # bias
        for k, v in cfg.items():
            i = self._feat_index.get((k, repr(v)))
            if i is not None:
                x[i] = 1.0
        return x

    def _predict(self) -> Optional[np.ndarray]:
        if len(self.records) < 2:
            return None
        X = np.stack([self._features(c) for c, _ in self.records])
        y = np.asarray([m for _, m in self.records])
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        return np.stack([self._features(c) for c in self.space]) @ w

    def tune(self, max_trials: Optional[int] = None) -> Tuple[Config, float]:
        n = min(max_trials or len(self.space), len(self.space))
        tried = set()
        order = self.rng.sample(range(len(self.space)), len(self.space))
        for trial in range(n):
            if trial < self.warmup:
                idx = next(i for i in order if i not in tried)
            else:
                pred = self._predict()
                cand = sorted(range(len(self.space)),
                              key=lambda i: -(pred[i] if pred is not None else 0))
                idx = next(i for i in cand if i not in tried)
            tried.add(idx)
            self._measure(self.space[idx])
        return self.best
