"""One autotuning trial in an isolated process.

The reference runs every autotuning experiment as a real launcher job
(``deepspeed/autotuning/scheduler.py``) so an OOM kills only that trial and
no jit/alloc state leaks between configurations. This is the TPU analog:
``python -m deepspeed_tpu.autotuning.trial_worker job.json`` builds a fresh
engine in a fresh process (fresh XLA client, fresh jit cache), times
``trial_steps`` train steps on synthetic tokens, and prints ONE JSON line
``{"samples_per_sec": ..., "step_time_s": ...}``.

Job spec (JSON file)::

    {"model": {"family": "llama", "config": {...Config kwargs...}},
     "trial_config": {<full deepspeed_tpu config for this trial>},
     "trial_steps": 3, "seq_len": 128}
"""

from __future__ import annotations

import json
import os
import sys
import time


def run_job(job: dict) -> dict:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # honor a CPU-pinned parent (tests/CI); the axon sitecustomize
        # overrides the env var, so the config update is required
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu as dst
    from ..models.hf_import import resolve_module

    model = job["model"]
    module = resolve_module(model["family"])
    cfg_cls = next(v for k, v in vars(module).items()
                   if k.endswith("Config") and isinstance(v, type))
    mcfg = cfg_cls(**model.get("config", {}))
    spec = module.model_spec(mcfg)
    engine, *_ = dst.initialize(model=spec, config=job["trial_config"])
    seq = int(job.get("seq_len", min(128, mcfg.max_seq_len)))
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(
        0, mcfg.vocab_size, (engine.train_batch_size(), seq + 1),
        dtype=np.int32)}
    steps = int(job.get("trial_steps", 3))
    float(engine.train_batch(batch).loss)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        out = engine.train_batch(batch)
    float(out.loss)
    dt = (time.perf_counter() - t0) / steps
    return {"samples_per_sec": engine.train_batch_size() / dt,
            "step_time_s": dt}


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    with open(argv[0]) as f:
        job = json.load(f)
    try:
        result = run_job(job)
    except Exception as e:
        print(json.dumps({"samples_per_sec": 0.0,
                          "step_time_s": float("inf"),
                          "error": str(e)[-500:]}))
        return 0  # the JSON line IS the report
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
