"""Parallel ssh fan-out over a hostfile (reference ``bin/ds_ssh``): run one
command on every resource-pool host and stream per-host output."""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from typing import List

from .runner import fetch_hostfile


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="dstpu_ssh")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host")
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    hosts = fetch_hostfile(args.hostfile)
    if not hosts:
        print(f"no hosts in {args.hostfile}; running locally")
        return subprocess.run(args.command).returncode
    cmd = shlex.join(args.command)  # preserve argv boundaries remotely
    procs = {h: subprocess.Popen(
        ["ssh", "-o", "StrictHostKeyChecking=no", h, cmd],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for h in hosts}
    rc = 0
    for h, proc in procs.items():
        out, _ = proc.communicate()
        for line in (out or "").splitlines():
            print(f"[{h}] {line}")
        rc = rc or proc.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
