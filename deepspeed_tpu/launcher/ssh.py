"""Parallel ssh fan-out over a hostfile (reference ``bin/ds_ssh``): run one
command on every resource-pool host and stream per-host output."""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
import threading
from typing import List

from .runner import fetch_hostfile


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="dstpu_ssh")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host")
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    hosts = fetch_hostfile(args.hostfile)
    if not hosts:
        print(f"no hosts in {args.hostfile}; running locally")
        return subprocess.run(args.command).returncode
    cmd = shlex.join(args.command)  # preserve argv boundaries remotely
    procs = {h: subprocess.Popen(
        ["ssh", "-o", "StrictHostKeyChecking=no", h, cmd],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        errors="replace")
        for h in hosts}
    # drain every host's pipe concurrently — a chatty later host must not
    # block behind an earlier one filling its OS pipe buffer — but print
    # each host as soon as its predecessors finish, so one wedged host
    # doesn't black out all output
    outputs: dict = {}

    def _drain(h, proc):
        try:
            outputs[h] = proc.communicate()[0]
        except Exception as e:  # a dead drain must not report success
            outputs[h] = f"dstpu_ssh: drain failed: {e!r}"
            proc.kill()

    threads = {h: threading.Thread(target=_drain, args=(h, p), daemon=True)
               for h, p in procs.items()}
    for t in threads.values():
        t.start()
    rc = 0
    for h, proc in procs.items():
        threads[h].join()
        for line in (outputs.get(h) or "").splitlines():
            print(f"[{h}] {line}")
        rc = rc or (1 if proc.returncode is None else proc.returncode)
    return rc


if __name__ == "__main__":
    sys.exit(main())
