"""Multi-host launcher CLI.

Reference parity: ``bin/deepspeed`` → ``launcher/runner.py:436 main`` (hostfile
parse :230, --include/--exclude filters :310) → per-node ``launcher/launch.py``
and the ``MultiNodeRunner`` family (``multinode_runner.py``: PDSH/MPI/SLURM).

TPU-first redesign: the reference forks one OS process per GPU and wires NCCL
ranks; on TPU the unit is one process per HOST (each process drives all local
chips), and the only true bootstrap job is ``jax.distributed.initialize`` —
so the launcher's work is (a) resolve the host list, (b) start one process per
host with coordinator env (``DSTPU_COORDINATOR``, ``DSTPU_NUM_PROCESSES``,
``DSTPU_PROCESS_ID``), via ssh/pdsh/slurm or locally.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

DEFAULT_COORD_PORT = 8476


# --------------------------------------------------------------------------- #
# hostfile handling (reference runner.py:230 fetch_hostfile)
# --------------------------------------------------------------------------- #
def parse_hostfile(text: str) -> Dict[str, int]:
    """'hostname slots=N' lines → {host: slots}. Comments/#/blank ignored."""
    hosts: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        if host in hosts:
            raise ValueError(f"duplicate host {host} in hostfile")
        hosts[host] = slots
    return hosts


def fetch_hostfile(path: Optional[str]) -> Optional[Dict[str, int]]:
    if not path or not os.path.isfile(path):
        return None
    with open(path) as f:
        return parse_hostfile(f.read())


def parse_inclusion_exclusion(hosts: Dict[str, int], include: str,
                              exclude: str) -> Dict[str, int]:
    """'--include host1@host2' / '--exclude host3' filters (reference :310).
    Per-slot syntax 'host:0,1' limits slot count on that host."""

    def parse_filter(s: str) -> Dict[str, Optional[List[int]]]:
        out: Dict[str, Optional[List[int]]] = {}
        for item in filter(None, s.split("@")):
            if ":" in item:
                host, slots = item.split(":", 1)
                out[host] = [int(x) for x in slots.split(",")]
            else:
                out[item] = None
        return out

    inc, exc = parse_filter(include), parse_filter(exclude)
    if inc and exc:
        raise ValueError("--include and --exclude are mutually exclusive")
    result = dict(hosts)
    if inc:
        result = {}
        for host, slots in inc.items():
            if host not in hosts:
                raise ValueError(f"included host {host} not in hostfile")
            result[host] = len(slots) if slots else hosts[host]
    for host, slots in exc.items():
        if host not in result:
            raise ValueError(f"excluded host {host} not in hostfile")
        if slots is None:
            del result[host]
        else:
            result[host] = max(0, result[host] - len(slots))
    return {h: s for h, s in result.items() if s > 0}


def encode_world_info(hosts: Dict[str, int]) -> str:
    """base64 world info passed to every node (reference :401)."""
    return base64.urlsafe_b64encode(json.dumps(hosts).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, int]:
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


# --------------------------------------------------------------------------- #
# multi-node runners (reference multinode_runner.py)
# --------------------------------------------------------------------------- #
class MultiNodeRunner:
    """Builds the per-node command lines; subclasses pick the transport."""

    name = "base"

    def __init__(self, args, world_info: Dict[str, int]):
        self.args = args
        self.world_info = world_info
        self.hosts = list(world_info.keys())

    def backend_exists(self) -> bool:
        return True

    def node_env(self, process_id: int) -> Dict[str, str]:
        coordinator = f"{self.hosts[0]}:{self.args.coordinator_port}"
        return {
            "DSTPU_COORDINATOR": coordinator,
            "DSTPU_NUM_PROCESSES": str(len(self.hosts)),
            "DSTPU_PROCESS_ID": str(process_id),
            "DSTPU_WORLD_INFO": encode_world_info(self.world_info),
        }

    def user_cmd(self) -> List[str]:
        return [sys.executable, self.args.user_script] + self.args.user_args

    def get_cmd(self) -> List[List[str]]:
        raise NotImplementedError


class LocalRunner(MultiNodeRunner):
    """Single host: exec the user script in-place with bootstrap env."""

    name = "local"

    def get_cmd(self) -> List[List[str]]:
        return [self.user_cmd()]


class LocalMultiRunner(MultiNodeRunner):
    """N processes on ONE host, coordinator on localhost — the reference's
    per-device fork (``launcher/launch.py:145`` spawns ``num_local_procs``
    workers with RANK/LOCAL_RANK env). On TPU pods one process drives all
    local chips so this is mainly the CPU/simulation path — but it is the
    same bootstrap contract (``jax.distributed.initialize``) as a real
    multi-host launch, which is exactly what makes it the right
    end-to-end launcher test double."""

    name = "local_multi"

    def __init__(self, args, world_info: Dict[str, int], nproc: int):
        super().__init__(args, world_info)
        self.nproc = nproc

    def node_env(self, process_id: int) -> Dict[str, str]:
        env = super().node_env(process_id)
        env["DSTPU_COORDINATOR"] = \
            f"127.0.0.1:{self.args.coordinator_port}"
        env["DSTPU_NUM_PROCESSES"] = str(self.nproc)
        # world info must agree with the actual process count, not the
        # 1-host hostfile it was derived from
        env["DSTPU_WORLD_INFO"] = encode_world_info({"localhost": self.nproc})
        return env

    def get_cmd(self) -> List[List[str]]:
        return [self.user_cmd() for _ in range(self.nproc)]


class PDSHRunner(MultiNodeRunner):
    """ssh fan-out, one command per host (reference PDSHRunner :55 — we emit
    explicit per-host ssh lines rather than requiring pdsh)."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("ssh") is not None

    def get_cmd(self) -> List[List[str]]:
        cmds = []
        for pid, host in enumerate(self.hosts):
            env = self.node_env(pid)
            envs = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
            remote = f"cd {shlex.quote(os.getcwd())} && {envs} " + \
                " ".join(shlex.quote(c) for c in self.user_cmd())
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host, remote])
        return cmds


class SlurmRunner(MultiNodeRunner):
    """srun launch (reference SlurmRunner :345)."""

    name = "slurm"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("srun") is not None

    def get_cmd(self) -> List[List[str]]:
        n = len(self.hosts)
        cmd = ["srun", f"--nodes={n}", "--ntasks-per-node=1",
               f"--nodelist={','.join(self.hosts)}",
               "--export=ALL," + ",".join(
                   f"{k}={v}" for k, v in self.node_env(0).items()
                   if k != "DSTPU_PROCESS_ID")]
        return [cmd + self.user_cmd()]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun launch, one rank per host (reference OpenMPIRunner :126).
    Process id comes from OMPI's rank env var at bootstrap time, so the
    exported env omits DSTPU_PROCESS_ID (comm.init_distributed reads
    OMPI_COMM_WORLD_RANK as a fallback)."""

    name = "openmpi"
    launcher = "mpirun"
    rank_env = "OMPI_COMM_WORLD_RANK"

    def backend_exists(self) -> bool:
        from shutil import which

        return which(self.launcher) is not None

    def _env_flags(self) -> List[str]:
        flags: List[str] = []
        for k, v in self.node_env(0).items():
            if k == "DSTPU_PROCESS_ID":
                continue
            flags += ["-x", f"{k}={v}"]
        flags += ["-x", f"DSTPU_RANK_ENV={self.rank_env}"]
        return flags

    def get_cmd(self) -> List[List[str]]:
        n = len(self.hosts)
        cmd = [self.launcher, "-np", str(n),
               "--host", ",".join(self.hosts), "--map-by", "ppr:1:node"]
        return [cmd + self._env_flags() + self.user_cmd()]


class MPICHRunner(OpenMPIRunner):
    """mpiexec (MPICH/hydra) launch (reference MPICHRunner :188)."""

    name = "mpich"
    launcher = "mpiexec"
    rank_env = "PMI_RANK"

    def _env_flags(self) -> List[str]:
        flags: List[str] = []
        for k, v in self.node_env(0).items():
            if k == "DSTPU_PROCESS_ID":
                continue
            flags += ["-genv", k, v]
        flags += ["-genv", "DSTPU_RANK_ENV", self.rank_env]
        return flags

    def get_cmd(self) -> List[List[str]]:
        n = len(self.hosts)
        cmd = [self.launcher, "-np", str(n), "-hosts", ",".join(self.hosts),
               "-ppn", "1"]
        return [cmd + self._env_flags() + self.user_cmd()]


class IMPIRunner(MPICHRunner):
    """Intel MPI: hydra flags, PMI rank (reference IMPIRunner :260)."""

    name = "impi"


class MVAPICHRunner(MPICHRunner):
    """MVAPICH: mpirun_rsh transport, MV2 rank var (reference :393)."""

    name = "mvapich"
    launcher = "mpirun_rsh"
    rank_env = "MV2_COMM_WORLD_RANK"

    def get_cmd(self) -> List[List[str]]:
        n = len(self.hosts)
        cmd = [self.launcher, "-np", str(n)] + list(self.hosts)
        env = [f"{k}={v}" for k, v in self.node_env(0).items()
               if k != "DSTPU_PROCESS_ID"]
        env.append(f"DSTPU_RANK_ENV={self.rank_env}")
        return [cmd + env + self.user_cmd()]


RUNNERS = {"local": LocalRunner, "pdsh": PDSHRunner, "slurm": SlurmRunner,
           "openmpi": OpenMPIRunner, "mpich": MPICHRunner,
           "impi": IMPIRunner, "mvapich": MVAPICHRunner}


# --------------------------------------------------------------------------- #
def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu",
        description="deepspeed_tpu launcher: start one process per host and "
                    "bootstrap jax.distributed")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("-i", "--include", default="")
    p.add_argument("-e", "--exclude", default="")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--launcher", default="local", choices=sorted(RUNNERS))
    p.add_argument("--num_local_procs", type=int, default=0,
                   help="spawn N coordinated processes on THIS host "
                        "(reference launch.py per-device fork; CPU "
                        "simulation / single-host multi-process)")
    p.add_argument("--coordinator_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--elastic_training", action="store_true")
    p.add_argument("--min_elastic_nodes", type=int, default=-1)
    p.add_argument("--max_elastic_nodes", type=int, default=-1)
    p.add_argument("--force_multi", action="store_true")
    p.add_argument("--autotuning", choices=["tune"], default=None,
                   help="run the autotuner instead of launching: "
                        "user_script is an autotuning job JSON; trials run "
                        "in isolated worker processes and the best config "
                        "is written to the job's 'output' path (reference "
                        "deepspeed --autotuning; the reference's 'run' mode "
                        "is the same sweep + relaunch — here relaunch with "
                        "the emitted best_config yourself)")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_commands(args) -> Tuple[MultiNodeRunner, List[List[str]]]:
    hosts = fetch_hostfile(args.hostfile)
    if hosts is None:
        hosts = {"localhost": max(1, len_local_devices())}
    hosts = parse_inclusion_exclusion(hosts, args.include, args.exclude)
    if args.num_nodes > 0:
        hosts = dict(list(hosts.items())[:args.num_nodes])
    if args.num_local_procs > 1:
        if len(hosts) > 1:
            raise ValueError(
                "--num_local_procs is a single-host mode; restrict the "
                "hostfile with --include/--num_nodes 1")
        if args.launcher != "local":
            raise ValueError(
                f"--num_local_procs forks plain local processes and cannot "
                f"honor --launcher {args.launcher}; drop one of the two")
        runner = LocalMultiRunner(args, hosts, args.num_local_procs)
        return runner, runner.get_cmd()
    if len(hosts) > 1 and args.launcher == "local":
        # ADVICE r1: silently falling back to one local process while
        # node_env still advertises len(hosts) peers makes
        # jax.distributed.initialize hang forever waiting for the others
        raise ValueError(
            f"hostfile resolves {len(hosts)} hosts but --launcher local runs "
            f"a single process; pick --launcher ssh/slurm/mpi or restrict "
            f"with --include/--num_nodes 1")
    multi = (len(hosts) > 1 or args.force_multi) and args.launcher != "local"
    runner_cls = RUNNERS[args.launcher if multi else "local"]
    runner = runner_cls(args, hosts)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{runner.name}' unavailable")
    return runner, runner.get_cmd()


def len_local_devices() -> int:
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.autotuning:
        # trials self-launch as isolated worker processes; no host fan-out
        from ..autotuning.cli import autotune_main

        return autotune_main(args.user_script, args.user_args)
    runner, cmds = build_commands(args)
    logger.info(f"launching {len(cmds)} command(s) via {runner.name}")
    procs = []
    for pid, cmd in enumerate(cmds):
        env = dict(os.environ)
        if runner.name != "slurm":
            env.update(runner.node_env(pid if runner.name != "local" else 0))
        procs.append(subprocess.Popen(cmd, env=env))
    # reap as a GROUP: one worker dying (nonzero) must kill its siblings —
    # survivors would otherwise block in jax.distributed.initialize waiting
    # for the dead rank forever (reference launch.py kills the local group
    # the same way)
    rc = 0
    live = list(procs)
    try:
        while live:
            time.sleep(0.2)
            for pr in list(live):
                ret = pr.poll()
                if ret is None:
                    continue
                live.remove(pr)
                rc = ret or rc
                if ret and live:
                    logger.error(
                        f"worker pid {pr.pid} exited rc={ret}; terminating "
                        f"{len(live)} sibling(s)")
                    for sib in live:
                        sib.terminate()
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.kill()
    return rc


if __name__ == "__main__":
    sys.exit(main())
