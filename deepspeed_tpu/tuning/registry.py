"""Tunable-knob registry — ONE catalog for offline and online autotuning.

Every knob the framework can self-optimize declares, in one place:

- ``path``: a dot-path into a config tree (attribute objects OR plain
  dicts — the offline autotuner applies to raw JSON config dicts, the
  online tuner to the live typed config);
- ``choices``: the ordered candidate values (discrete — every knob this
  repo grew is a small enum/power-of-two ladder, and discrete arms are
  what an A/B tuner can actually score);
- ``score_series``: the CLOSED-schema telemetry series that scores it
  (``telemetry/schema.py`` — the knob-coverage lint in tests/test_tuning.py
  fails on an unregistered series, so a knob can never silently score
  against a series nothing emits);
- ``mode``: objective direction over that series (``min`` for latencies,
  ``max`` for goodput/overlap fractions);
- ``boundary``: the only seam the knob may change at — ``train_step``
  (between optimizer steps), ``sched_tick`` (between scheduler ticks), or
  ``offline`` (fresh-engine trials only: knobs like ZeRO stage that
  re-layout optimizer state can't flip under a live engine);
- ``root``: which config object the path starts from (``train_config`` =
  the engine's DeepSpeedTPUConfig, ``train_dict`` = a raw JSON config
  dict, ``inference_config`` = the serving engine's InferenceConfig,
  ``sched_config`` = the serving SchedulerConfig);
- ``guards``: the invariant checks (tuning/guards.py) that must hold for
  an arm to be accepted.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Tuple

BOUNDARIES = ("train_step", "sched_tick", "offline")
ROOTS = ("train_config", "train_dict", "inference_config", "sched_config")
MODES = ("min", "max")


# --------------------------------------------------------------------------- #
# dot-path walkers (attribute trees AND dict trees)
# --------------------------------------------------------------------------- #
def config_get(root: Any, path: str, default: Any = None) -> Any:
    """Walk ``a.b.c`` through attributes or dict keys; ``default`` when any
    segment is missing."""
    node = root
    for seg in path.split("."):
        if isinstance(node, dict):
            if seg not in node:
                return default
            node = node[seg]
        elif hasattr(node, seg):
            node = getattr(node, seg)
        else:
            return default
    return node


def config_set(root: Any, path: str, value: Any) -> None:
    """Set ``a.b.c = value``, creating intermediate dicts in dict trees
    (the offline autotuner writes into sparse raw config dicts). Raises
    AttributeError when an attribute-tree segment doesn't exist — a typo'd
    knob path must fail loudly, not tune a phantom attribute."""
    segs = path.split(".")
    node = root
    for seg in segs[:-1]:
        if isinstance(node, dict):
            node = node.setdefault(seg, {})
        elif hasattr(node, seg):
            node = getattr(node, seg)
        else:
            raise AttributeError(
                f"tunable path {path!r}: {type(node).__name__} has no "
                f"attribute {seg!r}")
    leaf = segs[-1]
    if isinstance(node, dict):
        node[leaf] = value
    elif hasattr(node, leaf):
        setattr(node, leaf, value)
    else:
        raise AttributeError(
            f"tunable path {path!r}: {type(node).__name__} has no "
            f"attribute {leaf!r}")


# --------------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class Tunable:
    name: str                       # registry key AND `.dstpu_tuned.json` key
    path: str                       # dot-path under `root`
    choices: Tuple[Any, ...]        # ordered candidate values
    score_series: str               # closed-schema telemetry series
    mode: str                       # "min" | "max" objective over the series
    boundary: str                   # "train_step" | "sched_tick" | "offline"
    root: str = "train_config"
    guards: Tuple[str, ...] = ("recompile", "anomaly", "slo_burn")
    description: str = ""

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"tunable {self.name}: mode {self.mode!r} "
                             f"not in {MODES}")
        if self.boundary not in BOUNDARIES:
            raise ValueError(f"tunable {self.name}: boundary "
                             f"{self.boundary!r} not in {BOUNDARIES}")
        if self.root not in ROOTS:
            raise ValueError(f"tunable {self.name}: root {self.root!r} "
                             f"not in {ROOTS}")
        if not self.choices:
            raise ValueError(f"tunable {self.name}: empty choices")

    # -- apply/read against a live root object -------------------------- #
    def get(self, root_obj: Any) -> Any:
        return config_get(root_obj, self.path)

    def apply(self, root_obj: Any, value: Any) -> None:
        if value not in self.choices:
            raise ValueError(f"tunable {self.name}: value {value!r} not in "
                             f"choices {self.choices}")
        config_set(root_obj, self.path, value)


class TunableRegistry:
    """Name-keyed knob catalog. The default registry (``default_registry``)
    carries the framework's built-in knobs; tests and embedders can build
    private registries with synthetic knobs."""

    def __init__(self, tunables: Iterable[Tunable] = ()):
        self._by_name: Dict[str, Tunable] = {}
        for t in tunables:
            self.register(t)

    def register(self, t: Tunable) -> Tunable:
        if t.name in self._by_name:
            raise ValueError(f"duplicate tunable {t.name!r}")
        self._by_name[t.name] = t
        return t

    def __len__(self) -> int:
        return len(self._by_name)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def get(self, name: str) -> Tunable:
        return self._by_name[name]

    def names(self) -> List[str]:
        return sorted(self._by_name)

    def all(self) -> List[Tunable]:
        return [self._by_name[n] for n in self.names()]

    def for_boundary(self, boundary: str,
                     names: Optional[Iterable[str]] = None) -> List[Tunable]:
        """Knobs steppable at ``boundary``, optionally restricted to an
        explicit name list (the ``tuning.knobs`` config filter). Unknown
        names in the filter raise — a typo'd knob list must not silently
        tune nothing."""
        if names:
            missing = [n for n in names if n not in self._by_name]
            if missing:
                raise KeyError(f"unknown tunable(s) {missing}; registered: "
                               f"{self.names()}")
            pool = [self._by_name[n] for n in names]
        else:
            pool = self.all()
        return [t for t in pool if t.boundary == boundary]

    def choices(self, name: str) -> Tuple[Any, ...]:
        return self._by_name[name].choices


# --------------------------------------------------------------------------- #
# the built-in knob catalog
# --------------------------------------------------------------------------- #
def _default_tunables() -> List[Tunable]:
    return [
        # -- training, online (safe to flip between optimizer steps: each
        # apply invalidates the cached train step, costing one planned
        # recompile the guard allowance covers) --
        Tunable("train.prefetch_depth", "comms_overlap.prefetch_depth",
                (1, 2, 4), "Train/Step/step_ms", "min", "train_step",
                description="ZeRO-3 layer-prefetch double/triple buffering "
                            "(comm/overlap.py prefetch_scan)"),
        Tunable("train.bucket_size_mb", "comms_overlap.bucket_size_mb",
                (8.0, 25.0, 50.0, 100.0), "Train/Step/step_ms", "min",
                "train_step",
                description="gradient reduce-scatter coalescing bucket "
                            "(reference reduce_bucket_size analog)"),
        Tunable("train.remat_policy", "activation_checkpointing.policy",
                ("none", "dots_saveable", "full"), "Train/Step/step_ms",
                "min", "train_step",
                description="jax.checkpoint policy — recompute/memory "
                            "trade (runtime/activation_checkpointing)"),
        # -- training, offline (fresh-engine trials only: these re-layout
        # optimizer/param sharding — the seed autotuner's space, now
        # sourced from this catalog instead of its own tuples) --
        Tunable("train.micro_batch", "train_micro_batch_size_per_gpu",
                (1, 2, 4, 8, 16), "Train/Step/step_ms", "min", "offline",
                root="train_dict",
                description="per-chip micro batch (autotuning/autotuner.py "
                            "build_space)"),
        Tunable("train.zero_stage", "zero_optimization.stage",
                (0, 1, 2, 3), "Train/Step/step_ms", "min", "offline",
                root="train_dict",
                description="ZeRO sharding stage (offline: optimizer-state "
                            "layout changes under a live engine are not a "
                            "safe boundary)"),
        # -- serving, online (flipped between scheduler ticks; scored on
        # windowed goodput-under-SLO) --
        Tunable("serving.split_prefill_chunk", "split_prefill_chunk",
                (0, 256, 512, 1024), "Serving/sched/goodput_frac", "max",
                "sched_tick", root="inference_config",
                description="SplitFuse/chunked-prefill chunk tokens "
                            "(0 = whole-prompt prefill)"),
        Tunable("serving.spec_draft_tokens", "speculative.max_draft_tokens",
                (2, 4, 8), "Serving/sched/goodput_frac", "max", "sched_tick",
                root="inference_config",
                description="speculative-decode draft length per verify "
                            "step (engine_v2 _spec_k)"),
        Tunable("serving.sched_lookahead", "admission_lookahead",
                (2, 4, 8, 16), "Serving/sched/goodput_frac", "max",
                "sched_tick", root="sched_config",
                description="admission queue entries scanned past a "
                            "blocked head (serving/scheduler.py)"),
    ]


_DEFAULT: Optional[TunableRegistry] = None


def default_registry() -> TunableRegistry:
    """The process-wide built-in catalog (lazily built, shared — the
    offline autotuner and every online tuner see the same knobs)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = TunableRegistry(_default_tunables())
    return _DEFAULT
