"""Trial-arm guards — the invariants an online tuning step must not break.

A guard is a snapshot-delta check around one trial arm's dwell window:
``GuardBoard.arm()`` snapshots each source's counter before the arm is
applied, ``verdict()`` re-reads it when the arm's window closes, and any
delta past the allowance VETOES the arm (immediate revert, no score
comparison — a faster arm that recompile-storms or burns SLO budget is not
a winner). Sources are resolved best-effort: a missing source (no compile
monitor on this engine, no fleet accountant on this scheduler) passes — the
guard contract is "never break a measured invariant", not "require every
subsystem to be on".

Built-in guard names (the registry's ``Tunable.guards`` entries):

- ``recompile`` — CompileMonitor total recompile count (telemetry/
  compile.py). Allowance: ``recompile_allowance`` planned recompiles per
  arm (the apply itself legitimately rebuilds the train step); more means
  the arm is shape/dtype-churning the jit cache.
- ``anomaly``  — hub ``anomaly_counts`` spike findings (telemetry/
  anomaly.py). Allowance 0: a knob arm that trips the spike detector is
  rejected outright.
- ``slo_burn`` — TenantSLOAccountant burn-rate alert count (telemetry/
  fleet.py). Allowance 0: an arm that fires a burn alert never lands.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

GUARD_NAMES = ("recompile", "anomaly", "slo_burn")


def _recompiles(hub: Any) -> float:
    mon = getattr(hub, "compile", None)
    if mon is None or not getattr(mon, "enabled", False):
        return 0.0
    stats = getattr(mon, "stats", {}) or {}
    return float(sum(getattr(st, "recompiles", 0) for st in stats.values()))


def _anomaly_spikes(hub: Any) -> float:
    counts = getattr(hub, "anomaly_counts", None) or {}
    return float(sum(v for k, v in counts.items() if k.endswith("/spike")))


def _burn_alerts(obs: Any) -> float:
    acct = getattr(obs, "accountant", None)
    if acct is None:
        return 0.0
    return float(len(getattr(acct, "alerts", ()) or ()))


class GuardBoard:
    """Snapshot-delta guard evaluation for one tuner. ``hub`` is a
    TelemetryHub (or None), ``obs`` a FleetObservability (or None); both
    are read with getattr so partially-wired targets degrade to
    pass-through."""

    def __init__(self, hub: Any = None, obs: Any = None,
                 recompile_allowance: int = 2):
        self.hub = hub
        self.obs = obs
        self.recompile_allowance = max(0, int(recompile_allowance))
        self._sources: Dict[str, Tuple[Callable[[], float], float]] = {
            "recompile": (lambda: _recompiles(self.hub),
                          float(self.recompile_allowance)),
            "anomaly": (lambda: _anomaly_spikes(self.hub), 0.0),
            "slo_burn": (lambda: _burn_alerts(self.obs), 0.0),
        }
        self._armed: Dict[str, float] = {}

    def arm(self, guards: Tuple[str, ...]) -> None:
        """Snapshot every named source before a trial arm is applied."""
        self._armed = {}
        for name in guards:
            src = self._sources.get(name)
            if src is None:
                raise KeyError(f"unknown guard {name!r}; known: "
                               f"{sorted(self._sources)}")
            self._armed[name] = src[0]()

    def verdict(self) -> Optional[str]:
        """None = all invariants held; otherwise a human-readable veto
        reason naming the guard and the counter delta."""
        for name, before in self._armed.items():
            fn, allowance = self._sources[name]
            delta = fn() - before
            if delta > allowance:
                return (f"guard {name}: +{delta:g} past allowance "
                        f"{allowance:g}")
        return None

    def breakdown(self) -> List[Tuple[str, float]]:
        """Current (source, value) rows — for reports/tests."""
        return [(n, fn()) for n, (fn, _) in sorted(self._sources.items())]
