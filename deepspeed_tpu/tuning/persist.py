"""`.dstpu_tuned.json` — the one autotune persistence file, centralized.

Before this module every producer/consumer hand-rolled the same three
fragments: a "two dirs above the package" path join, a swallow-everything
read, and (in ``scripts/attn_sweep.py``) a tmp+``os.replace`` write. They
now all route through here so the path resolves ONE way, reads tolerate a
torn/partial file (a SIGKILL mid-write must never wedge every later
process), and writes are atomic read-modify-write under a same-directory
temp file.

File shape: one flat JSON object of ``key -> scalar`` winners —
``flash_block`` / ``flash_block_g<g>`` from the attention sweep, plus
``<knob name>`` entries from the online tuner (tuning/tuner.py). Flat on
purpose: any tool can read it, and a partial understanding of the keys
never corrupts the rest on rewrite (unknown keys are preserved).

Resolution order for the path: ``$DSTPU_TUNED_PATH`` (tests, multi-repo
checkouts) > ``<repo root>/.dstpu_tuned.json`` (two dirs above this
package — the location the flash-attention lookup has always used, kept
bit-identical).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional

_ENV = "DSTPU_TUNED_PATH"


def tuned_path(path: Optional[str] = None) -> str:
    """Absolute path of the tuned-knob file (no filesystem access)."""
    if path:
        return os.path.abspath(path)
    env = os.environ.get(_ENV)
    if env:
        return os.path.abspath(env)
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".dstpu_tuned.json")


def load_tuned(path: Optional[str] = None) -> Dict[str, Any]:
    """Read the tuned dict; ``{}`` for missing, torn, or non-object files.
    Never raises — a corrupt artifact means "no tuning data", not a crashed
    training job."""
    try:
        with open(tuned_path(path)) as f:
            data = json.load(f)
        return dict(data) if isinstance(data, dict) else {}
    except Exception:
        return {}


def write_tuned(tuned: Dict[str, Any], path: Optional[str] = None) -> str:
    """Atomically replace the whole file (tmp in the SAME directory +
    ``os.replace`` — a crash mid-write leaves either the old file or the
    new one, never a partial). Returns the path written."""
    dst = tuned_path(path)
    d = os.path.dirname(dst) or "."
    fd, tmp = tempfile.mkstemp(prefix=".dstpu_tuned.", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(tuned, f, indent=0, sort_keys=True)
        os.replace(tmp, dst)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return dst


def update_tuned(entries: Dict[str, Any],
                 path: Optional[str] = None) -> Dict[str, Any]:
    """Atomic read-modify-write: merge ``entries`` over the current file
    contents (unknown keys preserved — the attention sweep's winners and
    the online tuner's never clobber each other). Returns the merged
    dict."""
    tuned = load_tuned(path)
    tuned.update(entries)
    write_tuned(tuned, path)
    return tuned
