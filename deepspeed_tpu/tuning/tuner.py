"""Online A/B-step tuner — telemetry-scored knob search at safe boundaries.

One :class:`OnlineTuner` owns one boundary seam (the training engine's
optimizer-step seam or a serving scheduler's tick seam) and steps ONE knob
at a time through its candidate arms:

1. **baseline** — dwell ``steps_per_arm`` boundary events on the incumbent
   value, recording the knob's ``score_series`` into the tuner's tsdb
   (telemetry/tsdb.py — the PR 16 bounded RRD store, clock-injectable for
   tests); the window's mean and MAD become the noise yardstick;
2. **trial arms** — apply each non-incumbent choice (epsilon-greedy order:
   seeded shuffle) at the boundary, dwell, score via ``tsdb.score()`` over
   the arm's own window with a ``min_samples`` gate, and evaluate the
   guard board (tuning/guards.py) — a recompile storm, anomaly spike, or
   SLO burn alert VETOES the arm regardless of its score;
3. **decision** — the best-scoring arm must beat the baseline by
   ``max(accept_mads * MAD, min_rel_delta * |baseline|)`` (never chase
   jitter); a winner is applied and persisted atomically to
   `.dstpu_tuned.json` (tuning/persist.py), anything else reverts to the
   incumbent. The knob then closes until a drift signal (anomaly drift
   finding, burn-rate alert) re-opens it.

A fresh process reloads persisted winners at construction and starts with
those knobs closed — no re-search until drift says the workload moved.

The tuner never blocks the step/tick path: every hook is O(open knobs)
bookkeeping plus one tsdb record.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..telemetry.tsdb import TimeSeriesStore, TsdbConfig
from ..utils.logging import log_dist
from .guards import GuardBoard
from .persist import load_tuned, update_tuned
from .registry import Tunable, TunableRegistry, default_registry

# per-knob state machine phases
_BASELINE, _TRIAL, _CLOSED = "baseline", "trial", "closed"


@dataclasses.dataclass
class TunerOptions:
    """Knob-search options, shared by the training ``tuning`` config block
    and the serving ``serving.tuning`` router block."""
    enabled: bool = False
    knobs: Tuple[str, ...] = ()     # () = every knob at this boundary
    steps_per_arm: int = 16         # boundary events per measured window
    window_s: float = 600.0         # max trailing window the score may use
    min_samples: int = 8            # samples required before a verdict
    max_dwell_factor: int = 4       # give up a window after this x dwell
    accept_mads: float = 3.0        # improvement > this many baseline MADs
    min_rel_delta: float = 0.02     # ... AND this fraction of baseline
    recompile_allowance: int = 2    # planned recompiles per arm (guards)
    seed: int = 0                   # arm-order shuffle seed
    persist: bool = True            # write winners to .dstpu_tuned.json
    reload: bool = True             # reload persisted winners (no re-search)
    path: str = ""                  # "" = the default persist resolver

    @classmethod
    def from_any(cls, obj: Any) -> "TunerOptions":
        """Build from anything carrying the same field names (the runtime
        ``TuningConfig`` ConfigModel, a dict, or another TunerOptions)."""
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            src = dict(obj)
            get = src.pop
            opts = cls()
            for f in dataclasses.fields(cls):
                if f.name in src:
                    setattr(opts, f.name, get(f.name))
            if src:
                raise ValueError(f"unknown tuning option(s): {sorted(src)}")
            opts.knobs = tuple(opts.knobs or ())
            return opts
        opts = cls()
        for f in dataclasses.fields(cls):
            if hasattr(obj, f.name):
                setattr(opts, f.name, getattr(obj, f.name))
        opts.knobs = tuple(opts.knobs or ())
        return opts

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TunerOptions":
        return cls.from_any(dict(d or {}))


class _KnobState:
    def __init__(self, t: Tunable, incumbent: Any):
        self.t = t
        self.incumbent = incumbent      # value currently trusted/applied
        self.phase = _BASELINE
        self.dwell = 0                  # boundary events in current window
        self.window_start = 0.0
        self.baseline_mean = 0.0
        self.baseline_mad = 0.0
        self.pending: List[Any] = []    # arms not yet tried this search
        self.arm: Optional[Any] = None  # arm currently applied (trial phase)
        self.results: Dict[int, float] = {}   # choice index -> window mean
        self.counts = {"trials": 0, "accepts": 0, "reverts": 0,
                       "vetoes": 0, "retunes": 0}

    def idx(self, value: Any) -> int:
        return self.t.choices.index(value)


class OnlineTuner:
    """See module docstring. Construct via :meth:`for_engine` /
    :meth:`for_scheduler`, or directly (tests, bench) with a private
    registry and an injected clock."""

    def __init__(self, registry: TunableRegistry, options: Any, *,
                 boundary: str, roots: Dict[str, Any],
                 invalidate: Optional[Callable[[], None]] = None,
                 post_apply: Optional[Dict[str, Callable[[Any], None]]] = None,
                 hub: Any = None, obs: Any = None, tracer: Any = None,
                 tsdb: Optional[TimeSeriesStore] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.opts = TunerOptions.from_any(options)
        self.registry = registry
        self.boundary = boundary
        self.roots = dict(roots)
        self._invalidate = invalidate
        self._post_apply = dict(post_apply or {})
        self.hub = hub
        self.tracer = tracer
        self.clock = clock
        self.tsdb = tsdb if tsdb is not None else \
            TimeSeriesStore(TsdbConfig(), clock=clock)
        self.guards = GuardBoard(
            hub=hub, obs=obs,
            recompile_allowance=self.opts.recompile_allowance)
        self._rng = random.Random(self.opts.seed)
        self.totals = {"trials": 0, "accepts": 0, "reverts": 0,
                       "vetoes": 0, "retunes": 0}
        # set by _apply when the apply invalidated a compiled step: the
        # next boundary's sample IS the recompile and must not score the arm
        self._discard_next = False
        self.tune_values: Dict[str, float] = {}
        self.active: Optional[str] = None
        self._drift_marks: Dict[str, float] = {}
        self.states: Dict[str, _KnobState] = {}
        for t in registry.for_boundary(boundary, self.opts.knobs):
            root = self.roots.get(t.root)
            if root is None:
                continue            # knob's root object not wired here
            self.states[t.name] = _KnobState(t, t.get(root))
        # fresh-process reload: a persisted winner closes its knob — the
        # search already happened; only a drift signal re-opens it
        if self.opts.reload:
            tuned = load_tuned(self.opts.path or None)
            for name, st in self.states.items():
                if name not in tuned:
                    continue
                match = [c for c in st.t.choices if c == tuned[name]]
                if not match:
                    continue        # stale/foreign value — ignore, re-search
                self._apply(st, match[0])
                st.incumbent = match[0]
                st.phase = _CLOSED
                log_dist(f"tuning: reloaded {name}={match[0]!r} from "
                         f"persisted winners (search skipped)")

    # ------------------------------------------------------------------ #
    # construction seams
    # ------------------------------------------------------------------ #
    @classmethod
    def for_engine(cls, engine, cfg) -> "OnlineTuner":
        """Training-side tuner: optimizer-step boundary, knobs rooted at
        the engine's typed config; each apply invalidates the cached
        compiled step so the next ``train_batch`` rebuilds under the new
        knob (ONE planned recompile, covered by the guard allowance)."""
        def invalidate():
            for attr in ("_train_step", "_grad_step"):
                if getattr(engine, attr, None) is not None:
                    setattr(engine, attr, None)

        hub = engine.telemetry
        return cls(default_registry(), cfg, boundary="train_step",
                   roots={"train_config": engine.config},
                   invalidate=invalidate, hub=hub, tracer=hub.tracer)

    @classmethod
    def for_scheduler(cls, sched, options,
                      registry: Optional[TunableRegistry] = None,
                      clock: Optional[Callable[[], float]] = None
                      ) -> "OnlineTuner":
        """Serving-side tuner: sched-tick boundary, knobs rooted at the
        serving engine's InferenceConfig and the scheduler's own config;
        SLO-burn guard wired to the fleet accountant when the obs plane is
        attached."""
        eng = sched.engine

        def sync_spec(v):
            if getattr(eng, "_spec_k", None) is not None:
                eng._spec_k = max(1, int(v))

        tuner = cls(registry or default_registry(), options,
                    boundary="sched_tick",
                    roots={"inference_config": eng.config,
                           "sched_config": sched.cfg},
                    post_apply={"serving.spec_draft_tokens": sync_spec},
                    hub=getattr(eng, "_hub", None), obs=sched.obs,
                    tracer=sched.tracer,
                    clock=clock or sched.cfg.clock)
        tuner._last_done = 0
        tuner._last_met = 0
        return tuner

    # ------------------------------------------------------------------ #
    # boundary hooks
    # ------------------------------------------------------------------ #
    def on_train_step(self, step: int,
                      step_time_s: Optional[float] = None) -> None:
        """Optimizer-step seam (engine.train_batch, after step_end)."""
        if step_time_s:
            if self._discard_next:
                self._discard_next = False
            else:
                self.observe("Train/Step/step_ms", float(step_time_s) * 1e3)
        self._drift_from_counters(
            getattr(self.hub, "anomaly_counts", None) or {},
            lambda k: k.endswith("/drift"), "anomaly drift")
        self.advance(step)

    def on_sched_tick(self, sched) -> None:
        """Scheduler-tick seam (serving/scheduler.py tick tail): records
        WINDOWED goodput (SLO-met fraction of the completions since the
        last tick) so an arm is scored on requests it actually served."""
        done = sched.stats["completed"]
        met = sched.stats["slo_met"]
        dd, dm = done - self._last_done, met - self._last_met
        self._last_done, self._last_met = done, met
        if dd > 0:
            self.observe("Serving/sched/goodput_frac", dm / dd)
        obs = getattr(sched, "obs", None)
        acct = getattr(obs, "accountant", None) if obs is not None else None
        if acct is not None:
            self._drift_from_counters(
                {"burn": len(getattr(acct, "alerts", ()) or ())},
                lambda k: True, "slo burn alert")
        self.advance(int(sched.stats["ticks"]))

    def observe(self, series: str, value: float) -> None:
        """Record one sample of a score series into the tuner's tsdb."""
        self.tsdb.record(series, float(value))

    # ------------------------------------------------------------------ #
    # drift-triggered retune
    # ------------------------------------------------------------------ #
    def _drift_from_counters(self, counts: Dict[str, Any],
                             match: Callable[[str], bool],
                             why: str) -> None:
        fired = False
        for key, v in counts.items():
            if not match(key):
                continue
            v = float(v)
            if v > self._drift_marks.get(key, 0.0):
                fired = True
            self._drift_marks[key] = v
        if fired:
            self.reopen_all(why)

    def reopen_all(self, why: str) -> None:
        """Drift signal: re-open every CLOSED knob at this boundary (the
        workload moved — persisted winners are no longer presumed valid)."""
        for name in self.states:
            self.reopen(name, why)

    def reopen(self, name: str, why: str = "drift") -> None:
        st = self.states.get(name)
        if st is None or st.phase != _CLOSED:
            return
        st.phase = _BASELINE
        st.dwell = 0
        st.window_start = self.clock()
        st.pending = []
        st.arm = None
        st.results = {}
        st.counts["retunes"] += 1
        self.totals["retunes"] += 1
        self._emit_knob(st)
        self._emit_totals()
        log_dist(f"tuning: re-opened {name} ({why})")

    # ------------------------------------------------------------------ #
    # the state machine
    # ------------------------------------------------------------------ #
    def advance(self, step: int = 0) -> None:
        """One boundary event. Picks/continues the single active knob."""
        self._step = int(step)
        if self.active is None or \
                self.states[self.active].phase == _CLOSED:
            self.active = next(
                (n for n in sorted(self.states)
                 if self.states[n].phase != _CLOSED), None)
            if self.active is not None:
                st = self.states[self.active]
                st.dwell = 0
                st.window_start = self.clock()
        if self.active is None:
            return
        st = self.states[self.active]
        st.dwell += 1
        if st.dwell < self.opts.steps_per_arm:
            return
        if st.phase == _BASELINE:
            self._finish_baseline(st)
        elif st.phase == _TRIAL:
            self._finish_arm(st)

    def _window_stats(self, st: _KnobState) -> Tuple[int, float, float]:
        """(count, mean, MAD-of-bucket-means) over the current window.

        The window is widened by one tsdb bucket: ``query`` keeps a bucket
        only when its START is inside the window, so a window opened
        mid-bucket would otherwise hide its own samples (fast boundaries —
        sub-second optimizer steps — land entirely inside one bucket). The
        cost is up to one bucket of pre-window samples folding in, bounded
        by the tsdb resolution."""
        now = self.clock()
        res = getattr(self.tsdb.cfg, "resolution_s", 1.0)
        last_s = min(self.opts.window_s + res,
                     max(res, now - st.window_start + res))
        rows = self.tsdb.query(st.t.score_series, last_s=last_s, now=now)
        if not rows:
            return 0, 0.0, 0.0
        count = int(sum(r["count"] for r in rows))
        total = sum(r["mean"] * r["count"] for r in rows)
        mean = total / max(1, count)
        means = sorted(r["mean"] for r in rows)
        med = means[len(means) // 2]
        dev = sorted(abs(x - med) for x in means)
        mad = dev[len(dev) // 2]
        return count, mean, mad

    def _max_dwell(self) -> int:
        return self.opts.steps_per_arm * max(1, self.opts.max_dwell_factor)

    def _finish_baseline(self, st: _KnobState) -> None:
        count, mean, mad = self._window_stats(st)
        if count < self.opts.min_samples:
            if st.dwell < self._max_dwell():
                return              # keep dwelling for signal
            st.phase = _CLOSED      # series is silent here — nothing to tune
            self._emit_knob(st)
            return
        st.baseline_mean, st.baseline_mad = mean, mad
        st.results = {st.idx(st.incumbent): mean}
        st.pending = [c for c in st.t.choices if c != st.incumbent]
        self._rng.shuffle(st.pending)
        st.phase = _TRIAL
        self._start_arm(st)

    def _start_arm(self, st: _KnobState) -> None:
        st.arm = st.pending.pop(0)
        st.counts["trials"] += 1
        self.totals["trials"] += 1
        self.guards.arm(st.t.guards)
        self._apply(st, st.arm)
        st.dwell = 0
        st.window_start = self.clock()
        if self.tracer is not None:
            self.tracer.instant("tune_step", cat="tuning", knob=st.t.name,
                                arm=repr(st.arm), step=self._step)
        self._emit_knob(st)
        self._emit_totals()

    def _finish_arm(self, st: _KnobState) -> None:
        veto = self.guards.verdict()
        count, mean, _ = self._window_stats(st)
        if veto is None and count < self.opts.min_samples and \
                st.dwell < self._max_dwell():
            return                  # window not yet scoreable — keep dwelling
        if veto is not None:
            st.counts["vetoes"] += 1
            self.totals["vetoes"] += 1
            log_dist(f"tuning: veto {st.t.name}={st.arm!r} ({veto})")
            self._revert(st)
            st.arm = None           # applied state is the incumbent again
        elif count >= self.opts.min_samples:
            st.results[st.idx(st.arm)] = mean
        # else: starved window — the arm goes unscored (treated as a loss)
        if st.pending:
            # next arm applies directly arm->arm (one recompile, not two);
            # a vetoed arm already reverted to the incumbent above
            self._start_arm(st)
            return
        self._decide(st)

    def _decide(self, st: _KnobState) -> None:
        base_i = st.idx(st.incumbent)
        base = st.results.get(base_i, st.baseline_mean)
        margin = max(self.opts.accept_mads * st.baseline_mad,
                     self.opts.min_rel_delta * abs(base))
        sign = 1.0 if st.t.mode == "min" else -1.0
        best_i, best = base_i, base
        for i, v in st.results.items():
            if sign * v < sign * best:
                best_i, best = i, v
        improved = sign * (base - best) > margin
        if improved and best_i != base_i:
            winner = st.t.choices[best_i]
            if st.arm != winner:
                self._apply(st, winner)
            st.incumbent = winner
            st.counts["accepts"] += 1
            self.totals["accepts"] += 1
            if self.opts.persist:
                update_tuned({st.t.name: winner},
                             path=self.opts.path or None)
            log_dist(f"tuning: accepted {st.t.name}={winner!r} "
                     f"(score {best:.4g} vs baseline {base:.4g}, "
                     f"margin {margin:.4g})")
        else:
            # no arm cleared the noise gate — revert to the incumbent
            if st.arm is not None and st.arm != st.incumbent:
                self._revert(st)
        st.arm = None
        st.phase = _CLOSED
        self.tune_values[f"Tune/knob/{st.t.name}/score_baseline"] = base
        self.tune_values[f"Tune/knob/{st.t.name}/score_best"] = best
        self.tune_values[f"Tune/knob/{st.t.name}/score_delta"] = \
            sign * (base - best)
        self._emit_knob(st)
        self._emit_totals()

    # ------------------------------------------------------------------ #
    def _apply(self, st: _KnobState, value: Any) -> None:
        st.t.apply(self.roots[st.t.root], value)
        hook = self._post_apply.get(st.t.name)
        if hook is not None:
            hook(value)
        if self._invalidate is not None:
            self._invalidate()
            self._discard_next = True

    def _revert(self, st: _KnobState) -> None:
        self._apply(st, st.incumbent)
        st.counts["reverts"] += 1
        self.totals["reverts"] += 1
        if self.tracer is not None:
            self.tracer.instant("tune_revert", cat="tuning", knob=st.t.name,
                                arm=repr(st.arm), step=self._step)

    # ------------------------------------------------------------------ #
    # observability surface
    # ------------------------------------------------------------------ #
    _step = 0

    def _emit_totals(self) -> None:
        open_n = sum(1 for s in self.states.values()
                     if s.phase != _CLOSED)
        vals = dict(self.totals)
        vals["open_knobs"] = open_n
        vals["closed_knobs"] = len(self.states) - open_n
        for k, v in vals.items():
            self._emit(f"Tune/total/{k}", float(v))

    def _emit_knob(self, st: _KnobState) -> None:
        base = f"Tune/knob/{st.t.name}"
        for k, v in st.counts.items():
            self._emit(f"{base}/{k}", float(v))
        # `value` is the INDEX into choices — values may be non-numeric
        # (remat policy names) and events must be finite floats
        applied = st.arm if st.arm is not None else st.incumbent
        try:
            self._emit(f"{base}/value", float(st.idx(applied)))
        except ValueError:
            pass
        self._emit(f"{base}/active", 1.0 if st.phase != _CLOSED else 0.0)

    def _emit(self, name: str, value: float) -> None:
        self.tune_values[name] = float(value)
        if self.hub is not None and hasattr(self.hub, "tune_event"):
            self.hub.tune_event(name, value, self._step)

    def events(self, step: int = 0) -> List[Tuple[str, float, int]]:
        """Current ``Tune/*`` gauge snapshot as schema triples (reports,
        tests)."""
        self._step = int(step)
        self._emit_totals()
        for st in self.states.values():
            self._emit_knob(st)
        return [(n, float(v), int(step))
                for n, v in sorted(self.tune_values.items())]

    def summary(self) -> Dict[str, Any]:
        """Human-oriented rollup (bench probe, telemetry_report)."""
        knobs = {}
        for name, st in self.states.items():
            applied = st.arm if st.arm is not None else st.incumbent
            knobs[name] = {
                "phase": st.phase, "value": applied,
                "incumbent": st.incumbent,
                "baseline": st.baseline_mean,
                "counts": dict(st.counts),
                "results": {repr(st.t.choices[i]): v
                            for i, v in st.results.items()},
            }
        return {"totals": dict(self.totals), "active": self.active,
                "knobs": knobs}
