"""Telemetry-actuated self-tuning runtime (docs/tuning.md).

Closes the loop from the telemetry plane to the config knobs it measures:

- :mod:`persist` — the one `.dstpu_tuned.json` resolver/reader/writer
  (atomic tmp+rename, torn-file-tolerant) every autotune producer and
  consumer shares (flash-attention block lookup, ``scripts/attn_sweep.py``,
  the online tuner);
- :mod:`registry` — the tunable-knob catalog: each knob declares its config
  path, candidate values, the closed-schema telemetry series that scores
  it, the objective direction, the safe boundary it may step at, and the
  guards that veto an arm;
- :mod:`guards` — invariant checks sampled around each trial arm
  (recompile-budget blowout, anomaly spikes, SLO burn alerts);
- :mod:`tuner` — the online A/B-step tuner: epsilon-greedy over one knob at
  a time at optimizer-step / sched-tick seams, scored via ``tsdb.score()``
  with min-samples + MAD-noise gating, reverting losers and persisting
  winners.

Default OFF everywhere: with no ``tuning`` block the training engine and
serving scheduler never construct a tuner and their programs/streams are
byte-identical to pre-tuning behavior (pinned by tests/test_tuning.py).
"""

from .persist import tuned_path, load_tuned, update_tuned, write_tuned
from .registry import (Tunable, TunableRegistry, config_get, config_set,
                       default_registry)
from .guards import GuardBoard
from .tuner import OnlineTuner, TunerOptions

__all__ = ["tuned_path", "load_tuned", "update_tuned", "write_tuned",
           "Tunable", "TunableRegistry", "config_get", "config_set",
           "default_registry", "GuardBoard", "OnlineTuner", "TunerOptions"]
