"""Flops profiler.

Reference parity: ``deepspeed/profiling/flops_profiler/profiler.py:30
FlopsProfiler`` + standalone ``get_model_profile()``. The reference counts
MACs by monkey-patching ``torch.nn.functional``; on TPU the compiler already
knows — two native sources replace the patching:

- **XLA cost analysis** (``compiled.cost_analysis()``): exact post-fusion
  flops/bytes for the whole compiled step — what the hardware will run.
- **jaxpr walk**: pre-compilation per-primitive tally (dot_general/conv einsum
  math, elementwise sizes) — the per-module breakdown analog, keyed by
  primitive and source line instead of nn.Module names.

Latency comes from timed execution, so the profiler reports achieved FLOPS
and MFU directly (ThroughputTimer parity).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist, logger


def _num(x) -> float:
    try:
        return float(np.prod(x))
    except Exception:
        return 0.0


def _dot_flops(eqn) -> float:
    """2 × M × N × K for dot_general, from the eqn's avals."""
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    m = _num([d for i, d in enumerate(a.shape) if i not in lc and i not in lb])
    k = _num([a.shape[i] for i in lc])
    n = _num([d for i, d in enumerate(b.shape) if i not in rc and i not in rb])
    batch = _num([a.shape[i] for i in lb])
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # 2 × output_elements × (kernel_spatial × in_channels)
    return 2.0 * _num(out.shape) * _num(rhs.shape[:-1])


def profile_jaxpr(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Per-primitive flop tally from the traced jaxpr (the reference's
    per-module breakdown, at primitive granularity)."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    tally: Dict[str, float] = defaultdict(float)

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "dot_general":
                tally["dot_general"] += _dot_flops(eqn)
            elif name.startswith("conv"):
                tally["conv"] += _conv_flops(eqn)
            elif name in ("add", "mul", "sub", "div", "max", "min", "exp",
                          "log", "tanh", "logistic", "rsqrt", "sqrt"):
                tally["elementwise"] += _num(eqn.outvars[0].aval.shape)
            # recurse into nested jaxprs (scan/cond/remat bodies)
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    mult = eqn.params.get("length", 1) if eqn.primitive.name == "scan" else 1
                    before = dict(tally)
                    walk(inner)
                    if mult != 1:
                        for k in tally:
                            tally[k] = before.get(k, 0.0) + \
                                (tally[k] - before.get(k, 0.0)) * mult

    walk(closed.jaxpr)
    tally["total"] = sum(v for k, v in tally.items() if k != "total")
    return dict(tally)


def _count_params(tree) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree.leaves(tree)
                   if hasattr(x, "shape")))


def get_model_profile(fn: Callable, args: Tuple = (), kwargs: Optional[Dict] = None,
                      warmup: int = 1, iters: int = 3,
                      as_string: bool = False) -> Dict[str, Any]:
    """Standalone API (reference ``get_model_profile``): compile ``fn``,
    read XLA's cost analysis, time execution → flops / latency / FLOPS."""
    kwargs = kwargs or {}
    jitted = jax.jit(lambda *a: fn(*a, **kwargs))
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))

    for _ in range(warmup):
        jax.block_until_ready(jitted(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jitted(*args)
    jax.block_until_ready(out)
    latency = (time.perf_counter() - t0) / iters

    prof = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "latency_s": latency,
        "flops_per_s": flops / latency if latency > 0 else 0.0,
        "arithmetic_intensity": flops / bytes_accessed if bytes_accessed else 0.0,
    }
    if as_string:
        prof["summary"] = (f"flops={flops:.3e} latency={latency*1e3:.2f}ms "
                           f"achieved={prof['flops_per_s']/1e12:.2f} TFLOPS")
    return prof


class FlopsProfiler:
    """Engine-attached profiler (reference engine hooks
    ``runtime/engine.py:2278,2850``): arms at ``profile_step``, reads the cost
    analysis of the engine's compiled train step, reports params/flops/MFU."""

    def __init__(self, config, engine=None):
        self.cfg = config
        self.engine = engine
        self.profile: Optional[Dict[str, Any]] = None
        self._step_t0: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.cfg, "enabled", False))

    def start_profile(self) -> None:
        self._step_t0 = time.perf_counter()

    def stop_profile(self, flops: Optional[float] = None,
                     peak_flops_per_chip: float = 0.0) -> Dict[str, Any]:
        latency = time.perf_counter() - (self._step_t0 or time.perf_counter())
        prof: Dict[str, Any] = {"latency_s": latency}
        if self.engine is not None:
            prof["params"] = _count_params(self.engine.state.params)
        if flops:
            prof["flops"] = flops
            prof["flops_per_s"] = flops / latency if latency > 0 else 0.0
            if peak_flops_per_chip:
                prof["mfu"] = prof["flops_per_s"] / peak_flops_per_chip
        self.profile = prof
        return prof

    def print_profile(self) -> None:
        if self.profile:
            log_dist(f"[flops_profiler] {self.profile}")
