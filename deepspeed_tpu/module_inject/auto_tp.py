"""AutoTP: infer tensor-parallel sharding rules for ARBITRARY param trees.

Reference parity: ``module_inject/auto_tp.py:194 AutoTP`` — the reference
walks an nn.Module graph, classifies every Linear as row-parallel (needs an
all-reduce after it: ``LinearAllreduce``, ``module_inject/layers.py:581``) or
column-parallel (``LinearLayer`` :678), and splits the weights in place. Its
policy knowledge is a name list of "layers that end with an all-reduce"
(o_proj/out_proj/down_proj/dense_4h_to_h/...).

TPU-first redesign: nothing is rewritten or split at runtime. This pass maps
each leaf's *path name* to logical axis names; the shared ``Partitioner``
then lays the 'tp' axes onto the 'tensor' mesh axis and XLA inserts the
all-reduces the sharding implies. Models that publish hand-written
``param_logical_axes`` skip this entirely — AutoTP is the fallback that makes
un-annotated (imported) models TP-shardable, exactly the reference's role.

Classification per 2-D (or stacked 3-D [L, in, out]) leaf, by the LAST name
segment (our [in, out] layout — transposed from HF's [out, in]):

- row-parallel  (shard IN dim; partial sums all-reduce):
  name matches ROW_PARALLEL_PATTERNS (the reference's allreduce list).
- column-parallel (shard OUT dim): every other matmul weight.
- embeddings: ``embed``-like [V, H] shard the vocab dim; ``lm_head``/
  ``unembed`` [H, V] shard the vocab (out) dim.
- 1-D leaves (norms, biases, routers): replicated. (Biases of column-
  parallel linears could shard like their weight's out dim; they are tiny,
  so the conservative replicate keeps the pass sibling-free.)
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax

# the reference's "layers that end with an allreduce" knowledge
# (module_inject/auto_tp.py tp_parser candidates), plus this repo's own
# stacked-layer names
ROW_PARALLEL_PATTERNS = (
    r"o_proj", r"out_proj", r"down_proj", r"dense_4h_to_h", r"w_down",
    r"wo", r"w2", r"fc2", r"c_proj", r"attention\.dense", r"dense$",
    r"proj_out",
)

EMBED_PATTERNS = (r"embed", r"wte", r"word_embeddings", r"tok_embeddings")
HEAD_PATTERNS = (r"lm_head", r"unembed", r"output_proj$")
# never shard (small / positional / router tables)
REPLICATE_PATTERNS = (r"pos_embed", r"wpe", r"router", r"gate\.weight")


def _matches(name: str, patterns) -> bool:
    return any(re.search(p, name) for p in patterns)


def infer_shard_policy(path_name: str, shape: Tuple[int, ...]
                       ) -> Tuple[Optional[str], ...]:
    """Logical axes for one leaf given its dotted path and shape."""
    nd = len(shape)
    leaf = path_name.rsplit(".", 1)[-1]
    stacked = "layers" in path_name.split(".") and nd >= 2
    lead: Tuple[Optional[str], ...] = ("layers",) if stacked else ()
    core = nd - len(lead)

    if _matches(path_name, REPLICATE_PATTERNS) or core < 2:
        return lead + (None,) * core
    if _matches(leaf, HEAD_PATTERNS):
        return lead + (None,) * (core - 2) + ("embed", "vocab")
    if core == 2 and not stacked and \
            (_matches(leaf, EMBED_PATTERNS) or
             _matches(path_name, EMBED_PATTERNS)):
        return lead + ("vocab", "embed")
    if _matches(leaf, ROW_PARALLEL_PATTERNS):
        # [.., in(sharded), out] — partial sums; XLA inserts the all-reduce
        return lead + (None,) * (core - 2) + ("tp", None)
    # column-parallel default: [.., in, out(sharded)]
    return lead + (None,) * (core - 2) + (None, "tp")


def infer_logical_axes(params: Any) -> Any:
    """Pytree of logical-axis tuples for an arbitrary param tree — the
    ``AutoTP.tp_parser`` equivalent. Feed to ``Partitioner.param_specs``."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    axes = []
    for path, leaf in flat:
        name = ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        axes.append(infer_shard_policy(name, tuple(leaf.shape)))
    return jax.tree_util.tree_unflatten(treedef, axes)
