from .auto_tp import (ROW_PARALLEL_PATTERNS, infer_logical_axes,
                      infer_shard_policy)

__all__ = ["infer_logical_axes", "infer_shard_policy",
           "ROW_PARALLEL_PATTERNS"]
