"""Numerics integrity plane — silent-data-corruption (SDC) detection.

Crashes are the easy failure: PR 3/11/15 machinery already converts them
into durable saves and token-exact failover. The failure production fleets
actually lose runs to is *silent*: a flaky chip flips a mantissa bit, the
poisoned gradient all-reduces into every replica, and the run diverges hours
later with nothing in the logs. This module is the guardrail
(``reliability.integrity`` config block; docs/reliability.md "Numerics
integrity & SDC"):

**Cross-replica fingerprints.** The jitted train step — when the block is
enabled, and only then — additionally computes cheap per-leaf digests of
quantities that are replica-invariant by construction: post-all-reduce
grads, post-step replicated params, optimizer moments, the loss scalar.
A digest is three scalars per leaf: a bitcast-to-int32 wraparound sum
(order-independent, exact — any single bit flip changes it), an fp32 L2
norm (magnitude of the damage), and a nonfinite-element count (feeds the
watchdog's per-leaf attribution). The step program emits one logical digest
vector; every host fetches its own copy, so a host whose chips corrupt data
fetches a DIFFERENT vector than its peers. Every ``check_interval`` steps
the hosts allgather their vectors and majority-vote: a minority row is a
mismatch *attributed to a specific host*, not just detected.

**Shadow recompute audits.** Replica-invariance cannot see corruption that
hits every replica identically (a systematic compute-path defect). Every
``audit_interval`` steps a rotating auditor host re-runs the full fwd/bwd
on the recorded batch through a separate non-donating executable BEFORE the
live step consumes its buffers, and compares digests after the live step
lands. Audit agreement advances ``last_verified_step``.

**Quarantine protocol.** ``quarantine_threshold`` repeated attributions to
one host → the PR 15 elastic-exit path: ``PreemptionGuard.step_boundary``
answers with a durable universal save plus ``reshard_hint.json`` carrying
an ``excluded_hosts`` field, and ``run_elastic`` reshards onto the
survivors. Corruption confirmed AFTER ``last_verified_step`` (an audit
mismatch) additionally requests a walk-back: the hint pins resume to the
newest checkpoint tag at or before the last verified step, so the restart
never resumes poisoned weights.

Single-process drills (``testing/drill.py sdc_drill``) inject a simulated
fleet through the ``gather_fn`` / ``process_index`` / ``process_count``
constructor hooks — the same seam ``runtime/watchdog.py HostHeartbeat``
uses — with ``testing/faults.py bit_flip`` providing real bit-level
corruption at named sites.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import log_dist, logger

__all__ = [
    "IntegrityError",
    "IntegrityPlane",
    "fingerprint_names",
    "tree_fingerprint",
]

# fingerprinted sections in wire order (the allgathered row concatenates
# them in THIS order; both ends must agree)
SECTIONS = ("grads", "params", "opt_state", "loss")


class IntegrityError(RuntimeError):
    """Raised on confirmed corruption when ``on_corruption: raise``."""


# --------------------------------------------------------------------------- #
# on-device digests (jit-traceable)
# --------------------------------------------------------------------------- #
def _leaf_digest(x):
    """One leaf → (bitsum int32, sumsq float32, nonfinite int32).

    The bitsum is a wraparound sum of the raw bit patterns — commutative
    (safe under any reduction order XLA picks for a fixed program) and
    sensitive to every single-bit flip. The L2 sum-of-squares sizes the
    damage; the nonfinite count gives the watchdog per-leaf NaN/Inf
    attribution without an extra device pass."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    flat = jnp.ravel(x)
    if jnp.issubdtype(x.dtype, jnp.floating):
        ity = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32,
               8: jnp.int64}[x.dtype.itemsize]
        bits = jax.lax.bitcast_convert_type(flat, ity).astype(jnp.int32)
        f = flat.astype(jnp.float32)
    else:  # integer / bool leaves (step counters in opt state)
        bits = flat.astype(jnp.int32)
        f = flat.astype(jnp.float32)
    bitsum = jnp.sum(bits, dtype=jnp.int32)
    sumsq = jnp.sum(f * f, dtype=jnp.float32)
    nonfinite = jnp.sum(
        jnp.logical_not(jnp.isfinite(f))).astype(jnp.int32)
    return bitsum, sumsq, nonfinite


def tree_fingerprint(tree) -> Dict[str, Any]:
    """Pytree → ``{"bitsum": [L] i32, "sumsq": [L] f32, "nonfinite": [L]
    i32}`` stacked in ``jax.tree_util`` leaf order. Traceable — called from
    inside the jitted step when ``reliability.integrity`` is enabled."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        z = jnp.zeros((0,), jnp.int32)
        return {"bitsum": z, "sumsq": jnp.zeros((0,), jnp.float32),
                "nonfinite": z}
    digs = [_leaf_digest(leaf) for leaf in leaves]
    return {
        "bitsum": jnp.stack([d[0] for d in digs]),
        "sumsq": jnp.stack([d[1] for d in digs]),
        "nonfinite": jnp.stack([d[2] for d in digs]),
    }


def fingerprint_names(tree) -> List[str]:
    """Dotted leaf paths in the same order ``tree_fingerprint`` stacks —
    the attribution half of the digest (host-side, shape math only)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _leaf in flat:
        s = jax.tree_util.keystr(path)
        s = re.sub(r"\['([^']*)'\]", r".\1", s)
        s = re.sub(r"\[([0-9]+)\]", r".\1", s)
        names.append(s.strip(".") or "leaf")
    return names


# --------------------------------------------------------------------------- #
# host-side plane
# --------------------------------------------------------------------------- #
def _default_gather(vec: np.ndarray) -> np.ndarray:
    """Allgather one digest row across processes → ``[n_hosts, D]``."""
    import jax

    if jax.process_count() == 1:
        return vec[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(vec))


def _fp_to_host(fp: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, np.ndarray]]:
    """Device digest dict → host numpy (the only device sync the plane
    does, and only on check/audit steps)."""
    return {sec: {k: np.asarray(v) for k, v in d.items()}
            for sec, d in fp.items()}


class IntegrityPlane:
    """Host-side driver: consumes the step's digest aux, runs the
    cross-host compare cadence, attributes mismatches, and escalates to
    quarantine / walk-back. Constructed by the engine when
    ``reliability.integrity.enabled``; the ``gather_fn`` /
    ``process_index`` / ``process_count`` hooks exist so drills can
    simulate an N-host fleet in one process (HostHeartbeat pattern)."""

    def __init__(self, config, telemetry=None, *,
                 gather_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import jax

        self.cfg = config.reliability.integrity
        self.telemetry = telemetry
        self._gather = gather_fn or _default_gather
        self._index = (jax.process_index() if process_index is None
                       else int(process_index))
        self._count = (jax.process_count() if process_count is None
                       else int(process_count))
        # cumulative per-host attribution counts → quarantine decision
        self.attribution_counts: Dict[int, int] = {}
        self.excluded_hosts: List[int] = []
        # elastic-exit request (PreemptionGuard.step_boundary polls these,
        # exactly like the watchdog's restart_requested)
        self.restart_requested = False
        self.restart_reason: Optional[str] = None
        # audit-confirmed all-replica corruption → resume must walk back
        self.walkback_requested = False
        self.last_verified_step = -1
        # last check's verdict, for drills/tests: {"step", "mismatched_hosts",
        # "leaves": [(host, "section.leaf"), ...]}
        self.last_report: Optional[Dict[str, Any]] = None
        self.checks = 0
        self.mismatches = 0
        self.audits = 0
        self._names: Dict[str, List[str]] = {}
        self._audit_pending: Optional[Tuple[int, Dict[str, Any]]] = None

    # -- telemetry ---------------------------------------------------------
    def _emit(self, name: str, value: float = 1.0, step: int = 0) -> None:
        tel = self.telemetry
        if tel is not None and hasattr(tel, "reliability_event"):
            tel.reliability_event(f"integrity/{name}", float(value),
                                  int(step))

    # -- attribution metadata ---------------------------------------------
    def _section_names(self, engine, fp: Dict[str, Any]) -> Dict[str, List[str]]:
        """Leaf names per section, resolved lazily from the live state (the
        digest arrays carry order, the trees carry names)."""
        if self._names:
            return self._names
        names: Dict[str, List[str]] = {}
        for sec in fp:
            if sec in ("grads", "params"):
                names[sec] = fingerprint_names(engine.state.params)
            elif sec == "opt_state":
                names[sec] = fingerprint_names(engine.state.opt_state)
            else:
                names[sec] = ["loss"]
        self._names = names
        return names

    def _row_index(self, fp: Dict[str, Dict[str, np.ndarray]]) \
            -> List[Tuple[str, str, int]]:
        """Flat wire-row index → (section, digest kind, leaf idx)."""
        idx = []
        for sec in SECTIONS:
            if sec not in fp:
                continue
            n = len(fp[sec]["bitsum"])
            for kind in ("bitsum", "sumsq", "nonfinite"):
                idx.extend((sec, kind, i) for i in range(n))
        return idx

    def _to_row(self, fp: Dict[str, Dict[str, np.ndarray]]) -> np.ndarray:
        """Digest dict → one float64 wire row (int32 bitsums are exact in
        float64). Section/kind order must match :meth:`_row_index`."""
        parts = []
        for sec in SECTIONS:
            if sec not in fp:
                continue
            for kind in ("bitsum", "sumsq", "nonfinite"):
                parts.append(np.asarray(fp[sec][kind], np.float64).ravel())
        return np.concatenate(parts) if parts else np.zeros(0, np.float64)

    # -- step hooks --------------------------------------------------------
    def pre_step(self, engine, batch) -> None:
        """Called by ``train_batch`` BEFORE the live (donating) step when an
        audit is due: the shadow recompute must read the state buffers the
        live step is about to donate. Runs the rotating-auditor schedule."""
        cfg = self.cfg
        if not (cfg.enabled and cfg.audit_interval):
            return
        step = int(engine.global_steps) + 1  # the step about to run
        if step % int(cfg.audit_interval) != 0:
            return
        auditor = (step // int(cfg.audit_interval)) % max(1, self._count)
        if auditor != self._index:
            return
        fn = engine._ensure_audit_step()
        _state, out = fn(engine.state, batch, engine._lr_override)
        fp = (out.aux or {}).get("integrity")
        if fp is None:
            return
        self._audit_pending = (step, _fp_to_host(fp))
        self.audits += 1
        self._emit("audit_steps", step=step)

    def on_step(self, engine, out) -> None:
        """Called by ``train_batch`` after every optimizer step (post
        ``global_steps`` increment). Off-cadence steps return without
        touching device data."""
        cfg = self.cfg
        if not cfg.enabled:
            return
        fp_dev = (getattr(out, "aux", None) or {}).get("integrity")
        if fp_dev is None:
            return
        step = int(engine.global_steps)
        audit_due = (self._audit_pending is not None
                     and self._audit_pending[0] == step)
        check_due = (cfg.check_interval
                     and step % int(cfg.check_interval) == 0)
        if not (audit_due or check_due):
            return
        fp = _fp_to_host(fp_dev)
        if audit_due:
            self._audit_compare(engine, fp, step)
        if check_due:
            self._check(engine, fp, step)

    # -- cross-host compare ------------------------------------------------
    def _check(self, engine, fp, step: int) -> None:
        row = self._to_row(fp)
        rows = np.asarray(self._gather(row), np.float64)
        self.checks += 1
        self._emit("checks", step=step)
        keys = [rows[h].tobytes() for h in range(rows.shape[0])]
        votes: Dict[bytes, int] = {}
        for k in keys:
            votes[k] = votes.get(k, 0) + 1
        majority = max(votes.items(), key=lambda kv: kv[1])[0]
        bad = [h for h, k in enumerate(keys) if k != majority]
        if not bad:
            if not self.walkback_requested:
                self.last_verified_step = step
            self.last_report = {"step": step, "mismatched_hosts": [],
                                "leaves": []}
            return
        maj_row = np.frombuffer(majority, np.float64)
        idx = self._row_index(fp)
        names = self._section_names(engine, fp)
        leaves: List[Tuple[int, str]] = []
        for h in bad:
            diff = np.flatnonzero(rows[h] != maj_row)
            for d in diff[:8]:  # cap the report, not the detection
                sec, kind, i = idx[d]
                leaves.append((h, f"{sec}.{names[sec][i]}:{kind}"))
            self.mismatches += 1
            self._emit("mismatches", step=step)
            self._emit("attributed_host", value=float(h), step=step)
            self.attribution_counts[h] = self.attribution_counts.get(h, 0) + 1
        self.last_report = {"step": step, "mismatched_hosts": bad,
                            "leaves": leaves}
        detail = "; ".join(f"host {h}: {name}" for h, name in leaves[:4])
        log_dist(f"integrity: digest mismatch at step {step} attributed to "
                 f"host(s) {bad} ({detail})")
        thr = int(self.cfg.quarantine_threshold)
        over = [h for h in bad if thr and self.attribution_counts[h] >= thr]
        if over:
            self._quarantine(engine, over, step)

    # -- shadow audit ------------------------------------------------------
    def _audit_compare(self, engine, live_fp, step: int) -> None:
        _astep, shadow = self._audit_pending
        self._audit_pending = None
        rtol = float(self.cfg.audit_rtol)
        bad: List[str] = []
        names = self._section_names(engine, live_fp)
        for sec in live_fp:
            if sec not in shadow:
                continue
            ls, ss = live_fp[sec], shadow[sec]
            sq_l = np.asarray(ls["sumsq"], np.float64)
            sq_s = np.asarray(ss["sumsq"], np.float64)
            nf_l = np.asarray(ls["nonfinite"])
            nf_s = np.asarray(ss["nonfinite"])
            rel = np.abs(sq_l - sq_s) / np.maximum(1.0, np.abs(sq_s))
            # nonfinite sumsq on both sides (overflow step) compares equal
            rel = np.where(~np.isfinite(sq_l) & ~np.isfinite(sq_s), 0.0, rel)
            for i in np.flatnonzero((rel > rtol) | (nf_l != nf_s)):
                bad.append(f"{sec}.{names[sec][i]}")
        if not bad:
            if not self.walkback_requested:
                self.last_verified_step = step
            return
        self.mismatches += 1
        self._emit("mismatches", step=step)
        # all-replica compute corruption: the live step disagrees with its
        # own shadow recompute AFTER the last verified step → the current
        # weights are suspect; resume must walk back, not reload them
        self.walkback_requested = True
        self._emit("walkbacks", step=step)
        reason = (f"integrity audit mismatch at step {step} "
                  f"(last verified step {self.last_verified_step}): "
                  f"{', '.join(bad[:4])}")
        log_dist(f"integrity: {reason}")
        self._escalate(engine, reason)

    # -- escalation --------------------------------------------------------
    def _quarantine(self, engine, hosts: List[int], step: int) -> None:
        self.excluded_hosts = sorted(set(self.excluded_hosts) | set(hosts))
        for h in hosts:
            self._emit("quarantines", value=float(h), step=step)
        reason = (f"integrity quarantine: host(s) {hosts} attributed "
                  f"{self.cfg.quarantine_threshold}+ digest mismatches "
                  f"by step {step}")
        log_dist(f"integrity: {reason} — excluded_hosts="
                 f"{self.excluded_hosts}")
        self._escalate(engine, reason)

    def _escalate(self, engine, reason: str) -> None:
        action = (self.cfg.on_corruption or "exit").lower()
        if action == "raise":
            raise IntegrityError(reason)
        if action == "warn":
            logger.warning(f"integrity: {reason} (on_corruption=warn)")
            return
        # "exit": request checkpoint-and-exit through the elastic boundary
        # (PreemptionGuard.step_boundary polls engine.integrity — the same
        # protocol as watchdog on_violation=exit / heartbeat host loss)
        self.restart_requested = True
        if not self.restart_reason:
            self.restart_reason = reason
