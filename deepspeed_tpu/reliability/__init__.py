"""Reliability subsystems that sit ABOVE the runtime: today the numerics
integrity plane (silent-data-corruption detection — ``integrity.py``).
Crash consistency, the training watchdog, and elastic resume live in
``runtime/`` and ``elasticity/``; this package hosts the guardrails that
judge whether the numbers those systems move around are still correct."""

from .integrity import (IntegrityError, IntegrityPlane, fingerprint_names,
                        tree_fingerprint)

__all__ = [
    "IntegrityError",
    "IntegrityPlane",
    "fingerprint_names",
    "tree_fingerprint",
]
