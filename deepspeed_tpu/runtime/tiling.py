"""TiledLinear: split a large linear into tiles to bound peak memory.

Reference parity: ``runtime/zero/tiling.py TiledLinear`` (splits a Linear
into row/col tiles so ZeRO-3 gathers smaller pieces). TPU-first: the tile
loop is a ``lax.scan`` over input-dim tiles with an fp32 accumulator — XLA
keeps one tile of the weight live at a time (with ZeRO-3 sharding, one
all-gather per tile instead of one huge gather), same peak-memory effect.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def tiled_linear(x: jnp.ndarray, w: jnp.ndarray,
                 bias: Optional[jnp.ndarray] = None,
                 in_splits: int = 1, out_splits: int = 1) -> jnp.ndarray:
    """y = x @ w (+ bias), computed in in_splits × out_splits tiles.
    x: [..., in]; w: [in, out]. Tile sizes must divide evenly."""
    in_f, out_f = w.shape
    if in_f % in_splits or out_f % out_splits:
        raise ValueError(f"splits {in_splits}x{out_splits} must divide {w.shape}")
    ti, to = in_f // in_splits, out_f // out_splits

    # scan over input tiles, accumulating partial sums in fp32
    x_tiles = jnp.stack(jnp.split(x, in_splits, axis=-1))       # [I, ..., ti]
    w_tiles = w.reshape(in_splits, ti, out_f)                   # [I, ti, out]

    def body(acc, xw):
        xt, wt = xw
        if out_splits == 1:
            return acc + (xt @ wt).astype(jnp.float32), None
        # inner loop over output tiles keeps the live partial small
        parts = [xt @ wt[:, j * to:(j + 1) * to] for j in range(out_splits)]
        return acc + jnp.concatenate(parts, axis=-1).astype(jnp.float32), None

    acc0 = jnp.zeros(x.shape[:-1] + (out_f,), jnp.float32)
    acc, _ = lax.scan(body, acc0, (x_tiles, w_tiles))
    if bias is not None:
        acc = acc + bias.astype(jnp.float32)
    return acc.astype(x.dtype)


class TiledLinear:
    """Module-style wrapper (reference API shape): holds splits, applies
    :func:`tiled_linear`."""

    def __init__(self, in_splits: int = 1, out_splits: int = 1):
        self.in_splits = in_splits
        self.out_splits = out_splits

    def __call__(self, x, w, bias=None):
        return tiled_linear(x, w, bias, self.in_splits, self.out_splits)
