"""Training watchdog: notice when training has gone off the rails.

Reference lineage: DeepSpeed's fp16 optimizer already *skips* overflowed
steps and cuts the loss scale, but nothing in the reference loop bounds how
long that can go on, flags a diverging host-side loss, or notices a stalled
step. On preemption-prone TPU fleets those are the failure modes that burn
whole reservations (ZeRO-Infinity assumes resumability, arXiv:2104.07857;
Gemma-class pod runs assume frequent preempt-and-resume, arXiv:2605.25645).

The watchdog is deliberately *in-band and host-side*: it acts only on
signals the loop already computes (``StepOutput.overflow`` / ``.loss`` and
wall-clock time between step boundaries), so it adds zero device work. The
engine calls :meth:`step_started` / :meth:`observe` around every optimizer
step when ``watchdog.enabled`` is set; each detector emits ``Reliability/*``
events through TelemetryHub and, on a violation, applies the configured
``on_violation`` policy:

- ``raise``   — raise :class:`WatchdogViolation` (abort the run);
- ``warn``    — log and keep going;
- ``restore`` — reload the newest good checkpoint from ``restore_dir`` (or
  the bound :class:`~deepspeed_tpu.elasticity.elastic_agent.PreemptionGuard`
  save dir) and continue;
- ``exit``    — set :attr:`restart_requested`, which a bound PreemptionGuard
  treats exactly like a preemption signal at its next ``step_boundary`` —
  checkpoint-and-exit for an elastic restart.

Forcing these paths in tests: ``deepspeed_tpu.testing.faults``.
"""

from __future__ import annotations

import contextlib
import math
import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..utils.logging import log_dist, logger


class WatchdogViolation(RuntimeError):
    """A watchdog detector fired with ``on_violation: raise``."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


class HostHeartbeat:
    """Multi-host liveness: convert a dead peer or hung collective into a
    clean elastic exit instead of an indefinite hang (the elastic training
    runtime — docs/reliability.md "Elastic training & universal checkpoint").

    Two detection paths, both deterministic under the fault harness
    (``faults.host_loss``):

    - **liveness allgather** — every ``beat()`` gathers ``(host, beat
      counter, step)`` from all processes (``multihost_utils
      .process_allgather`` by default — the same collective lane PR 10's
      straggler gather rides, so the heartbeat adds no new comm pattern).
      A peer whose row is missing or whose counter stops advancing for
      ``heartbeat_max_missed`` consecutive gathers is declared dead.
    - **per-collective deadline** — the gather itself runs under a wall-
      clock deadline (``collective_deadline_s``): a peer that died mid-step
      leaves the survivors stuck *inside* the collective, which no amount of
      post-hoc checking can see. The deadline timer fires off-thread,
      records the hang, and the caller observes it as a host loss the moment
      the collective unblocks (or, on a real fleet, the process manager
      reaps the stuck process while the recorded hint explains why).

    Detection is sticky: once a host loss is recorded, ``beat()`` keeps
    returning it so every layer (watchdog → PreemptionGuard → elastic
    restart) sees the same verdict. Injectable ``gather_fn`` / ``clock`` /
    ``process_count`` make single-process tests exact.
    """

    def __init__(self, config, telemetry=None,
                 gather_fn: Optional[Callable[[np.ndarray], Any]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        import jax

        self.cfg = config
        self.telemetry = telemetry
        self._gather = gather_fn
        self._clock = clock
        self._idx = (jax.process_index() if process_index is None
                     else int(process_index))
        self._n = (jax.process_count() if process_count is None
                   else int(process_count))
        self._beats = 0
        self._last_t: Optional[float] = None
        self._last_seen: Dict[int, int] = {}
        self._stale: Dict[int, int] = {}
        self.detected: Optional[Dict[str, Any]] = None
        self.hung: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------ #
    def _emit(self, name: str, step: int, value: float = 1.0) -> None:
        tel = self.telemetry
        if tel is not None and hasattr(tel, "reliability_event"):
            tel.reliability_event(name, value, step)

    def _do_gather(self, payload: np.ndarray) -> np.ndarray:
        if self._gather is not None:
            return np.atleast_2d(np.asarray(self._gather(payload)))
        from jax.experimental import multihost_utils

        return np.atleast_2d(np.asarray(
            multihost_utils.process_allgather(payload)))

    @contextlib.contextmanager
    def _deadline(self, what: str, step: int):
        """Arm a wall-clock deadline around one collective. The timer thread
        only RECORDS the hang (``self.hung``) — the caller turns it into a
        host-loss verdict when (if) the collective returns; on a real fleet
        a collective that never returns leaves the recorded hang as the
        post-mortem."""
        d = float(getattr(self.cfg, "collective_deadline_s", 0.0) or 0.0)
        if d <= 0:
            yield
            return
        t0 = self._clock()

        def fire():
            self.hung = {"kind": "hung_collective", "what": what,
                         "deadline_s": d, "step": step}
            logger.error(
                f"heartbeat: collective '{what}' blew its {d:g}s deadline — "
                f"a peer is likely dead; recording host loss")

        timer = threading.Timer(d, fire)
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()
            # fake clocks (tests) never tick the Timer thread — check the
            # injected clock too so deadline detection is deterministic
            if self.hung is None and self._clock() - t0 > d:
                fire()

    # ------------------------------------------------------------------ #
    def beat(self, step: int = 0, force: bool = False) -> Optional[Dict]:
        """One liveness round. Returns the (sticky) host-loss verdict dict
        or None; throttled to ``heartbeat_interval_s`` unless ``force``."""
        if self.detected is not None:
            return self.detected
        now = self._clock()
        interval = float(getattr(self.cfg, "heartbeat_interval_s", 0.0) or 0)
        if not force and self._last_t is not None and \
                now - self._last_t < interval:
            return None
        self._last_t = now
        self._beats += 1
        payload = np.asarray([self._idx, self._beats, int(step)], np.int64)
        with self._deadline("heartbeat_allgather", int(step)):
            rows = self._do_gather(payload)
        if self.hung is not None:
            return self._detect(dict(self.hung), int(step))
        seen = {int(r[0]): int(r[1]) for r in rows}
        dead = []
        for peer in range(self._n):
            if peer == self._idx:
                continue
            b = seen.get(peer)
            if b is None or b <= self._last_seen.get(peer, -1):
                self._stale[peer] = self._stale.get(peer, 0) + 1
            else:
                self._stale[peer] = 0
                self._last_seen[peer] = b
            if self._stale[peer] >= max(1, int(getattr(
                    self.cfg, "heartbeat_max_missed", 3))):
                dead.append(peer)
        if dead:
            return self._detect({"kind": "dead_peer", "peers": dead,
                                 "step": int(step)}, int(step))
        return None

    def _detect(self, info: Dict[str, Any], step: int) -> Dict[str, Any]:
        self.detected = info
        self._emit("elastic/host_loss_detected", step)
        logger.error(f"heartbeat: host loss detected: {info}")
        return info


class TrainingWatchdog:
    """See module docstring. Construct with a
    :class:`~deepspeed_tpu.runtime.config.WatchdogConfig`."""

    def __init__(self, config, telemetry=None, guard=None, heartbeat=None):
        self.cfg = config
        self.telemetry = telemetry
        self.guard = guard
        self.consecutive_skips = 0
        self.restart_requested = False
        self.restart_reason: Optional[str] = None
        self.violations = 0
        self._loss_window = deque(maxlen=max(2, int(config.loss_window)))
        self._time_window = deque(maxlen=max(2, int(config.stall_window)))
        self._step_t0: Optional[float] = None
        # multi-host heartbeat (host-loss detection → elastic exit): built
        # from the config's heartbeat keys, or injected for tests
        self.heartbeat = heartbeat
        if heartbeat is None and bool(getattr(config, "heartbeat", False)):
            self.heartbeat = HostHeartbeat(config, telemetry=telemetry)
        self._host_loss_handled = False

    # ------------------------------------------------------------------ #
    def bind_guard(self, guard) -> None:
        """Attach a PreemptionGuard: ``on_violation: exit`` requests a
        checkpoint-and-exit through it, and ``restore`` without an explicit
        ``restore_dir`` restores from the guard's save dir."""
        self.guard = guard

    def step_started(self) -> None:
        """Mark the wall-clock start of a step (engine prologue)."""
        self._step_t0 = time.monotonic()

    # ------------------------------------------------------------------ #
    def _emit(self, name: str, step: int, value: float = 1.0) -> None:
        tel = self.telemetry
        if tel is not None and hasattr(tel, "reliability_event"):
            tel.reliability_event(name, value, step)

    def observe(self, engine, out, step_time_s: Optional[float] = None) -> None:
        """Run every detector against one completed optimizer step.

        ``out`` is the engine's StepOutput; reading ``.loss``/``.overflow``
        here forces a host sync, which is why the watchdog is opt-in — with
        ``watchdog.enabled: false`` the training step is untouched.
        """
        step = int(getattr(engine, "global_steps", 0))
        now = time.monotonic()
        if step_time_s is None and self._step_t0 is not None:
            step_time_s = now - self._step_t0
        self._step_t0 = None

        # 0. multi-host liveness: a dead peer or hung collective routes
        # through the elastic-exit protocol (durable universal save + clean
        # exit at the guard's next boundary) rather than any on_violation
        # policy — there is nothing to "warn and continue" past when a host
        # is gone, and raising would skip the checkpoint
        if self.heartbeat is not None and not self._host_loss_handled:
            det = self.heartbeat.beat(step=step)
            if det is not None:
                self._host_loss(engine, det, step)

        cfg = self.cfg
        overflow = bool(out.overflow)
        loss = float(out.loss)

        # 1. consecutive overflow-skip limit: the fp16 scaler cutting the
        # scale forever is divergence wearing a trench coat
        if overflow:
            self.consecutive_skips += 1
            self._emit("overflow_skip", step)
            if cfg.max_skipped_steps and \
                    self.consecutive_skips >= int(cfg.max_skipped_steps):
                self._violate(
                    engine, "skip_limit", step,
                    f"{self.consecutive_skips} consecutive overflow-skipped "
                    f"steps (limit {cfg.max_skipped_steps}) at step {step}")
                return
        else:
            self.consecutive_skips = 0

        # 2. non-finite / spiking host-side loss. When the integrity plane
        # is on, its per-leaf digest pass rides along in ``out.aux`` — the
        # violation message then NAMES the poisoned layers instead of just
        # reporting a bad scalar, at no extra device sync (satellite of
        # docs/reliability.md "Numerics integrity & SDC")
        if not math.isfinite(loss):
            if cfg.detect_non_finite:
                where = self._nonfinite_leaves(engine, out)
                suffix = f"; nonfinite grads in {', '.join(where)}" \
                    if where else ""
                self._violate(engine, "non_finite_loss", step,
                              f"non-finite loss ({loss}) at step "
                              f"{step}{suffix}")
                return
        else:
            # on-device per-leaf grad sentinels: nonfinite grads under a
            # FINITE loss are corruption the host-side loss check cannot
            # see. Overflow steps are excluded — fp16 inf grads there are
            # the loss scaler's business (detector 1)
            if cfg.detect_non_finite and not overflow:
                where = self._nonfinite_leaves(engine, out)
                if where:
                    self._violate(engine, "non_finite_grads", step,
                                  f"non-finite grads at step {step} in "
                                  f"{', '.join(where)}")
                    return
            spike = float(cfg.loss_spike_factor or 0.0)
            if spike > 0 and len(self._loss_window) >= int(cfg.min_samples):
                med = statistics.median(self._loss_window)
                if med > 0 and loss > spike * med:
                    logger.warning(f"watchdog: loss {loss:.4g} > "
                                   f"{spike:g}x trailing median {med:.4g} "
                                   f"at step {step}")
                    self._emit("loss_spike", step, value=loss / med)
            self._loss_window.append(loss)

        # 3. stall detection on wall-clock step time
        if step_time_s is not None and step_time_s > 0:
            stall = float(cfg.stall_factor or 0.0)
            if stall > 0 and len(self._time_window) >= int(cfg.min_samples):
                med = statistics.median(self._time_window)
                if med > 0 and step_time_s > stall * med:
                    logger.warning(
                        f"watchdog: step {step} took {step_time_s:.2f}s "
                        f"(> {stall:g}x trailing median {med:.2f}s)")
                    self._emit("stall_warning", step,
                               value=step_time_s / med)
            hard = float(cfg.hard_timeout_s or 0.0)
            if hard > 0 and step_time_s > hard:
                self._violate(
                    engine, "stall_timeout", step,
                    f"step {step} took {step_time_s:.2f}s "
                    f"(hard_timeout_s={hard:g})")
                return
            self._time_window.append(step_time_s)

    @staticmethod
    def _nonfinite_leaves(engine, out, limit: int = 4):
        """Layer attribution from the integrity fingerprint pass (present in
        ``out.aux`` when ``reliability.integrity`` is enabled): dotted names
        of grad leaves carrying NaN/Inf elements. Empty without the plane —
        the host-side loss detectors still run unchanged."""
        fp = (getattr(out, "aux", None) or {}).get("integrity")
        if not isinstance(fp, dict) or "grads" not in fp:
            return []
        import numpy as np

        counts = np.asarray(fp["grads"]["nonfinite"])
        idx = np.flatnonzero(counts)
        if idx.size == 0:
            return []
        try:
            from ..reliability.integrity import fingerprint_names

            names = fingerprint_names(engine.state.params)
        except Exception:
            names = []
        leaves = []
        for i in idx[:limit]:
            nm = names[i] if i < len(names) else f"leaf[{i}]"
            leaves.append(f"{nm} ({int(counts[i])} elem)")
        if idx.size > limit:
            leaves.append(f"+{int(idx.size) - limit} more leaves")
        return leaves

    # convenience alias mirroring PreemptionGuard.step_boundary: run the
    # detectors and report whether the loop should exit for a restart
    def step_boundary(self, engine, out,
                      step_time_s: Optional[float] = None) -> bool:
        self.observe(engine, out, step_time_s=step_time_s)
        return self.restart_requested

    # ------------------------------------------------------------------ #
    def _host_loss(self, engine, det: Dict[str, Any], step: int) -> None:
        """Host loss always takes the elastic-exit path: flag the restart,
        trigger a bound PreemptionGuard (durable save + reshard hint at the
        next step boundary), dump the flight recorder — never hang, never
        silently continue."""
        self._host_loss_handled = True
        self.violations += 1
        self._emit("violation/host_loss", step)
        tel = self.telemetry
        if tel is not None and hasattr(tel, "trace_dump"):
            try:
                tel.trace_dump("watchdog_host_loss")
            except Exception:
                pass
        self.restart_requested = True
        self.restart_reason = "host_loss"
        logger.error(f"watchdog: host loss ({det}) at step {step} — "
                     f"requesting durable save + elastic exit at the next "
                     f"guard boundary")
        if self.guard is not None and hasattr(self.guard, "trigger"):
            self.guard.trigger()

    # ------------------------------------------------------------------ #
    def _violate(self, engine, kind: str, step: int, msg: str) -> None:
        self.violations += 1
        self._emit(f"violation/{kind}", step)
        # flight-recorder dump FIRST: whatever the on_violation policy does
        # next (raise/restore/exit), the spans of the steps that led here
        # are on disk for the post-mortem (telemetry/trace.py)
        tel = self.telemetry
        if tel is not None and hasattr(tel, "trace_dump"):
            try:
                path = tel.trace_dump(f"watchdog_{kind}")
                if path:
                    logger.warning(
                        f"watchdog: flight-recorder trace dumped to {path}")
            except Exception:
                pass
        action = (self.cfg.on_violation or "raise").lower()
        if action == "warn":
            logger.warning(f"watchdog violation ({kind}): {msg}")
            return
        if action == "restore":
            restore_dir = self.cfg.restore_dir or \
                getattr(self.guard, "save_dir", None)
            if restore_dir and hasattr(engine, "load_checkpoint"):
                logger.warning(f"watchdog violation ({kind}): {msg} — "
                               f"auto-restoring from {restore_dir}")
                self._emit("auto_restore", step)
                path, _ = engine.load_checkpoint(restore_dir)
                if path is not None:
                    self._reset_after_restore()
                    log_dist(f"watchdog: restored {path}, resuming at step "
                             f"{engine.global_steps}")
                    return
                logger.error(f"watchdog: no checkpoint to restore under "
                             f"{restore_dir}")
            else:
                logger.error("watchdog: on_violation=restore but no "
                             "restore_dir configured and no guard bound")
            # unable to restore — fall through to raise: silently continuing
            # a diverged run is the one unacceptable outcome
        elif action == "exit":
            logger.warning(f"watchdog violation ({kind}): {msg} — "
                           f"requesting checkpoint-and-exit at the next "
                           f"guard boundary")
            self.restart_requested = True
            return
        raise WatchdogViolation(kind, msg)

    def _reset_after_restore(self) -> None:
        self.consecutive_skips = 0
        self._loss_window.clear()
        self._time_window.clear()
        self._step_t0 = None
