"""DeepSpeed-compatible typed configuration.

Capability parity with the reference's ``runtime/config.py`` (``DeepSpeedConfig``
at :651) and its pydantic sub-configs (e.g. ZeRO config ``runtime/zero/config.py:95``):
a JSON/dict config tree with the same key names, plus the batch-size resolution
invariant ``train_batch_size == micro_batch * gradient_accumulation_steps * dp_world``.

TPU-first differences:
- ``mesh``: explicit named-axis mesh shape (data/fsdp/tensor/pipe/seq/expert) —
  replaces the reference's process-group plumbing (``utils/groups.py``).
- ZeRO stages select *sharding specs* (see ``runtime/zero/sharding.py``), not
  runtime hook machinery.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from ..utils.logging import logger
from .config_utils import ConfigModel, is_auto, register_config_model
from . import constants as C


@register_config_model
@dataclass
class FP16Config(ConfigModel):
    """Reference: ``runtime/fp16`` config block (``runtime/config.py`` fp16 keys)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 → dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0


@register_config_model
@dataclass
class BF16Config(ConfigModel):
    enabled: bool = False


@register_config_model
@dataclass
class OffloadDeviceConfig(ConfigModel):
    """Reference: ``runtime/zero/offload_config.py:21/:52``."""
    device: str = C.OFFLOAD_NONE  # none | cpu | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = False
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    ratio: float = 1.0
    max_in_cpu: int = 1_000_000_000


@register_config_model
@dataclass
class ZeroConfig(ConfigModel):
    """Reference: ``runtime/zero/config.py:95-376``. Stage semantics:

    0: plain DP (grad psum over data axis)
    1: optimizer states sharded over the fsdp axis
    2: + gradients reduce-scattered over fsdp
    3: + parameters sharded over fsdp, gathered on use (XLA SPMD schedules the
       all-gathers; replaces the IPG bucket/stream machinery of the reference)
    """
    stage: int = 0
    overlap_comm: bool = True          # XLA latency-hiding scheduler: always on
    contiguous_gradients: bool = True  # XLA owns layout; accepted for compat
    reduce_bucket_size: int = 500_000_000
    allgather_bucket_size: int = 500_000_000
    reduce_scatter: bool = True
    round_robin_gradients: bool = False
    offload_param: OffloadDeviceConfig = field(default_factory=OffloadDeviceConfig)
    offload_optimizer: OffloadDeviceConfig = field(default_factory=OffloadDeviceConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    zero_quantized_weights: bool = False     # ZeRO++ qwZ
    zero_quantized_gradients: bool = False   # ZeRO++ qgZ
    zero_hpz_partition_size: int = 1         # ZeRO++ hpZ (hierarchical partition)
    mics_shard_size: int = -1                # MiCS sub-axis shard size
    mics_hierarchical_params_gather: bool = False
    ignore_unused_parameters: bool = True
    elastic_checkpoint: bool = False


@register_config_model
@dataclass
class OptimizerConfig(ConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)
    # param-group analog (reference: the param_groups list handed to
    # torch optimizers): [{"pattern": <regex over leaf paths>, <hyper
    # overrides>}, ...]; first match wins, unmatched leaves use `params`
    param_groups: List[Dict[str, Any]] = field(default_factory=list)


@register_config_model
@dataclass
class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


@register_config_model
@dataclass
class MeshConfig(ConfigModel):
    """TPU-native replacement for mpu/topology/process-groups: the named device
    mesh. Sizes of 1 mean the axis is unused. ``data`` defaults to "fill the
    remaining devices". fsdp is folded with data for ZeRO sharding (the ZeRO
    partition group == the data-parallel group, as in the reference)."""
    data: int = -1        # -1 → infer (devices / product(other axes))
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def axis_sizes(self, n_devices: int) -> Dict[str, int]:
        fixed = self.tensor * self.pipe * self.seq * self.expert
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by "
                    f"tensor*pipe*seq*expert={fixed}")
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh data={data} expert={self.expert} pipe={self.pipe} "
                f"seq={self.seq} tensor={self.tensor} = {total} != device count {n_devices}")
        return {"data": data, "expert": self.expert, "pipe": self.pipe,
                "seq": self.seq, "tensor": self.tensor}


@register_config_model
@dataclass
class TensorParallelConfig(ConfigModel):
    """Reference: ``autotp_size`` training config (``runtime/tensor_parallel/``)."""
    autotp_size: int = 1
    tp_overlap_comm: bool = False


@register_config_model
@dataclass
class AttentionOpsConfig(ConfigModel):
    """``attention`` block — attention-kernel behavior knobs
    (docs/performance.md "Native GQA attention").

    ``gqa_native: false`` (the default) keeps every attention program
    byte-identical to the historical widening path (K/V broadcast to the
    query head count before the kernel). ``true`` arms the native-GQA flash
    kernels process-wide (``ops.attention.configure_gqa_native``, published
    at engine init like the remat-policy registry): K/V stay kv-head-narrow
    through forward AND backward — up to nq/nkv× less KV HBM traffic —
    with ``repeat_kv`` surviving only as the XLA-fallback reference and
    the Ulysses head-sharding alignment widener."""
    gqa_native: bool = False


@register_config_model
@dataclass
class RingSequenceConfig(ConfigModel):
    """``sequence.ring`` block — ring context-parallelism schedule knobs
    (docs/performance.md "Million-token context").

    ``layout: zigzag`` replaces the contiguous causal layout (rank r does
    r+1 block-pairs; rank P-1 is a P× straggler) with the striped layout
    where rank r owns global half-chunks {r, 2P-1-r} — every rank then
    executes exactly 2P+1 flash pairs and causal wall-clock drops ~2×.
    ``overlap: true`` issues each hop's ``ppermute`` before the previous
    block's flash kernels so the ICI transfer hides under compute.
    Published at engine init via ``sequence.ring.configure_ring`` (the
    ``attention.gqa_native`` pattern); both settings preserve exact
    numerics — layout/ordering changes only."""
    layout: str = "contiguous"  # "contiguous" | "zigzag"
    overlap: bool = False


@register_config_model
@dataclass
class SequenceConfig(ConfigModel):
    """``sequence`` block — long-context behavior of the training engine.

    ``tiled_loss: true`` routes the engine loss through the model's tiled
    fused logits+loss head (``sequence.tiled.tiled_fused_logits_loss``):
    the ``[B, S, V]`` logits tensor — the FIRST thing to OOM at long
    context, before attention — is never materialized; logits exist one
    ``[B, S/shards, V]`` tile at a time inside a rematerialized scan.
    Default OFF keeps the train step byte-identical (pinned)."""
    tiled_loss: bool = False
    tiled_loss_shards: int = 8
    ring: RingSequenceConfig = field(default_factory=RingSequenceConfig)


@register_config_model
@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Reference: ``runtime/activation_checkpointing/checkpointing.py`` flags.
    On TPU these select a ``jax.checkpoint`` (remat) policy."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False   # → offload remat residuals to host memory
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # none | full | dots_saveable | save_attn_out | save_big_matmuls |
    # save_names | offload | ... — the named-policy registry in
    # runtime/activation_checkpointing/checkpointing.py (POLICIES)
    policy: str = "none"


@register_config_model
@dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@register_config_model
@dataclass
class CommsLoggerConfig(ConfigModel):
    """Reference ``comms_logger`` block (``utils/comms_logging.py``): with
    ``prof_all`` off, only op names starting with a ``prof_ops`` entry are
    recorded by ``comm.CommsTelemetry``."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@register_config_model
@dataclass
class CommsOverlapConfig(ConfigModel):
    """``comms_overlap`` block — the gradient-communication overlap engine
    (``comm/overlap.py``; see docs/performance.md). ``enabled: false`` (the
    default) reproduces the baseline numerics bit-for-bit; when enabled the
    engine reduces gradients with explicit, coalesced collectives under
    shard_map instead of per-leaf sharding-constraint-implied ones.

    The gradient-reduction engine requires ZeRO stage <= 2 (stage 3's
    gather-on-use parameter sharding conflicts with the manual data-parallel
    region) and no pipeline axis. At stage 3, enabling the block requires
    ``layer_prefetch`` — the ZeRO-3 half of the overlap story: per-layer
    param all-gather prefetch pipelined against the previous layer's
    matmuls (T3), with the XLA async-collective flags still applied."""
    enabled: bool = False
    # flatten small grad leaves into flat buckets of ~this size before the
    # reduce-scatter (reference reduce_bucket_size analog); leaves larger
    # than the cap keep their own per-leaf reduce-scatter
    coalesce_buckets: bool = True
    bucket_size_mb: float = 25.0
    # accumulate micro-batch grads locally and reduce ONCE per optimizer
    # step (gas x less DP comm volume; costs a full-size fp32 accumulator)
    deferred_gradient_reduce: bool = True
    # LoCo error feedback for the int8-quantized reduction paths (reference
    # all_to_all_loco_quant_reduce; needs zero_quantized_gradients or
    # quantized_all_reduce — without a quantizer there is no error to feed)
    loco: bool = False
    loco_err_beta: float = 0.8
    # EQuARX-style quantized all-reduce (comm/compressed.py
    # quantized_all_reduce): the non-ZeRO DP gradient path — leaves whose
    # grad layout stays replicated (stage 0/1, or indivisible dims) reduce
    # via int8 quantized reduce-scatter + int8 quantized all-gather instead
    # of a full-width psum (~4x less wire per half). Composes with loco
    # error feedback; bucketed small leaves keep their exact fp32 buckets.
    quantized_all_reduce: bool = False
    # ZeRO-3 per-layer all-gather prefetch (comm/overlap.py prefetch_scan):
    # the stacked-layer scan gathers layer i+1's param shards while layer
    # i's matmuls run instead of gathering at first use. prefetch_depth =
    # layers of gathered params kept in flight (1 = double buffer); each
    # costs one gathered layer of HBM
    layer_prefetch: bool = False
    prefetch_depth: int = 1
    # XLA latency-hiding-scheduler / async-collective programming
    async_collectives: bool = True
    combine_threshold_mb: float = 0.0  # 0 -> leave the XLA default
    extra_xla_flags: List[str] = field(default_factory=list)
    # optional link bandwidth (GB/s per device) for the telemetry hub's
    # estimated unoverlapped-comm fraction; 0 -> skip that event
    reference_bw_gbps: float = 0.0


@register_config_model
@dataclass
class ProfilerConfig(ConfigModel):
    """Config-gated JAX profiler session: brackets global steps
    ``[start_step, end_step]`` with ``jax.profiler.start_trace/stop_trace``
    (xprof/tensorboard-viewable), managed by ``telemetry.ProfilerSession``."""
    enabled: bool = False
    start_step: int = 1
    end_step: int = 1
    output_dir: str = ""  # "" → <tmpdir>/dstpu_profile


@register_config_model
@dataclass
class TraceTelemetryConfig(ConfigModel):
    """``telemetry.trace`` block — span tracer + crash flight recorder
    (``telemetry/trace.py``; docs/observability.md). Default OFF: the step
    and serving paths record nothing and start no timers."""
    enabled: bool = False
    ring_size: int = 4096       # flight-recorder capacity (events retained)
    export_path: str = ""       # "" → <tmpdir>/dstpu_trace/flight_<pid>.json
    dump_on_crash: bool = True  # auto-dump on watchdog/fault/preempt/atexit


@register_config_model
@dataclass
class CompileTelemetryConfig(ConfigModel):
    """``telemetry.compile`` block — recompilation sentinel + analytic
    cost-model MFU attribution (``telemetry/compile.py``;
    docs/observability.md). Default OFF: every monitored jit site gets the
    plain ``jax.jit`` object back and the default program is
    byte-identical."""
    enabled: bool = False
    # distinct signatures per program treated as expected warmup
    warmup_signatures: int = 1
    # unexpected recompiles tolerated before on_budget fires (0 = unlimited)
    recompile_budget: int = 0
    on_budget: str = "warn"       # warn | raise
    # pull cost_analysis() flops/bytes per compiled program
    cost_analysis: bool = True


@register_config_model
@dataclass
class AnomalyTelemetryConfig(ConfigModel):
    """``telemetry.anomaly`` block — step-time anomaly detection
    (``telemetry/anomaly.py``; docs/observability.md). Default OFF: the hub
    never feeds the detector."""
    enabled: bool = False
    window: int = 64              # rolling median/MAD window (samples)
    min_samples: int = 16         # silence until this many samples
    spike_mad: float = 6.0        # spike: x > median + spike_mad * MAD
    mad_floor_frac: float = 0.02  # MAD floor as a fraction of the median
    drift_frac: float = 0.25      # drift: rolling median vs frozen baseline
    straggler_frac: float = 0.25  # per-host: above cross-host median by this
    dump_flight_recorder: bool = True  # trace dump on the first finding


@register_config_model
@dataclass
class TelemetryConfig(ConfigModel):
    """Top-level ``telemetry`` block (trace + compile + anomaly sub-blocks;
    the older observability gates — ``wall_clock_breakdown``,
    ``comms_logger``, ``profiler`` — stay where reference configs put
    them)."""
    trace: TraceTelemetryConfig = field(default_factory=TraceTelemetryConfig)
    compile: CompileTelemetryConfig = field(
        default_factory=CompileTelemetryConfig)
    anomaly: AnomalyTelemetryConfig = field(
        default_factory=AnomalyTelemetryConfig)
    # JSONL monitor sink rotation threshold (MiB): when events.jsonl exceeds
    # this, it rotates to events.jsonl.1 so long serving runs can't fill the
    # disk. 0 = no rotation (docs/observability.md).
    jsonl_max_mb: float = 0.0


@register_config_model
@dataclass
class TuningConfig(ConfigModel):
    """Top-level ``tuning`` block — the telemetry-actuated online tuner
    (``tuning/tuner.py``; docs/tuning.md). Default OFF: the engine never
    constructs a tuner and the train step is byte-identical to pre-tuning
    behavior (pinned by tests/test_tuning.py). Field semantics mirror
    ``tuning.TunerOptions``; the serving side takes the same keys under
    ``serving.tuning`` on the router config."""
    enabled: bool = False
    # registered tunable names to search ([] = every train_step-boundary
    # knob in tuning/registry.py default_registry)
    knobs: List[str] = field(default_factory=list)
    steps_per_arm: int = 16       # optimizer steps dwelled per measured arm
    window_s: float = 600.0       # max trailing scoring window (seconds)
    min_samples: int = 8          # tsdb samples required before a verdict
    max_dwell_factor: int = 4     # abandon a window after this x dwell
    accept_mads: float = 3.0      # win margin: this many baseline MADs...
    min_rel_delta: float = 0.02   # ...AND this fraction of the baseline
    recompile_allowance: int = 2  # planned recompiles per arm (guard veto)
    seed: int = 0                 # arm-order shuffle seed
    persist: bool = True          # write winners to .dstpu_tuned.json
    reload: bool = True           # reload persisted winners (no re-search)
    path: str = ""                # "" = the default persist resolver


@register_config_model
@dataclass
class MonitorBackendConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"
    # wandb / comet extras
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None
    workspace: Optional[str] = None
    experiment_name: Optional[str] = None


@register_config_model
@dataclass
class PipelineConfig(ConfigModel):
    stages: int = 1
    partition_method: str = "parameters"  # parameters | uniform | type:regex
    activation_checkpoint_interval: int = 0
    pipe_schedule: str = "1f1b"           # 1f1b | gpipe | inference


@register_config_model
@dataclass
class MoEConfig(ConfigModel):
    enabled: bool = False
    expert_parallel_size: int = 1
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    drop_tokens: bool = True
    use_rts: bool = True          # random token selection
    aux_loss_coef: float = 0.01


@register_config_model
@dataclass
class CheckpointConfig(ConfigModel):
    """Reference: checkpoint-engine selection + options (``runtime/engine.py:1287``).

    Crash-consistency knobs (``docs/reliability.md``): ``atomic`` stages each
    save in ``<tag>.tmp.*`` and publishes it with fsync + manifest + atomic
    rename before ``latest`` advances; ``verify_on_load`` checks the SHA-256
    manifest and walks back to the newest verifiable tag on corruption;
    ``keep_last_n`` garbage-collects old tags (0 = keep all); ``io_retries`` /
    ``io_backoff_s`` retry transient checkpoint I/O errors with exponential
    backoff + jitter (0 retries = fail fast, the legacy behavior)."""
    engine: str = "default"  # default | async | fast
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    tag_validation: str = "Warn"  # Warn | Ignore | Fail
    load_universal: bool = False
    writer_buffer_mb: int = 64
    atomic: bool = True
    verify_on_load: bool = True
    keep_last_n: int = 0
    io_retries: int = 0
    io_backoff_s: float = 0.5


@register_config_model
@dataclass
class WatchdogConfig(ConfigModel):
    """Training watchdog (``runtime/watchdog.py``): acts on host-visible
    signals the loop already computes. Every detector defaults OFF so the
    default step is untouched; ``Reliability/*`` events flow through
    TelemetryHub (see ``docs/reliability.md``)."""
    enabled: bool = False
    # N consecutive overflow-skipped steps → violation (0 = off)
    max_skipped_steps: int = 0
    # NaN/Inf host-side loss → violation
    detect_non_finite: bool = True
    # loss > k × trailing-median loss → Reliability/loss_spike warning (0 = off)
    loss_spike_factor: float = 0.0
    loss_window: int = 32
    # step time > k × trailing-median step time → stall warning (0 = off)
    stall_factor: float = 0.0
    stall_window: int = 16
    # detectors based on a trailing median stay silent until this many samples
    min_samples: int = 5
    # any single step exceeding this wall-clock budget → violation (0 = off)
    hard_timeout_s: float = 0.0
    # raise | warn | restore (reload last good checkpoint from restore_dir)
    # | exit (request a checkpoint-and-exit via PreemptionGuard.step_boundary)
    on_violation: str = "raise"
    restore_dir: Optional[str] = None
    # ---- multi-host heartbeat (host-loss detection → elastic exit; see
    # docs/reliability.md "Elastic training & universal checkpoint") ----
    # run an allgather-based liveness round after optimizer steps
    heartbeat: bool = False
    # min seconds between liveness gathers (0 = every observed step)
    heartbeat_interval_s: float = 0.0
    # consecutive gathers a peer may miss / stall before it is declared dead
    heartbeat_max_missed: int = 3
    # wall-clock deadline on the liveness collective itself: a gather stuck
    # past this records a hung-collective host loss (0 = off)
    collective_deadline_s: float = 0.0


@register_config_model
@dataclass
class IntegrityConfig(ConfigModel):
    """``reliability.integrity`` block — the numerics-integrity plane
    (``deepspeed_tpu/reliability/integrity.py``; docs/reliability.md
    "Numerics integrity & SDC"). Default OFF: the training step is the exact
    pre-integrity program, byte-identical (pinned by tests/test_integrity.py).

    With ``enabled`` the jitted step additionally computes cheap per-leaf
    digests (bitcast-to-int32 wraparound sums + L2 norms + nonfinite counts)
    of replica-invariant quantities — post-all-reduce grads, post-step
    replicated params, optimizer moments, the loss scalar. Every
    ``check_interval`` steps the host allgathers the digest vector across
    processes and majority-votes: a minority row attributes the mismatch to a
    specific host. Every ``audit_interval`` steps a rotating auditor re-runs
    fwd/bwd on a recorded micro-batch and compares digests against the live
    step (catches all-replica compute SDC that replica invariance cannot
    see). ``quarantine_threshold`` repeated attributions to one host fire the
    elastic-exit path: durable universal save + ``reshard_hint.json`` with an
    ``excluded_hosts`` field that ``run_elastic`` reshards around."""
    enabled: bool = False
    # steps between cross-host digest compare rounds
    check_interval: int = 10
    # steps between shadow recompute audits (0 = off)
    audit_interval: int = 0
    # attributions to one host before quarantine fires (0 = never quarantine)
    quarantine_threshold: int = 3
    # relative tolerance for the shadow-audit L2 compare (bitcast sums are
    # exact; the audit recompute may legally differ by reduction order)
    audit_rtol: float = 1e-6
    # which quantities are fingerprinted
    fingerprint_grads: bool = True
    fingerprint_params: bool = True
    fingerprint_opt_state: bool = True
    # raise | warn | exit (quarantine via PreemptionGuard elastic exit)
    on_corruption: str = "exit"


@register_config_model
@dataclass
class ReliabilityConfig(ConfigModel):
    """Top-level ``reliability`` block (integrity sub-block;
    docs/reliability.md)."""
    integrity: IntegrityConfig = field(default_factory=IntegrityConfig)


@register_config_model
@dataclass
class MemoryTieringConfig(ConfigModel):
    """``memory.tiering`` block — the tiered memory subsystem
    (``deepspeed_tpu/memory``; docs/memory.md). Default OFF: the training
    step is the exact pre-tiering program, byte-identical (pinned by parity
    tests in tests/test_tiered_memory.py).

    ``optimizer_tier='host'`` keeps the optimizer state (fp32 masters'
    moments) host-resident between steps: the H2D restore prefetches on the
    transfer worker UNDER the fwd/bwd grad computation and the D2H
    writeback of the updated state overlaps the NEXT step — measured via
    ``Memory/tier/overlap_frac``. ``optimizer_tier='nvme'`` is the
    ZeRO-Infinity disk tier (``zero_optimization.offload_optimizer
    device=nvme`` is the streamed equivalent and remains supported).

    ``param_tier='host'`` parks cold ZeRO-3 stacked layer shards in host
    memory; the per-layer host→HBM copy-in rides the SAME pipeline as
    ``comms_overlap.layer_prefetch`` (the gather-to-compute constraint is
    issued a layer ahead — compose rule in docs/memory.md). Real on
    backends with a host memory space (TPU); identity on the CPU mesh."""
    enabled: bool = False
    optimizer_tier: str = "none"   # none | host | nvme
    param_tier: str = "none"       # none | host (needs layer_prefetch)
    pin_memory: bool = True
    nvme_path: Optional[str] = None


@register_config_model
@dataclass
class MemoryConfig(ConfigModel):
    """Top-level ``memory`` block (tiering sub-block; docs/memory.md)."""
    tiering: MemoryTieringConfig = field(default_factory=MemoryTieringConfig)


@register_config_model
@dataclass
class AIOConfig(ConfigModel):
    """Reference: ``runtime/swap_tensor/aio_config.py``."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


@dataclass
class DeepSpeedTPUConfig:
    """The full config tree. Built by :func:`parse_config`."""

    # batch sizes (resolved; see _resolve_batch_size)
    train_batch_size: int = 0
    train_micro_batch_size_per_gpu: int = 0
    gradient_accumulation_steps: int = 0

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_config: ZeroConfig = field(default_factory=ZeroConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    attention: AttentionOpsConfig = field(default_factory=AttentionOpsConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    comms_overlap: CommsOverlapConfig = field(default_factory=CommsOverlapConfig)
    profiler: ProfilerConfig = field(default_factory=ProfilerConfig)
    tensorboard: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    wandb: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    comet: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    csv_monitor: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    jsonl_monitor: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    tuning: TuningConfig = field(default_factory=TuningConfig)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    reliability: ReliabilityConfig = field(default_factory=ReliabilityConfig)
    aio: AIOConfig = field(default_factory=AIOConfig)
    sequence: SequenceConfig = field(default_factory=SequenceConfig)

    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    sequence_parallel_size: int = 1
    seed: int = 42
    # persistent XLA compilation cache dir: re-runs skip the multi-minute
    # TPU compiles. None -> fall back to $DSTPU_COMPILE_CACHE; "" -> cache
    # explicitly OFF even if the env var is set
    compile_cache_dir: Optional[str] = None
    communication_data_type: Optional[str] = None
    gradient_accumulation_dtype: Optional[str] = None
    data_efficiency: Dict[str, Any] = field(default_factory=dict)
    compression_training: Dict[str, Any] = field(default_factory=dict)
    elasticity: Dict[str, Any] = field(default_factory=dict)
    autotuning: Dict[str, Any] = field(default_factory=dict)
    raw: Dict[str, Any] = field(default_factory=dict)

    # -- derived --
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def compute_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    @property
    def loss_scale_enabled(self) -> bool:
        return self.fp16.enabled

    def print_config(self) -> None:
        logger.info(json.dumps(_dictify(self), indent=2, default=str))


def _dictify(cfg: DeepSpeedTPUConfig) -> Dict[str, Any]:
    out = {}
    for k, v in cfg.__dict__.items():
        if k == "raw":
            continue
        out[k] = v.to_dict() if isinstance(v, ConfigModel) else v
    return out


_SUBCONFIG_KEYS = {
    "optimizer": OptimizerConfig,
    "scheduler": SchedulerConfig,
    "fp16": FP16Config,
    "bf16": BF16Config,
    "bfloat16": BF16Config,  # alias used by the reference
    "zero_optimization": ZeroConfig,
    "mesh": MeshConfig,
    "tensor_parallel": TensorParallelConfig,
    "pipeline": PipelineConfig,
    "moe": MoEConfig,
    "attention": AttentionOpsConfig,
    "activation_checkpointing": ActivationCheckpointingConfig,
    "flops_profiler": FlopsProfilerConfig,
    "comms_logger": CommsLoggerConfig,
    "comms_overlap": CommsOverlapConfig,
    "profiler": ProfilerConfig,
    "tensorboard": MonitorBackendConfig,
    "wandb": MonitorBackendConfig,
    "comet": MonitorBackendConfig,
    "csv_monitor": MonitorBackendConfig,
    "jsonl_monitor": MonitorBackendConfig,
    "checkpoint": CheckpointConfig,
    "watchdog": WatchdogConfig,
    "telemetry": TelemetryConfig,
    "tuning": TuningConfig,
    "memory": MemoryConfig,
    "reliability": ReliabilityConfig,
    "aio": AIOConfig,
    "sequence": SequenceConfig,
}

_ATTR_FOR_KEY = {"zero_optimization": "zero_config", "bfloat16": "bf16"}

_SCALAR_KEYS = [
    "gradient_clipping", "prescale_gradients", "gradient_predivide_factor",
    "steps_per_print", "wall_clock_breakdown", "memory_breakdown",
    "sequence_parallel_size", "seed", "communication_data_type",
    "gradient_accumulation_dtype", "compile_cache_dir",
]

_DICT_KEYS = ["data_efficiency", "compression_training", "elasticity", "autotuning"]

# keys accepted but intentionally inert on TPU (GPU-runtime specific); kept so
# reference configs parse cleanly
_IGNORED_KEYS = {
    "amp", "zero_allow_untested_optimizer", "zero_force_ds_cpu_optimizer",
    "dump_state", "sparse_gradients", "checkpoint_tag_validation", "dataloader_drop_last",
    "use_data_before_expert_parallel_", "hybrid_engine", "data_types", "compile",
}


def parse_config(config: Union[str, Dict[str, Any], None],
                 world_size: int = 1,
                 dp_world_size: Optional[int] = None,
                 resolve_batch: bool = True) -> DeepSpeedTPUConfig:
    """JSON path / dict → :class:`DeepSpeedTPUConfig` with batch math resolved.

    ``dp_world_size`` is the size of the data-parallel axis (batch replication
    degree); defaults to ``world_size`` (pure DP).
    """
    if config is None:
        config = {}
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"config must be a dict or JSON path, got {type(config)}")

    cfg = DeepSpeedTPUConfig(raw=dict(config))
    for key, value in config.items():
        if key in _SUBCONFIG_KEYS:
            attr = _ATTR_FOR_KEY.get(key, key)
            setattr(cfg, attr, _SUBCONFIG_KEYS[key].from_dict(value))
        elif key in _SCALAR_KEYS:
            setattr(cfg, key, value)
        elif key in _DICT_KEYS:
            setattr(cfg, key, dict(value))
        elif key in (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                     C.GRADIENT_ACCUMULATION_STEPS):
            # reference configs may carry the "auto" sentinel (resolved by
            # integrations like HF) — treat as unset here
            setattr(cfg, key, 0 if is_auto(value) else int(value))
        elif key in _IGNORED_KEYS:
            logger.debug(f"config key '{key}' accepted but inert on TPU")
        else:
            logger.warning(f"Unknown top-level config key '{key}' (ignored)")

    if cfg.fp16.enabled and cfg.bf16.enabled:
        raise ValueError("fp16 and bf16 cannot both be enabled")

    dp = dp_world_size if dp_world_size is not None else world_size
    if resolve_batch:
        _resolve_batch_size(cfg, dp)
    return cfg


def _resolve_batch_size(cfg: DeepSpeedTPUConfig, dp_world_size: int) -> None:
    """Reference semantics (``runtime/config.py`` batch assertions):
    train_batch == micro_batch * gas * dp_world_size; any missing values are
    derived, all-missing defaults to micro=1, gas=1."""
    tb, mb, gas = (cfg.train_batch_size, cfg.train_micro_batch_size_per_gpu,
                   cfg.gradient_accumulation_steps)
    if tb and mb and gas:
        if tb != mb * gas * dp_world_size:
            raise ValueError(
                f"train_batch_size {tb} != micro_batch {mb} * gas {gas} * dp {dp_world_size}")
    elif tb and mb:
        if tb % (mb * dp_world_size) != 0:
            raise ValueError(f"train_batch_size {tb} not divisible by micro*dp")
        gas = tb // (mb * dp_world_size)
    elif tb and gas:
        if tb % (gas * dp_world_size) != 0:
            raise ValueError(f"train_batch_size {tb} not divisible by gas*dp")
        mb = tb // (gas * dp_world_size)
    elif mb and gas:
        tb = mb * gas * dp_world_size
    elif tb:
        mb = tb // dp_world_size
        gas = 1
        if mb * dp_world_size != tb:
            raise ValueError(f"train_batch_size {tb} not divisible by dp {dp_world_size}")
    elif mb:
        gas = 1
        tb = mb * dp_world_size
    else:
        mb, gas = 1, 1
        tb = dp_world_size
    cfg.train_batch_size = tb
    cfg.train_micro_batch_size_per_gpu = mb
    cfg.gradient_accumulation_steps = gas
