"""Misc runtime utilities — reference ``deepspeed/runtime/utils.py`` parity:
``clip_grad_norm_``, ``CheckOverflow``, ``see_memory_usage`` (re-export).

The engine does clipping/overflow inside the compiled step; these standalone
versions serve user code and tests that drive grads outside the engine
(reference-style ``tensor.backward()`` flows)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.memory import memory_stats, see_memory_usage  # noqa: F401
from .precision import grads_finite


def global_norm(tree: Any) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def clip_grad_norm_(grads: Any, max_norm: float,
                    norm: Optional[jnp.ndarray] = None
                    ) -> Tuple[Any, jnp.ndarray]:
    """Scale ``grads`` so their global norm is at most ``max_norm``
    (reference ``clip_grad_norm_``). Returns (clipped, pre-clip norm)."""
    norm = global_norm(grads) if norm is None else norm
    coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * coef, grads), norm


class CheckOverflow:
    """Reference ``CheckOverflow``: scan grads for inf/nan. Under SPMD the
    scan is already global (no cross-rank allreduce needed); tracks how many
    consecutive overflows were seen (the loss-scaler hysteresis input)."""

    def __init__(self, param_groups: Any = None):
        self.params = param_groups
        self.consecutive_overflows = 0

    def check(self, grads: Any) -> bool:
        """True if ANY grad leaf contains inf/nan."""
        overflow = not bool(grads_finite(grads))
        self.consecutive_overflows = \
            self.consecutive_overflows + 1 if overflow else 0
        return overflow

    def check_using_norm(self, norm_group: Any) -> bool:
        arr = jnp.asarray(jax.tree.leaves(norm_group))
        return not bool(jnp.all(jnp.isfinite(arr)))
