"""Data analysis + curriculum-aware sampling.

Reference parity: ``runtime/data_pipeline/data_sampling/data_analyzer.py``
(map a dataset to per-sample difficulty metrics, build index files) and
``data_sampler.py`` (``DeepSpeedDataSampler``: sample only examples whose
difficulty ≤ the current curriculum threshold). Host-side numpy — sampling
never enters the jit graph.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...utils.logging import log_dist


class DataAnalyzer:
    """Compute per-sample metrics over a dataset (reference DataAnalyzer —
    file-backed map/reduce collapsed to an in-memory pass; datasets that
    exceed memory stream through ``run_map`` in chunks)."""

    def __init__(self, dataset: Sequence,
                 metric_fns: Dict[str, Callable[[object], float]]):
        self.dataset = dataset
        self.metric_fns = metric_fns
        self.metrics: Dict[str, np.ndarray] = {}

    def run_map(self, chunk_size: int = 4096) -> Dict[str, np.ndarray]:
        vals: Dict[str, List[float]] = {m: [] for m in self.metric_fns}
        for start in range(0, len(self.dataset), chunk_size):
            for i in range(start, min(start + chunk_size, len(self.dataset))):
                sample = self.dataset[i]
                for name, fn in self.metric_fns.items():
                    vals[name].append(float(fn(sample)))
        self.metrics = {m: np.asarray(v) for m, v in vals.items()}
        return self.metrics

    def index_by_difficulty(self, metric: str) -> np.ndarray:
        """Sample indices sorted easiest → hardest."""
        if metric not in self.metrics:
            self.run_map()
        return np.argsort(self.metrics[metric], kind="stable")


class CurriculumDataSampler:
    """Batch sampler drawing only samples with difficulty ≤ threshold(step);
    threshold comes from a CurriculumScheduler (reference
    DeepSpeedDataSampler + curriculum integration)."""

    def __init__(self, difficulties: np.ndarray, batch_size: int,
                 scheduler, seed: int = 0, drop_last: bool = True):
        self.difficulties = np.asarray(difficulties)
        self.batch_size = batch_size
        self.scheduler = scheduler
        self.rng = np.random.RandomState(seed)
        self.drop_last = drop_last

    def eligible(self, global_step: int) -> np.ndarray:
        thresh = self.scheduler.get_difficulty(global_step)
        idx = np.nonzero(self.difficulties <= thresh)[0]
        if len(idx) < self.batch_size:  # always serve at least one batch
            idx = np.argsort(self.difficulties)[:self.batch_size]
        return idx

    def sample_batch(self, global_step: int) -> np.ndarray:
        idx = self.eligible(global_step)
        return self.rng.choice(idx, size=self.batch_size,
                               replace=len(idx) < self.batch_size)
