"""Data analysis + curriculum-aware sampling.

Reference parity: ``runtime/data_pipeline/data_sampling/data_analyzer.py``
(map a dataset to per-sample difficulty metrics, build index files) and
``data_sampler.py`` (``DeepSpeedDataSampler``: sample only examples whose
difficulty ≤ the current curriculum threshold). Host-side numpy — sampling
never enters the jit graph.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...utils.logging import log_dist


class DataAnalyzer:
    """Compute per-sample metrics over a dataset (reference DataAnalyzer,
    ``data_sampling/data_analyzer.py:22``): map workers each cover a
    contiguous shard of sample indices and persist per-worker index files;
    ``run_reduce`` merges them into the final metric arrays. The reference's
    file-backed map/reduce machinery stays, minus torch/mmap: numpy ``.npz``
    per worker.

    Metric types (reference :71-89):
    - ``single_value_per_sample`` — fn(sample) → scalar; yields one value per
      sample plus the sorted easiest→hardest index.
    - ``accumulate_value_over_samples`` — fn(sample) → vector; values are
      summed across samples (e.g. vocabulary histograms)."""

    def __init__(self, dataset: Sequence,
                 metric_fns: Dict[str, Callable[[object], object]],
                 metric_types: Optional[Dict[str, str]] = None,
                 save_path: Optional[str] = None,
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.metric_fns = metric_fns
        self.metric_types = metric_types or {
            m: "single_value_per_sample" for m in metric_fns}
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.metrics: Dict[str, np.ndarray] = {}

    def _worker_range(self, n: int, worker_id: int):
        per = (n + self.num_workers - 1) // self.num_workers
        return range(worker_id * per, min((worker_id + 1) * per, n))

    def _worker_file(self, worker_id: int) -> str:
        return os.path.join(self.save_path,
                            f"metrics_worker{worker_id}.npz")

    def _map_range(self, lo: int, hi: int):
        single: Dict[str, List[float]] = {}
        accum: Dict[str, np.ndarray] = {}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for name, fn in self.metric_fns.items():
                v = fn(sample)
                if self.metric_types[name] == "accumulate_value_over_samples":
                    v = np.asarray(v)
                    accum[name] = v if name not in accum else accum[name] + v
                else:
                    single.setdefault(name, []).append(float(v))
        return single, accum

    def run_map(self, num_threads: int = 1) -> Dict[str, np.ndarray]:
        """Analyze this worker's shard; persist to the worker index file when
        ``save_path`` is set. ``num_threads`` splits the shard across a
        thread pool (reference ``data_analyzer.py`` thread splitting — wins
        when the metric fns do I/O; sample ORDER is preserved on merge)."""
        idx = self._worker_range(len(self.dataset), self.worker_id)
        lo, hi = (idx.start, idx.stop) if len(idx) else (0, 0)
        if num_threads <= 1 or hi - lo < num_threads:
            single, accum = self._map_range(lo, hi)
        else:
            from concurrent.futures import ThreadPoolExecutor

            bounds = np.linspace(lo, hi, num_threads + 1).astype(int)
            with ThreadPoolExecutor(max_workers=num_threads) as pool:
                parts = list(pool.map(
                    lambda be: self._map_range(be[0], be[1]),
                    zip(bounds[:-1], bounds[1:])))
            single, accum = {}, {}
            for s_part, a_part in parts:  # in shard order
                for m, vals in s_part.items():
                    single.setdefault(m, []).extend(vals)
                for m, v in a_part.items():
                    accum[m] = v if m not in accum else accum[m] + v
        out = {m: np.asarray(v) for m, v in single.items()}
        out.update(accum)
        if self.save_path is not None:
            os.makedirs(self.save_path, exist_ok=True)
            # persist each metric's type alongside its values so run_reduce
            # does not depend on being re-constructed with matching
            # metric_types (concat-vs-sum would silently diverge)
            types = {f"__type__{m}": np.str_(self.metric_types[m])
                     for m in out}
            np.savez(self._worker_file(self.worker_id), **out, **types)
            log_dist(f"DataAnalyzer worker {self.worker_id}/"
                     f"{self.num_workers}: wrote "
                     f"{self._worker_file(self.worker_id)}")
        if self.num_workers == 1:
            self.metrics = out
        return out

    def run_reduce(self) -> Dict[str, np.ndarray]:
        """Merge all worker index files (concat per-sample metrics in worker
        order; sum accumulated metrics) → final metric arrays."""
        if self.num_workers == 1 and self.metrics:
            return self.metrics
        assert self.save_path is not None, "run_reduce needs save_path"
        missing = [w for w in range(self.num_workers)
                   if not os.path.exists(self._worker_file(w))]
        if missing:
            raise FileNotFoundError(
                f"run_reduce: missing worker index files for workers "
                f"{missing} under {self.save_path} — every worker must "
                f"run_map before any worker reduces (stale leftovers from a "
                f"different num_workers run would merge silently)")
        merged: Dict[str, np.ndarray] = {}
        for w in range(self.num_workers):
            with np.load(self._worker_file(w)) as z:
                types = {name[len("__type__"):]: str(z[name])
                         for name in z.files if name.startswith("__type__")}
                for name in z.files:
                    if name.startswith("__type__"):
                        continue
                    part = z[name]
                    mtype = types.get(name, self.metric_types.get(
                        name, "single_value_per_sample"))
                    if name not in merged:
                        merged[name] = part
                    elif mtype == "accumulate_value_over_samples":
                        merged[name] = merged[name] + part
                    else:
                        merged[name] = np.concatenate([merged[name], part])
        self.metrics = merged
        if self.save_path is not None:
            np.savez(os.path.join(self.save_path, "metrics_merged.npz"),
                     **merged)
        return merged

    def index_by_difficulty(self, metric: str) -> np.ndarray:
        """Sample indices sorted easiest → hardest."""
        if metric not in self.metrics:
            self.run_map()
            if self.num_workers > 1:
                self.run_reduce()
        return np.argsort(self.metrics[metric], kind="stable")

    # -- persisted index files (reference data_analyzer.py:72-117:
    #    {metric}_sample_to_metric + {metric}_metric_to_sample) ---------- #
    def build_indices(self, metric: str) -> Dict[str, np.ndarray]:
        """Write the reference's two per-metric index artifacts:

        - ``{metric}_sample_to_metric.npy`` — the metric value per sample
          (lookup by sample index);
        - ``{metric}_metric_to_sample.npz`` — one array of sample indices
          per distinct metric value (the curriculum difficulty buckets).
        Returns the bucket dict (key = str(metric value))."""
        assert self.save_path is not None, "build_indices needs save_path"
        if metric not in self.metrics:
            self.run_map()
            if self.num_workers > 1:
                self.run_reduce()
        values = np.asarray(self.metrics[metric])
        np.save(os.path.join(self.save_path,
                             f"{metric}_sample_to_metric.npy"), values)
        # one argsort + split: O(N log N) and immune to near-continuous
        # metrics (each distinct value still gets its bucket, but without
        # a full values==v scan per value)
        order = np.argsort(values, kind="stable")
        uniq, starts = np.unique(values[order], return_index=True)
        groups = np.split(order, starts[1:])
        buckets = {str(v): g for v, g in zip(uniq, groups)}
        np.savez(os.path.join(self.save_path,
                              f"{metric}_metric_to_sample.npz"), **buckets)
        log_dist(f"DataAnalyzer: wrote {metric}_sample_to_metric.npy + "
                 f"{metric}_metric_to_sample.npz ({len(buckets)} buckets)")
        return buckets

    @staticmethod
    def load_indices(save_path: str, metric: str):
        """Load the two index artifacts written by :meth:`build_indices`."""
        values = np.load(os.path.join(save_path,
                                      f"{metric}_sample_to_metric.npy"))
        with np.load(os.path.join(
                save_path, f"{metric}_metric_to_sample.npz")) as z:
            buckets = {k: z[k] for k in z.files}
        return values, buckets

    def run_map_reduce(self, num_threads: int = 1) -> Dict[str, np.ndarray]:
        """Map this worker's shard then merge all workers (reference
        ``run_map_reduce``). Only valid when every worker has mapped."""
        self.run_map(num_threads=num_threads)
        return self.run_reduce() if self.num_workers > 1 else self.metrics


class CurriculumDataSampler:
    """Batch sampler drawing only samples with difficulty ≤ threshold(step);
    threshold comes from a CurriculumScheduler (reference
    DeepSpeedDataSampler + curriculum integration).

    Multi-metric form (reference ``data_sampling/data_sampler.py``: the
    sampler tracks one difficulty array + scheduler PER curriculum metric
    and a sample is eligible only when EVERY metric admits it): pass dicts
    ``{metric: difficulties}`` / ``{metric: scheduler}`` with matching
    keys. Scalars remain accepted as the single-metric special case."""

    def __init__(self, difficulties, batch_size: int,
                 scheduler, seed: int = 0, drop_last: bool = True):
        if isinstance(difficulties, dict) != isinstance(scheduler, dict):
            raise ValueError("difficulties and scheduler must BOTH be "
                             "dicts (multi-metric) or both single")
        if isinstance(difficulties, dict):
            if set(difficulties) != set(scheduler):
                raise ValueError(
                    f"metric sets differ: {sorted(difficulties)} vs "
                    f"{sorted(scheduler)}")
            self.difficulties = {m: np.asarray(d)
                                 for m, d in difficulties.items()}
            lens = {m: len(d) for m, d in self.difficulties.items()}
            if len(set(lens.values())) > 1:
                raise ValueError(f"metric arrays disagree on dataset "
                                 f"size: {lens}")
            self.schedulers = dict(scheduler)
        else:
            self.difficulties = {"difficulty": np.asarray(difficulties)}
            self.schedulers = {"difficulty": scheduler}
        self.batch_size = batch_size
        self.rng = np.random.RandomState(seed)
        self.drop_last = drop_last

    def eligible(self, global_step: int) -> np.ndarray:
        n = len(next(iter(self.difficulties.values())))
        ok = np.ones(n, bool)
        for m, diff in self.difficulties.items():
            ok &= diff <= self.schedulers[m].get_difficulty(global_step)
        idx = np.nonzero(ok)[0]
        if len(idx) < self.batch_size:
            # always serve at least one batch: easiest by SUMMED rank
            # across metrics (single-metric: plain difficulty order)
            ranks = np.zeros(n)
            for diff in self.difficulties.values():
                ranks += np.argsort(np.argsort(diff, kind="stable"))
            idx = np.argsort(ranks, kind="stable")[:self.batch_size]
        return idx

    def sample_batch(self, global_step: int) -> np.ndarray:
        idx = self.eligible(global_step)
        return self.rng.choice(idx, size=self.batch_size,
                               replace=len(idx) < self.batch_size)
