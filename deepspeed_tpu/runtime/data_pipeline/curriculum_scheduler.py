"""Curriculum learning scheduler.

Reference parity: ``runtime/data_pipeline/curriculum_scheduler.py`` —
difficulty (typically sequence length) ramps with the step count under
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` schedules. The engine
truncates each batch to the current difficulty before sharding — a free perf
win on TPU because shorter padded shapes compile to their own cached jit
programs per bucket.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from ...utils.logging import log_dist


class CurriculumScheduler:
    def __init__(self, config: Dict):
        self.enabled = bool(config.get("enabled", False))
        self.min_difficulty = int(config.get("min_difficulty", 8))
        self.max_difficulty = int(config.get("max_difficulty", 1024))
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        sc = config.get("schedule_config", {})
        self.total_steps = int(sc.get("total_curriculum_step", 10000))
        self.difficulty_step = int(sc.get("difficulty_step", 8))
        self.root_degree = int(sc.get("root_degree", 2))
        self.difficulties: List[int] = [int(d) for d in sc.get("difficulty", [])]
        self.max_steps: List[int] = [int(s) for s in sc.get("max_step", [])]
        self.current_difficulty = self.min_difficulty

    def get_difficulty(self, global_steps: int) -> int:
        if not self.enabled:
            return self.max_difficulty
        t = min(max(global_steps, 0), self.total_steps)
        if self.schedule_type == "fixed_linear":
            frac = t / self.total_steps
        elif self.schedule_type == "fixed_root":
            frac = (t / self.total_steps) ** (1.0 / self.root_degree)
        elif self.schedule_type == "fixed_discrete":
            d = self.difficulties[0] if self.difficulties else self.min_difficulty
            for diff, until in zip(self.difficulties, self.max_steps + [10 ** 12]):
                d = diff
                if global_steps <= until:
                    break
            return min(d, self.max_difficulty)
        else:
            raise ValueError(f"unknown curriculum schedule {self.schedule_type}")
        d = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        # round to difficulty_step granularity (stable jit bucket shapes)
        d = int(d // self.difficulty_step * self.difficulty_step)
        return max(self.min_difficulty, min(d, self.max_difficulty))

    def update_difficulty(self, global_steps: int) -> int:
        new = self.get_difficulty(global_steps)
        if new != self.current_difficulty:
            log_dist(f"curriculum: difficulty {self.current_difficulty} → {new} "
                     f"at step {global_steps}")
            self.current_difficulty = new
        return new

    def truncate(self, batch: Dict, global_steps: int) -> Dict:
        """Clip token-like [b, s] entries to the current difficulty."""
        d = self.update_difficulty(global_steps)
        out = {}
        for k, v in batch.items():
            out[k] = v[:, :d + 1] if getattr(v, "ndim", 0) >= 2 else v
        return out
