"""Variable batch size + LR scaling schedule.

Reference parity: ``runtime/data_pipeline/variable_batch_size_and_lr.py`` —
ramp the global batch over training and scale LR with it (linear or sqrt
scaling rule). Batch sizes snap to multiples of (micro_batch × dp) so every
size maps to a whole number of accumulation steps and a cached jit program.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple


class VariableBatchSchedule:
    def __init__(self, base_batch_size: int, max_batch_size: int,
                 ramp_steps: int, base_lr: float,
                 lr_scaling: str = "linear", increment: int = 0):
        self.base = int(base_batch_size)
        self.max = int(max_batch_size)
        self.ramp_steps = max(1, int(ramp_steps))
        self.base_lr = float(base_lr)
        self.lr_scaling = lr_scaling
        self.increment = int(increment) or self.base

    def batch_size(self, step: int) -> int:
        frac = min(max(step, 0), self.ramp_steps) / self.ramp_steps
        b = self.base + frac * (self.max - self.base)
        b = int(b // self.increment * self.increment)
        return max(self.base, min(b, self.max))

    def lr(self, step: int) -> float:
        """LR scaled with the batch (linear or sqrt rule)."""
        ratio = self.batch_size(step) / self.base
        if self.lr_scaling == "linear":
            return self.base_lr * ratio
        if self.lr_scaling == "sqrt":
            return self.base_lr * math.sqrt(ratio)
        return self.base_lr

    def schedule(self, total_steps: int) -> List[Tuple[int, int, float]]:
        """(step, batch, lr) at every change point — for logging/planning."""
        out, last = [], None
        for s in range(total_steps):
            b = self.batch_size(s)
            if b != last:
                out.append((s, b, self.lr(s)))
                last = b
        return out
