from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_sampler import CurriculumDataSampler, DataAnalyzer  # noqa: F401
from .progressive_layer_drop import ProgressiveLayerDrop  # noqa: F401
from .random_ltd import RandomLTDScheduler, random_ltd_layer  # noqa: F401
from .variable_batch import VariableBatchSchedule  # noqa: F401
