"""Random layerwise token dropping (random-LTD).

Reference parity: ``runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop`` + scheduler (``data_routing/scheduler.py``) + CUDA
``token_sort``/``gather_scatter`` kernels (``csrc/random_ltd``). TPU-first:
token selection is a uniform random permutation prefix (static keep count →
static shapes under jit), gather/scatter are ``jnp.take``/``.at[].set`` —
XLA lowers these to efficient dynamic-slice/scatter on TPU, no custom kernel
needed. The scheduler ramps the kept-token count linearly, matching the
reference's seq-length schedule.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ...utils.logging import log_dist


def random_ltd_layer(layer_fn: Callable, x: jnp.ndarray, rng: jax.Array,
                     keep_tokens: int) -> jnp.ndarray:
    """Run ``layer_fn`` on a random subset of tokens; passthrough the rest.

    x: [batch, seq, hidden]; keep_tokens must be static under jit. The kept
    subset keeps its original order (sorted indices) so causal attention
    inside ``layer_fn`` stays meaningful (reference sorts sampled indices
    with token_sort.cu)."""
    b, s, h = x.shape
    if keep_tokens >= s:
        return layer_fn(x)
    perm = jax.vmap(lambda k: jax.random.permutation(k, s))(
        jax.random.split(rng, b))
    idx = jnp.sort(perm[:, :keep_tokens], axis=1)           # [b, keep]
    sub = jnp.take_along_axis(x, idx[:, :, None], axis=1)   # gather
    out = layer_fn(sub)
    return jnp.asarray(x).at[jnp.arange(b)[:, None], idx].set(out)  # scatter


class RandomLTDScheduler:
    """Ramps kept tokens from ``start`` to full seq over ``total_steps``
    (reference ``data_routing/scheduler.py`` linear schedule)."""

    def __init__(self, config: Dict):
        self.enabled = bool(config.get("enabled", False))
        sched = config.get("random_ltd_schedule", {})
        self.start = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 2048))
        self.step_size = int(sched.get("schedule_config", {}).get("seq_per_step", 16))
        self.total_steps = int(sched.get("schedule_config", {})
                               .get("require_steps", 10000))
        self.current = self.start

    def keep_tokens(self, global_steps: int, seq_len: int) -> int:
        if not self.enabled:
            return seq_len
        frac = min(max(global_steps, 0), self.total_steps) / self.total_steps
        k = self.start + frac * (self.max_value - self.start)
        k = int(k // self.step_size * self.step_size)
        return max(self.start, min(k, seq_len))

    def update(self, global_steps: int, seq_len: int) -> int:
        new = self.keep_tokens(global_steps, seq_len)
        if new != self.current:
            log_dist(f"random-ltd: keep {self.current} → {new} tokens "
                     f"at step {global_steps}")
            self.current = new
        return new
