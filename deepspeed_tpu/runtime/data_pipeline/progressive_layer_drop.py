"""Progressive layer drop (PLD).

Reference parity: ``runtime/progressive_layer_drop.py:10
ProgressiveLayerDrop`` — per-step global keep-probability theta(t) =
(1 - gamma')·exp(-gamma·t) schedule... simplified in the reference to
``theta + (1-theta)·exp(-gamma·t)`` decaying toward ``theta``; each layer i
keeps with prob ``1 - i/L · (1-theta(t))`` (deeper layers drop more). Here
the drop is a ``jnp.where`` over the scanned layer outputs — XLA executes
both branches but the *expected* compute saving of the reference is traded
for zero divergence under jit; for real step-time savings pair PLD with
``layer_reduction``. The schedule math and state dict match the reference.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self, global_step: int) -> float:
        """Keep probability at this step (reference ``get_theta``)."""
        return (1.0 - self.theta) * math.exp(-self.gamma * global_step) + self.theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.get_theta(global_step)
        return self.current_theta

    def layer_keep_probs(self, num_layers: int,
                         global_step: int) -> jnp.ndarray:
        """Per-layer keep prob: linear depth scaling i/L of the drop rate."""
        theta_t = self.get_theta(global_step)
        depth = jnp.arange(1, num_layers + 1) / num_layers
        return 1.0 - depth * (1.0 - theta_t)

    def apply_scan_block(self, block_fn, x, layer_params, rng: jax.Array,
                         keep_prob: jnp.ndarray):
        """Stochastic residual skip of one scanned block:
        x' = keep ? block(x) : x  (scaled at train time like dropout)."""
        keep = jax.random.bernoulli(rng, keep_prob)
        y = block_fn(x, layer_params)
        return jnp.where(keep, y, x)

    def state_dict(self):
        return {"theta": self.theta, "gamma": self.gamma,
                "current_theta": self.current_theta}

    def load_state_dict(self, sd):
        self.theta = sd["theta"]
        self.gamma = sd["gamma"]
        self.current_theta = sd.get("current_theta", 1.0)
