"""Universal checkpoint: topology-independent per-parameter fp32 fragments.

Reference parity: ``deepspeed/checkpoint/ds_to_universal.py`` (extract zero
shards → merge tp slices → atomic universal dir) and the runtime loader
``universal_checkpoint.py:99 load_hp_checkpoint_state``. The reference needs
an offline merge step because each rank writes its own partition file; here
sharded state is already saved globally (orbax gathers), so "conversion" is a
re-serialization into the explicit universal layout:

    <out>/universal/
        meta.json                          (step, counters, param index)
        param/<dotted.path>/fp32.npy       (full fp32 parameter)
        optim/<dotted.path>/<state>.npy    (full fp32 optimizer-state leaf)

Any (mesh, ZeRO stage, TP/PP/SP degree) can load these fragments — placement
onto the current topology is a ``jax.device_put`` with the current shardings.

**Universal checkpoint v2** (the elastic training runtime;
``docs/reliability.md`` "Elastic training & universal checkpoint"):
:func:`save_universal_checkpoint` / :func:`load_universal_checkpoint` are the
ENGINE-level entry points that make "train at N chips, resume at M chips with
a different mesh/ZeRO layout, continue the exact trajectory" a tested
guarantee. They ride PR 3's two-phase commit — staged ``<tag>.tmp.stage`` dir
+ fsync of every fragment file and parent dir + per-fragment SHA-256 (in both
``meta.json`` and a standard ``manifest.json``) + multihost barrier before
the atomic publish + ``latest`` advance — and the fragment set grows
everything a resume actually needs:

- step/token counters, skipped steps, loss-scaler state, LR-scheduler state;
- the base RNG seed, from which per-host streams are RE-DERIVED
  deterministically for the NEW topology (:func:`derive_host_rng`);
- LoCo error-feedback residuals (stored topology-free as the per-leaf SUM
  over the device dim, redistributed across the new DP world on load);
- the GAS phase (a mid-window save records it; resume restarts the window);
- a checkpointable dataloader cursor so data order fast-forwards exactly.

Loading reshards onto any (mesh shape, ZeRO stage, hpZ partition, host/NVMe
optimizer tier): placement goes through the current engine's shardings
(``Partitioner`` specs) and the ``memory/`` tier (HostBuffer leaves rebuilt
in place; NVMe masters/moments streamed back into the swap files), never
materializing more than O(largest shard) per host. Verified loads walk back
to the newest verifiable universal tag; ``checkpoint.io_retries`` backoff
applies to both directions.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist, logger
from ...utils.tree import path_to_str

UNIVERSAL_DIR = "universal"
UNIVERSAL_FORMAT = "universal2"


def _path_str(path) -> str:
    """KeyPath → dotted string ('layers.wq', 'opt.0.mu.embed', ...)."""
    return path_to_str(path, ".") or "_root"


def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", name)


def _wait_for(fn: str, timeout_s: float = 300.0) -> None:
    import time

    t0 = time.time()
    while not os.path.exists(fn):
        if time.time() - t0 > timeout_s:
            raise TimeoutError(f"rank-0 fragment file never appeared: {fn}")
        time.sleep(0.2)


def _dump_leaf(leaf, fn: str) -> None:
    """Stream one (possibly sharded) leaf to a .npy WITHOUT ever gathering it
    to host (r1 weak #6: a full device_get OOMs the host for any model that
    needed ZeRO-3). Each process memmaps the file and writes only its
    addressable replica-0 shards; host RAM stays O(largest shard)."""
    dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
    is_float = jnp.issubdtype(dtype, jnp.floating)
    target = np.float32 if is_float else np.dtype(str(dtype))
    shape = tuple(leaf.shape) if hasattr(leaf, "shape") else np.shape(leaf)
    if not hasattr(leaf, "addressable_shards"):
        # numpy / scalar / HostBuffer (tiered host residency) leaves land
        # whole — they are host-resident already
        np.save(fn, np.asarray(leaf).astype(target))
        return
    if jax.process_index() == 0:
        mm = np.lib.format.open_memmap(fn, mode="w+", dtype=target,
                                       shape=shape)
    else:  # shared FS: rank 0 creates the header, others attach
        _wait_for(fn)
        mm = None
        for _ in range(100):  # existence != complete header: retry briefly
            try:
                mm = np.lib.format.open_memmap(fn, mode="r+")
                break
            except ValueError:
                import time

                time.sleep(0.1)
        if mm is None:
            raise IOError(f"fragment header never became readable: {fn}")
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue  # exactly one writer per region
        mm[shard.index] = np.asarray(shard.data).astype(target)
    mm.flush()
    del mm


def _dump_tree(tree: Any, root: str) -> Dict[str, Dict]:
    from .manifest import _fsync_path, _sha256

    index: Dict[str, Dict] = {}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        name = _safe(_path_str(path))
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        fn = os.path.join(d, "fp32.npy")
        _dump_leaf(leaf, fn)
        # durability + integrity: fsync the fragment file and its dir entry,
        # and record the per-fragment SHA-256 so verified loads can tell a
        # complete fragment from a torn one (previously there was neither —
        # a crash after the rename could still publish un-synced bytes)
        _fsync_path(fn)
        _fsync_path(d)
        index[name] = {"shape": list(np.shape(leaf)),
                       "dtype": str(getattr(leaf, "dtype",
                                            np.asarray(leaf).dtype)),
                       "sha256": _sha256(fn),
                       "bytes": os.path.getsize(fn)}
    if flat:
        _fsync_path(root)
    return index


class _FragmentWriter:
    """The object whose ``save`` writes a fragment tree to disk — a seam the
    fault harness can patch (``faults.crash_after_save(FRAGMENT_WRITER)``
    models process death between the fragment write and the seal/publish,
    ``faults.io_errors`` exercises ``checkpoint.io_retries``)."""

    def save(self, tree: Any, root: str) -> Dict[str, Dict]:
        return _dump_tree(tree, root)


FRAGMENT_WRITER = _FragmentWriter()


def _load_tree_like(template: Any, root: str, *, place: bool = True) -> Any:
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        name = _safe(_path_str(path))
        fn = os.path.join(root, name, "fp32.npy")
        if not os.path.exists(fn):
            raise FileNotFoundError(f"universal checkpoint missing fragment {name}")
        # memmap: each device reads only ITS slice (topology-independent
        # placement without a full host copy — the reference's
        # load_hp_checkpoint_state fragment mapping, universal_checkpoint.py:99)
        arr = np.load(fn, mmap_mode="r")
        dtype = getattr(leaf, "dtype", arr.dtype)
        if arr.shape != tuple(getattr(leaf, "shape", arr.shape)):
            raise ValueError(f"fragment {name}: shape {arr.shape} != "
                             f"expected {leaf.shape}")
        sharding = getattr(leaf, "sharding", None)
        if place and sharding is not None and \
                hasattr(sharding, "addressable_devices"):
            leaves.append(jax.make_array_from_callback(
                arr.shape, sharding,
                # astype always copies -> contiguous; np.asarray (NOT
                # ascontiguousarray) keeps 0-d scalars 0-d
                lambda idx, a=arr, dt=dtype: np.asarray(a[idx]).astype(dt)))
        else:
            leaves.append(np.asarray(arr).astype(dtype))
    return jax.tree.unflatten(treedef, leaves)


def derive_host_rng(seed: int, step: int, process_index: int,
                    process_count: int) -> jax.Array:
    """Re-derive this host's RNG stream for the CURRENT topology: a pure
    function of (base seed, resume step, host index, host count), so a
    restart at ANY scale gets per-host streams that are deterministic,
    distinct per host, and independent of the topology the checkpoint was
    written on (the reference re-seeds torch generators per rank on elastic
    restart; here the fold-in chain is the whole story)."""
    key = jax.random.PRNGKey(int(seed))
    for v in (int(step), int(process_count), int(process_index)):
        key = jax.random.fold_in(key, v)
    return key


def save_universal(state, out_dir: str, *, meta: Optional[Dict] = None,
                   subdir: bool = True) -> str:
    """Write a TrainState (or any {'params':..., 'opt_state':...} mapping) as a
    universal checkpoint. Atomic: writes to a temp dir then renames.

    Multi-process (shared FS): rank 0 owns the tmp-dir lifecycle and the
    final rename; every rank writes its addressable shards, fsyncs them, and
    drops a ``.done`` marker; rank 0 renames only after all markers arrive
    AND a multihost barrier confirms every rank left the write phase (the
    ``.done`` file alone races a peer's in-flight fsync — a torn dir could
    otherwise publish). A failure on any rank GCs the staging dir instead of
    stranding it forever."""
    params = state.params if hasattr(state, "params") else state["params"]
    opt_state = state.opt_state if hasattr(state, "opt_state") else state.get("opt_state")
    out_dir = os.path.normpath(out_dir)  # trailing '/' would nest tmp in final
    final = os.path.join(out_dir, UNIVERSAL_DIR) if subdir else out_dir
    if not subdir and os.path.exists(final) and os.listdir(final):
        # a user-supplied exact target is never rmtree'd (only the
        # tool-owned 'universal/' subdir is fair game below)
        raise ValueError(f"output folder {final} exists and is not empty; "
                         f"refusing to overwrite")
    tmp = final + ".tmp"
    rank, nproc = jax.process_index(), jax.process_count()
    if rank == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
    else:
        _wait_for(tmp)
    try:
        index = {"param": FRAGMENT_WRITER.save(params,
                                               os.path.join(tmp, "param"))}
        if opt_state is not None:
            index["optim"] = FRAGMENT_WRITER.save(opt_state,
                                                  os.path.join(tmp, "optim"))
        with open(os.path.join(tmp, f".rank{rank}.done"), "w") as f:
            f.write("ok")
        if rank != 0:
            from .manifest import multihost_barrier

            multihost_barrier(f"universal_seal:{os.path.basename(final)}")
            _wait_for(final)  # rank 0 renames once everyone is done
            return final
        for r in range(1, nproc):
            _wait_for(os.path.join(tmp, f".rank{r}.done"))
        from .manifest import _fsync_path, multihost_barrier

        # all ranks must have LEFT the write phase (not just dropped their
        # marker) before the dir is sealed and renamed
        multihost_barrier(f"universal_seal:{os.path.basename(final)}")
        info = dict(meta or {})
        info["format"] = UNIVERSAL_FORMAT
        info["index"] = index
        mp = os.path.join(tmp, "meta.json")
        with open(mp, "w") as f:
            json.dump(info, f, indent=2, default=str)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_path(os.path.dirname(final))
    except Exception:
        # stage-dir GC: a straggler-rank timeout / I/O error must not strand
        # the .tmp dir forever (process death — SimulatedCrash, a
        # BaseException — can't run this, and the stage stays invisible to
        # loads either way)
        if rank == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    log_dist(f"wrote universal checkpoint {final} "
             f"({len(index['param'])} params)")
    return final


def load_universal(universal_dir: str, params_template: Any,
                   opt_state_template: Any = None,
                   *, place: bool = True) -> Tuple[Any, Any, Dict]:
    """Map fp32 fragments onto the CURRENT topology (reference
    ``universal_checkpoint.py:99``): templates supply shapes/dtypes/shardings;
    fragments are cast and device_put accordingly."""
    root = universal_dir
    if os.path.basename(root) != UNIVERSAL_DIR and \
            not os.path.isdir(os.path.join(root, "param")) and \
            os.path.isdir(os.path.join(root, UNIVERSAL_DIR)):
        root = os.path.join(root, UNIVERSAL_DIR)
    params = _load_tree_like(params_template, os.path.join(root, "param"),
                             place=place)
    opt_state = None
    if opt_state_template is not None and os.path.isdir(os.path.join(root, "optim")):
        opt_state = _load_tree_like(opt_state_template,
                                    os.path.join(root, "optim"), place=place)
    meta: Dict = {}
    mp = os.path.join(root, "meta.json")
    if os.path.exists(mp):
        with open(mp) as f:
            meta = json.load(f)
    return params, opt_state, meta


# --------------------------------------------------------------------------- #
# universal checkpoint v2 — engine-level elastic save/load
# --------------------------------------------------------------------------- #
def is_universal_tag(tag_dir: str) -> bool:
    """A tag dir written by :func:`save_universal_checkpoint` (fragment
    layout), as opposed to a regular engine checkpoint (``state/`` dir)."""
    return os.path.isdir(os.path.join(tag_dir, "param"))


def _reliability(engine, name: str, value: float = 1.0) -> None:
    tel = getattr(engine, "telemetry", None)
    if tel is not None and hasattr(tel, "reliability_event"):
        tel.reliability_event(name, value,
                              int(getattr(engine, "global_steps", 0)))


def _nvme_state_trees(engine):
    """(fp32 master params tree, AdamState-shaped opt tree) materialized from
    the NVMe swap files — the SAME fragment layout a non-NVMe adamw engine
    writes, so universal checkpoints convert freely between tiers."""
    from ...ops.optimizers import AdamState

    ps, ms, vs = engine._nvme_opt.state_leaves()
    unflat = lambda ls: jax.tree_util.tree_unflatten(  # noqa: E731
        engine._nvme_treedef, [np.asarray(l, np.float32) for l in ls])
    opt = AdamState(np.asarray(engine._nvme_opt.step_count, np.int32),
                    unflat(ms), unflat(vs))
    return unflat(ps), opt


def _engine_universal_trees(engine):
    """(params, opt_state) as dumped into fragments, normalized across the
    optimizer tiers: fp32 masters for params, the optimizer's state pytree
    for optim (HostBuffer leaves under ``optimizer_tier=host`` dump their
    host-resident numpy directly)."""
    if getattr(engine, "_nvme_opt", None) is not None:
        return _nvme_state_trees(engine)
    return engine.state.params, engine.state.opt_state


def save_universal_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                              client_state: Optional[Dict] = None,
                              reason: Optional[str] = None) -> str:
    """Elastic (topology-free) engine checkpoint, two-phase-committed.

    Protocol (shared with ``saver.py``; primitives in ``manifest.py``):
    stage fragments into ``<tag>.tmp.stage`` (fsync per fragment + dirs, GC
    on failure) → multihost barrier → seal (``manifest.json`` over the full
    dir) → atomic publish → advance ``latest``. ``checkpoint.io_retries``
    backoff wraps the whole write."""
    from .manifest import (fsync_tree, multihost_barrier, publish_dir,
                           with_io_retries, write_latest, write_manifest)

    cfg = engine.config.checkpoint
    tag = tag or f"universal_step{engine.global_steps}"
    save_dir = os.path.abspath(save_dir)
    os.makedirs(save_dir, exist_ok=True)
    final = os.path.join(save_dir, tag)
    stage = os.path.join(save_dir, f"{tag}.tmp.stage")
    rank0 = jax.process_index() == 0
    multihost = jax.process_count() > 1

    state = engine.state
    params, opt_state = _engine_universal_trees(engine)
    meta: Dict[str, Any] = {
        "format": UNIVERSAL_FORMAT,
        "global_steps": int(engine.global_steps),
        "micro_steps": int(engine.micro_steps),
        "global_tokens": int(getattr(engine, "global_tokens", 0)),
        "skipped_steps": int(np.asarray(state.skipped_steps)),
        "seed": int(engine.config.seed),
        "loss_scale": [float(np.asarray(l))
                       for l in jax.tree.leaves(state.loss_scale)],
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        # a mid-GAS-window save records the phase; the partial window's
        # staged device grads are NOT portable across topologies, so resume
        # restarts the window (documented in docs/reliability.md)
        "gas_phase": {"pending_micros": int(getattr(engine, "_pending_count",
                                                    0) or 0)},
        "topology": {
            "mesh": {k: int(v) for k, v in engine.mesh_mgr.mesh.shape.items()},
            "processes": int(jax.process_count()),
            "zero_stage": int(engine.config.zero_config.stage),
            "hpz": int(engine.config.zero_config.zero_hpz_partition_size),
            "optimizer_tier": (
                "nvme" if getattr(engine, "_nvme_opt", None) is not None
                else "host" if getattr(engine, "_tiered_opt", False)
                else "none"),
        },
        "batch": {"global": int(engine.train_batch_size()),
                  "micro": int(engine.train_micro_batch_size_per_gpu()),
                  "gas": int(engine.gradient_accumulation_steps())},
        "client_state": client_state or {},
        "config": engine.config.raw,
        "reason": reason,
    }
    loader = getattr(engine, "training_dataloader", None)
    if loader is not None and hasattr(loader, "state_dict"):
        meta["dataloader"] = loader.state_dict()
    # LoCo residuals: topology-free as the per-leaf SUM over the device dim
    # (the total un-applied quantization error); load redistributes it
    # uniformly over the new DP world
    loco = tuple(getattr(state, "loco_residual", ()) or ())
    loco_tree = {f"r{i}": jnp.sum(r, axis=0) for i, r in enumerate(loco)}

    def _write():
        if rank0:
            if os.path.isdir(stage):
                shutil.rmtree(stage)  # stale stage from a crashed earlier save
            os.makedirs(stage, exist_ok=True)
        else:
            _wait_for(stage)
        if multihost:
            multihost_barrier(f"universal_stage:{tag}")
        try:
            index = {"param": FRAGMENT_WRITER.save(
                params, os.path.join(stage, "param"))}
            if opt_state is not None and jax.tree.leaves(opt_state):
                index["optim"] = FRAGMENT_WRITER.save(
                    opt_state, os.path.join(stage, "optim"))
            if loco_tree:
                index["loco"] = FRAGMENT_WRITER.save(
                    loco_tree, os.path.join(stage, "loco"))
                meta["loco_leaves"] = len(loco)
            with open(os.path.join(stage, f".rank{jax.process_index()}.done"),
                      "w") as f:
                f.write("ok")
            if multihost:
                # every rank must have LEFT the write phase before rank 0
                # seals + renames (a .done marker alone races in-flight I/O)
                multihost_barrier(f"universal_seal:{tag}")
            if not rank0:
                _wait_for(final)
                return final
            for r in range(1, jax.process_count()):
                _wait_for(os.path.join(stage, f".rank{r}.done"))
            for name in os.listdir(stage):  # markers never publish
                if name.startswith(".rank") and name.endswith(".done"):
                    os.unlink(os.path.join(stage, name))
            meta["index"] = index
            mp = os.path.join(stage, "meta.json")
            with open(mp, "w") as f:
                json.dump(meta, f, indent=2, default=str)
                f.flush()
                os.fsync(f.fileno())
            fsync_tree(stage)
            write_manifest(stage)
            publish_dir(stage, final)
            write_latest(save_dir, tag)
        except Exception:
            # stage-dir GC on failure (Exception only: a SimulatedCrash /
            # real process death leaves the stage, which is invisible to
            # loads and reclaimed by the next save of this tag)
            if rank0:
                shutil.rmtree(stage, ignore_errors=True)
            raise
        return final

    retries = int(getattr(cfg, "io_retries", 0) or 0)
    with_io_retries(
        _write, retries=retries,
        backoff_s=float(getattr(cfg, "io_backoff_s", 0.5)),
        what=f"universal checkpoint save '{tag}'",
        on_retry=lambda n, e: _reliability(engine, "checkpoint_io_retry"))
    _reliability(engine, "elastic/saves")
    log_dist(f"saved UNIVERSAL checkpoint {final} (step "
             f"{engine.global_steps}, reason={reason or 'scheduled'})")
    return final


def _newest_universal_tag(load_dir: str, exclude=()) -> Optional[str]:
    """Walk-back target among UNIVERSAL tags: newest tag dir that has the
    fragment layout and passes manifest verification."""
    from .manifest import tag_candidates, verify_manifest

    excluded = set(exclude)
    for name in tag_candidates(load_dir):
        if name in excluded:
            continue
        full = os.path.join(load_dir, name)
        if not is_universal_tag(full):
            continue
        status, detail = verify_manifest(full)
        if status == "corrupt":
            logger.warning(f"walk-back: skipping corrupt universal "
                           f"checkpoint '{name}' ({detail})")
            continue
        return name
    return None


def _restore_opt_state(engine, path: str, meta: Dict) -> Any:
    """Load the optim fragments onto the engine's CURRENT optimizer tier."""
    from ...memory.placement import HostBuffer

    optim_root = os.path.join(path, "optim")
    if not os.path.isdir(optim_root):
        return None
    if getattr(engine, "_nvme_opt", None) is not None:
        # stream masters + moments back into the NVMe swap files; the
        # template is the ABSTRACT adamw state (fragment names match any
        # adamw engine's opt_state layout)
        tpl_params = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, jnp.float32),
            engine.state.params)
        opt_tpl = jax.eval_shape(engine.optimizer.init, tpl_params)
        opt_np = _load_tree_like(opt_tpl, optim_root, place=False)
        ps = _load_tree_like(tpl_params, os.path.join(path, "param"),
                             place=False)
        ps_leaves = jax.tree.leaves(ps)
        ms_leaves = jax.tree.leaves(opt_np.mu)
        vs_leaves = jax.tree.leaves(opt_np.nu)
        engine._nvme_opt.load_state_leaves(
            ps_leaves, ms_leaves, vs_leaves,
            step=int(np.asarray(opt_np.step)))
        return ()  # the engine's in-TrainState opt slot stays empty
    template = engine.state.opt_state
    if getattr(engine, "_tiered_opt", False):
        # host tier: rebuild the HostBuffer leaves in place (numpy residency
        # + the template's exact restore sharding) — no allocator traffic
        flat_np = _load_tree_like(template, optim_root, place=False)

        def rebuild(tpl, arr):
            if isinstance(tpl, HostBuffer):
                return HostBuffer(np.asarray(arr, tpl.dtype),
                                  tpl.memory_kind, tpl.sharding)
            return arr
        return jax.tree.map(rebuild, template, flat_np,
                            is_leaf=lambda x: isinstance(x, HostBuffer))
    return _load_tree_like(template, optim_root, place=True)


def _restore_loco(engine, path: str, meta: Dict):
    """Redistribute the saved (summed) LoCo residuals over the new DP world;
    drops them with a log when the leaf count no longer matches."""
    current = tuple(getattr(engine.state, "loco_residual", ()) or ())
    n_saved = int(meta.get("loco_leaves", 0) or 0)
    if not n_saved:
        return None
    if len(current) != n_saved:
        logger.warning(
            f"universal checkpoint carries {n_saved} LoCo residual leaves "
            f"but this engine has {len(current)} — residuals reset to zero "
            f"(error feedback re-warms within a few steps)")
        return None
    loco_root = os.path.join(path, "loco")
    tpl = {f"r{i}": jax.ShapeDtypeStruct(r.shape[1:], jnp.float32)
           for i, r in enumerate(current)}
    summed = _load_tree_like(tpl, loco_root, place=False)
    out = []
    for i, r in enumerate(current):
        world = int(r.shape[0])
        dist = np.broadcast_to(
            np.asarray(summed[f"r{i}"], np.float32) / world, r.shape)
        out.append(jax.device_put(dist, r.sharding))
    return tuple(out)


def load_universal_checkpoint(engine, load_dir: str,
                              tag: Optional[str] = None):
    """Restore an engine — at ANY topology — from a universal checkpoint tag.

    Verified load with walk-back: a corrupt (or non-universal) ``latest`` tag
    falls back to the newest verifiable universal tag instead of crashing.
    Returns ``(path, client_state)`` like ``engine.load_checkpoint``."""
    from .manifest import verify_manifest, with_io_retries
    from .saver import jnp_step, resolve_tag

    cfg = engine.config.checkpoint
    explicit = tag is not None
    try:
        tag = resolve_tag(load_dir, tag)
    except FileNotFoundError as e:
        logger.warning(str(e))
        return None, {}
    path = os.path.abspath(os.path.join(load_dir, tag))
    verify = bool(getattr(cfg, "verify_on_load", True))
    problem = None
    if not is_universal_tag(path):
        problem = "not a universal (fragment) checkpoint"
    elif verify:
        status, detail = verify_manifest(path)
        if status == "corrupt":
            problem = detail
    if problem is not None:
        logger.warning(f"universal checkpoint '{tag}' unusable ({problem}) "
                       f"— walking back to the newest verifiable universal "
                       f"tag")
        _reliability(engine, "checkpoint_rollback")
        alt = _newest_universal_tag(load_dir, exclude={tag})
        if alt is None:
            if explicit:
                raise RuntimeError(
                    f"universal checkpoint '{tag}' under {load_dir} is "
                    f"unusable ({problem}) and no verifiable universal "
                    f"fallback exists")
            logger.warning(f"no verifiable universal checkpoint under "
                           f"{load_dir} — starting fresh")
            return None, {}
        log_dist(f"universal checkpoint rollback: '{tag}' → '{alt}'")
        tag = alt
        path = os.path.abspath(os.path.join(load_dir, tag))

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)

    retries = int(getattr(cfg, "io_retries", 0) or 0)
    backoff = float(getattr(cfg, "io_backoff_s", 0.5))

    def _read():
        params = _load_tree_like(engine.state.params,
                                 os.path.join(path, "param"), place=True)
        opt_state = _restore_opt_state(engine, path, meta)
        return params, opt_state

    params, opt_state = with_io_retries(
        _read, retries=retries, backoff_s=backoff,
        what=f"universal checkpoint load '{tag}'",
        on_retry=lambda n, e: _reliability(engine, "checkpoint_io_retry"))

    rep = engine.mesh_mgr.replicated()
    small = lambda x, d: jax.device_put(np.asarray(x, d), rep)  # noqa: E731
    gstep = int(meta.get("global_steps", 0))
    ls_vals = meta.get("loss_scale")
    loss_scale = engine.state.loss_scale
    if ls_vals is not None:
        tpl_leaves = jax.tree.leaves(loss_scale)
        if len(ls_vals) == len(tpl_leaves):
            loss_scale = jax.tree.unflatten(
                jax.tree.structure(loss_scale),
                [small(v, np.asarray(t).dtype)
                 for v, t in zip(ls_vals, tpl_leaves)])
    loco = _restore_loco(engine, path, meta)
    engine.state = engine.state._replace(
        params=params,
        opt_state=(opt_state if opt_state is not None
                   else engine.state.opt_state),
        step=jnp_step(engine, gstep),
        skipped_steps=small(int(meta.get("skipped_steps", 0)),
                            np.asarray(engine.state.skipped_steps).dtype),
        loss_scale=loss_scale,
        loco_residual=(loco if loco is not None
                       else engine.state.loco_residual))
    engine.global_steps = gstep
    engine.micro_steps = int(meta.get("micro_steps", 0))
    engine.global_tokens = int(meta.get("global_tokens", 0))
    if "lr_scheduler" in meta:
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    # GAS phase: a partial accumulation window cannot be restored across
    # topologies — the window restarts (its micro grads recompute)
    pending = int(meta.get("gas_phase", {}).get("pending_micros", 0) or 0)
    if pending:
        logger.warning(f"universal checkpoint was taken mid-GAS-window "
                       f"({pending} staged micro(s)) — the window restarts "
                       f"on resume")
    engine._pending_grads = None
    engine._pending_loss = None
    engine._pending_count = 0
    engine._staged_batches = []
    # per-host RNG stream, RE-DERIVED for the new topology
    engine.host_rng = derive_host_rng(
        int(meta.get("seed", engine.config.seed)), gstep,
        jax.process_index(), jax.process_count())
    loader = getattr(engine, "training_dataloader", None)
    if loader is not None and hasattr(loader, "load_state_dict") and \
            meta.get("dataloader") is not None:
        loader.load_state_dict(meta["dataloader"])
    _reliability(engine, "elastic/resumes")
    _reliability(engine, "checkpoint_loaded")
    log_dist(f"loaded UNIVERSAL checkpoint {path} at step "
             f"{engine.global_steps}")
    return path, meta.get("client_state", {})


def ds_to_universal(ckpt_dir: str, tag: Optional[str] = None,
                    out_dir: Optional[str] = None) -> str:
    """Offline converter (reference ``ds_to_universal.py`` CLI): engine
    checkpoint → universal fragments."""
    from .saver import read_state_tree, resolve_tag

    tag = resolve_tag(ckpt_dir, tag)
    state = read_state_tree(os.path.join(ckpt_dir, tag))
    meta_path = os.path.join(ckpt_dir, tag, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = {k: v for k, v in json.load(f).items()
                    if k in ("global_steps", "micro_steps", "lr_scheduler")}
    # an explicit out_dir is honored EXACTLY (reference ds_to_universal
    # contract: fragments land at --output_folder, not a subdir of it)
    return save_universal(
        type("S", (), {"params": state["params"],
                       "opt_state": state.get("opt_state")})(),
        out_dir or os.path.join(ckpt_dir, tag), meta=meta,
        subdir=out_dir is None)


def main(argv=None) -> int:
    """``dstpu_to_universal`` CLI (reference
    ``deepspeed/checkpoint/ds_to_universal.py`` entry): engine checkpoint →
    topology-free universal fragments."""
    import argparse

    p = argparse.ArgumentParser(prog="dstpu_to_universal")
    p.add_argument("--input_folder", required=True,
                   help="checkpoint dir written by engine.save_checkpoint")
    p.add_argument("--tag", default=None)
    p.add_argument("--output_folder", default=None,
                   help="default: <input>/<tag>/universal")
    args = p.parse_args(argv)
    out = ds_to_universal(args.input_folder, tag=args.tag,
                          out_dir=args.output_folder)
    print(f"universal checkpoint written to {out}")
    return 0
