"""Universal checkpoint: topology-independent per-parameter fp32 fragments.

Reference parity: ``deepspeed/checkpoint/ds_to_universal.py`` (extract zero
shards → merge tp slices → atomic universal dir) and the runtime loader
``universal_checkpoint.py:99 load_hp_checkpoint_state``. The reference needs
an offline merge step because each rank writes its own partition file; here
sharded state is already saved globally (orbax gathers), so "conversion" is a
re-serialization into the explicit universal layout:

    <out>/universal/
        meta.json                          (step, counters, param index)
        param/<dotted.path>/fp32.npy       (full fp32 parameter)
        optim/<dotted.path>/<state>.npy    (full fp32 optimizer-state leaf)

Any (mesh, ZeRO stage, TP/PP/SP degree) can load these fragments — placement
onto the current topology is a ``jax.device_put`` with the current shardings.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist, logger
from ...utils.tree import path_to_str

UNIVERSAL_DIR = "universal"


def _path_str(path) -> str:
    """KeyPath → dotted string ('layers.wq', 'opt.0.mu.embed', ...)."""
    return path_to_str(path, ".") or "_root"


def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", name)


def _wait_for(fn: str, timeout_s: float = 300.0) -> None:
    import time

    t0 = time.time()
    while not os.path.exists(fn):
        if time.time() - t0 > timeout_s:
            raise TimeoutError(f"rank-0 fragment file never appeared: {fn}")
        time.sleep(0.2)


def _dump_leaf(leaf, fn: str) -> None:
    """Stream one (possibly sharded) leaf to a .npy WITHOUT ever gathering it
    to host (r1 weak #6: a full device_get OOMs the host for any model that
    needed ZeRO-3). Each process memmaps the file and writes only its
    addressable replica-0 shards; host RAM stays O(largest shard)."""
    dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
    is_float = jnp.issubdtype(dtype, jnp.floating)
    target = np.float32 if is_float else np.dtype(str(dtype))
    shape = tuple(leaf.shape) if hasattr(leaf, "shape") else np.shape(leaf)
    if not hasattr(leaf, "addressable_shards"):
        np.save(fn, np.asarray(leaf).astype(target))
        return
    if jax.process_index() == 0:
        mm = np.lib.format.open_memmap(fn, mode="w+", dtype=target,
                                       shape=shape)
    else:  # shared FS: rank 0 creates the header, others attach
        _wait_for(fn)
        mm = None
        for _ in range(100):  # existence != complete header: retry briefly
            try:
                mm = np.lib.format.open_memmap(fn, mode="r+")
                break
            except ValueError:
                import time

                time.sleep(0.1)
        if mm is None:
            raise IOError(f"fragment header never became readable: {fn}")
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue  # exactly one writer per region
        mm[shard.index] = np.asarray(shard.data).astype(target)
    mm.flush()
    del mm


def _dump_tree(tree: Any, root: str) -> Dict[str, Dict]:
    index: Dict[str, Dict] = {}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        name = _safe(_path_str(path))
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        _dump_leaf(leaf, os.path.join(d, "fp32.npy"))
        index[name] = {"shape": list(np.shape(leaf)),
                       "dtype": str(getattr(leaf, "dtype",
                                            np.asarray(leaf).dtype))}
    return index


def _load_tree_like(template: Any, root: str, *, place: bool = True) -> Any:
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        name = _safe(_path_str(path))
        fn = os.path.join(root, name, "fp32.npy")
        if not os.path.exists(fn):
            raise FileNotFoundError(f"universal checkpoint missing fragment {name}")
        # memmap: each device reads only ITS slice (topology-independent
        # placement without a full host copy — the reference's
        # load_hp_checkpoint_state fragment mapping, universal_checkpoint.py:99)
        arr = np.load(fn, mmap_mode="r")
        dtype = getattr(leaf, "dtype", arr.dtype)
        if arr.shape != tuple(getattr(leaf, "shape", arr.shape)):
            raise ValueError(f"fragment {name}: shape {arr.shape} != "
                             f"expected {leaf.shape}")
        if place and hasattr(leaf, "sharding"):
            leaves.append(jax.make_array_from_callback(
                arr.shape, leaf.sharding,
                # astype always copies -> contiguous; np.asarray (NOT
                # ascontiguousarray) keeps 0-d scalars 0-d
                lambda idx, a=arr, dt=dtype: np.asarray(a[idx]).astype(dt)))
        else:
            leaves.append(np.asarray(arr).astype(dtype))
    return jax.tree.unflatten(treedef, leaves)


def save_universal(state, out_dir: str, *, meta: Optional[Dict] = None,
                   subdir: bool = True) -> str:
    """Write a TrainState (or any {'params':..., 'opt_state':...} mapping) as a
    universal checkpoint. Atomic: writes to a temp dir then renames.

    Multi-process (shared FS): rank 0 owns the tmp-dir lifecycle and the
    final rename; every rank writes its addressable shards and drops a
    ``.done`` marker; rank 0 renames only after all markers arrive."""
    params = state.params if hasattr(state, "params") else state["params"]
    opt_state = state.opt_state if hasattr(state, "opt_state") else state.get("opt_state")
    out_dir = os.path.normpath(out_dir)  # trailing '/' would nest tmp in final
    final = os.path.join(out_dir, UNIVERSAL_DIR) if subdir else out_dir
    if not subdir and os.path.exists(final) and os.listdir(final):
        # a user-supplied exact target is never rmtree'd (only the
        # tool-owned 'universal/' subdir is fair game below)
        raise ValueError(f"output folder {final} exists and is not empty; "
                         f"refusing to overwrite")
    tmp = final + ".tmp"
    rank, nproc = jax.process_index(), jax.process_count()
    if rank == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
    else:
        _wait_for(tmp)
    index = {"param": _dump_tree(params, os.path.join(tmp, "param"))}
    if opt_state is not None:
        index["optim"] = _dump_tree(opt_state, os.path.join(tmp, "optim"))
    with open(os.path.join(tmp, f".rank{rank}.done"), "w") as f:
        f.write("ok")
    if rank != 0:
        _wait_for(final)  # rank 0 renames once everyone is done
        return final
    for r in range(1, nproc):
        _wait_for(os.path.join(tmp, f".rank{r}.done"))
    info = dict(meta or {})
    info["index"] = index
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(info, f, indent=2, default=str)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    log_dist(f"wrote universal checkpoint {final} "
             f"({len(index['param'])} params)")
    return final


def load_universal(universal_dir: str, params_template: Any,
                   opt_state_template: Any = None,
                   *, place: bool = True) -> Tuple[Any, Any, Dict]:
    """Map fp32 fragments onto the CURRENT topology (reference
    ``universal_checkpoint.py:99``): templates supply shapes/dtypes/shardings;
    fragments are cast and device_put accordingly."""
    root = universal_dir
    if os.path.basename(root) != UNIVERSAL_DIR and \
            os.path.isdir(os.path.join(root, UNIVERSAL_DIR)):
        root = os.path.join(root, UNIVERSAL_DIR)
    params = _load_tree_like(params_template, os.path.join(root, "param"),
                             place=place)
    opt_state = None
    if opt_state_template is not None and os.path.isdir(os.path.join(root, "optim")):
        opt_state = _load_tree_like(opt_state_template,
                                    os.path.join(root, "optim"), place=place)
    meta: Dict = {}
    mp = os.path.join(root, "meta.json")
    if os.path.exists(mp):
        with open(mp) as f:
            meta = json.load(f)
    return params, opt_state, meta


def ds_to_universal(ckpt_dir: str, tag: Optional[str] = None,
                    out_dir: Optional[str] = None) -> str:
    """Offline converter (reference ``ds_to_universal.py`` CLI): engine
    checkpoint → universal fragments."""
    from .saver import read_state_tree, resolve_tag

    tag = resolve_tag(ckpt_dir, tag)
    state = read_state_tree(os.path.join(ckpt_dir, tag))
    meta_path = os.path.join(ckpt_dir, tag, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = {k: v for k, v in json.load(f).items()
                    if k in ("global_steps", "micro_steps", "lr_scheduler")}
    # an explicit out_dir is honored EXACTLY (reference ds_to_universal
    # contract: fragments land at --output_folder, not a subdir of it)
    return save_universal(
        type("S", (), {"params": state["params"],
                       "opt_state": state.get("opt_state")})(),
        out_dir or os.path.join(ckpt_dir, tag), meta=meta,
        subdir=out_dir is None)


def main(argv=None) -> int:
    """``dstpu_to_universal`` CLI (reference
    ``deepspeed/checkpoint/ds_to_universal.py`` entry): engine checkpoint →
    topology-free universal fragments."""
    import argparse

    p = argparse.ArgumentParser(prog="dstpu_to_universal")
    p.add_argument("--input_folder", required=True,
                   help="checkpoint dir written by engine.save_checkpoint")
    p.add_argument("--tag", default=None)
    p.add_argument("--output_folder", default=None,
                   help="default: <input>/<tag>/universal")
    args = p.parse_args(argv)
    out = ds_to_universal(args.input_folder, tag=args.tag,
                          out_dir=args.output_folder)
    print(f"universal checkpoint written to {out}")
    return 0
