"""Checkpoint-engine abstraction: sync, fast (double-buffered), decoupled
(async background) writers.

Reference parity: ``runtime/checkpoint_engine/checkpoint_engine.py:21
CheckpointEngine`` and its implementations — TorchCheckpointEngine,
FastCheckpointEngine (``fast_checkpoint_engine.py`` over the double-buffered
``deepspeed/io/fast_file_writer.py``), DecoupledCheckpointEngine
(``decoupled_checkpoint_engine.py``, background-process writer committed at the
next GAS boundary ``runtime/engine.py:2797``).

TPU-first redesign: the unit of work is a *pytree snapshot*, not a torch
``state_dict`` stream. The async engine snapshots device arrays to host
(``jax.device_get`` — the TPU analog of the reference's pinned-memory staging
buffers) and hands the host tree to a writer thread; training resumes
immediately while the thread serializes. ``commit()`` is the barrier the
engine calls at the next step boundary.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ...utils.logging import log_dist, logger


class CheckpointEngine:
    """save(tree, path) / load(path) / commit(tag) — see module docstring."""

    name = "base"

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def save(self, tree: Any, path: str,
             on_durable: Optional[Callable[[], None]] = None) -> None:
        """Write ``tree`` under ``path``. ``on_durable`` is invoked exactly
        once after the bytes are durably on disk — synchronous engines call
        it before returning; the async engine calls it from the writer thread
        (so the saver's commit/publish phase stays off the training path).
        If the write fails, ``on_durable`` is never called."""
        raise NotImplementedError

    def load(self, path: str, template: Optional[Any] = None) -> Any:
        """Restore a pytree. ``template`` supplies shardings/dtypes — restoring
        onto a DIFFERENT mesh than the writer's is supported (topology-
        independent resume)."""
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Wait until the tagged save is durable (async engines); re-raises
        any background-writer failure for that tag."""
        return True

    def wait_all(self) -> None:
        """Drain every pending write (no-op for synchronous engines)."""
        return None


def _tree_to_host(tree: Any) -> Any:
    """Device → host snapshot (fast path: one batched transfer)."""
    return jax.tree.map(np.asarray, jax.device_get(tree))


class SyncCheckpointEngine(CheckpointEngine):
    """Orbax StandardCheckpointer, synchronous — the reference's
    TorchCheckpointEngine counterpart; sharding-aware parallel write."""

    name = "default"

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.StandardCheckpointer()

    def save(self, tree: Any, path: str,
             on_durable: Optional[Callable[[], None]] = None) -> None:
        self._ckptr.save(path, tree, force=True)
        self._ckptr.wait_until_finished()
        if on_durable is not None:
            on_durable()

    def load(self, path: str, template: Optional[Any] = None) -> Any:
        if template is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding)
                if hasattr(x, "sharding") else x, template)
            return self._ckptr.restore(path, abstract)
        return self._ckptr.restore(path)

    def commit(self, tag: str) -> bool:
        self._ckptr.wait_until_finished()
        return True


class FastCheckpointEngine(CheckpointEngine):
    """Chunked double-buffered writer to a temp file + atomic rename
    (reference ``deepspeed/io/fast_file_writer.py`` FastFileWriter). Host
    serialization is a flat .npz-style pickle of leaves — no torch, no orbax —
    for maximum single-file write bandwidth on local NVMe."""

    name = "fast"

    def __init__(self, buffer_mb: int = 64):
        self.buffer_bytes = buffer_mb << 20

    def save(self, tree: Any, path: str,
             on_durable: Optional[Callable[[], None]] = None) -> None:
        # multi-host: only process 0 writes (concurrent writers on shared
        # storage corrupt the file — ADVICE r1); ranks>0 skip BEFORE paying
        # the D2H snapshot. This single-file path requires fully-addressable
        # arrays + shared (or rank-0-served) storage; use the orbax engine
        # for per-shard parallel-safe multi-host writes.
        if jax.process_index() != 0:
            if on_durable is not None:
                on_durable()
            return
        host = _tree_to_host(tree)
        leaves, treedef = jax.tree.flatten(host)
        os.makedirs(path, exist_ok=True)
        tmp = os.path.join(path, f".tmp_state.{os.getpid()}.bin")
        with open(tmp, "wb", buffering=self.buffer_bytes) as f:
            header = {"treedef": pickle.dumps(treedef),
                      "leaves": [(l.shape, str(l.dtype)) for l in leaves]}
            hb = pickle.dumps(header)
            f.write(len(hb).to_bytes(8, "little"))
            f.write(hb)
            for leaf in leaves:
                f.write(np.ascontiguousarray(leaf).tobytes())
            # durable before the rename publishes it: a crash right after
            # os.replace must not expose a state.bin whose tail pages never
            # left the page cache
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, "state.bin"))
        if on_durable is not None:
            on_durable()

    def load(self, path: str, template: Optional[Any] = None) -> Any:
        fn = os.path.join(path, "state.bin")
        with open(fn, "rb", buffering=self.buffer_bytes) as f:
            n = int.from_bytes(f.read(8), "little")
            header = pickle.loads(f.read(n))
            treedef = pickle.loads(header["treedef"])
            leaves = []
            for shape, dtype in header["leaves"]:
                arr = np.frombuffer(
                    f.read(int(np.prod(shape)) * np.dtype(dtype).itemsize),
                    dtype=dtype).reshape(shape)
                leaves.append(arr)
        tree = jax.tree.unflatten(treedef, leaves)
        if template is not None:
            tree = jax.tree.map(
                lambda t, x: jax.device_put(t, x.sharding)
                if hasattr(x, "sharding") else t, tree, template)
        return tree


class DecoupledCheckpointEngine(CheckpointEngine):
    """Async engine: snapshot → background writer thread; ``commit`` joins.
    Reference ``decoupled_checkpoint_engine.py`` (background process +
    commit at the next boundary, ``runtime/engine.py:2797``)."""

    name = "async"

    def __init__(self, inner: Optional[CheckpointEngine] = None):
        self.inner = inner or FastCheckpointEngine()
        self._pending: Dict[str, threading.Thread] = {}
        self._errors: Dict[str, BaseException] = {}

    def save(self, tree: Any, path: str,
             on_durable: Optional[Callable[[], None]] = None) -> None:
        host = _tree_to_host(tree)  # blocking D2H; write is async

        def _write():
            try:
                self.inner.save(host, path)
                if on_durable is not None:
                    # two-phase commit phase 2 (manifest/publish/latest)
                    # runs HERE, in the writer thread — training never
                    # blocks on it, and a write failure above means the
                    # checkpoint is never published
                    on_durable()
            except BaseException as e:  # surfaced at commit()
                self._errors[path] = e
                logger.error(f"async checkpoint write failed: {e}")

        t = threading.Thread(target=_write, name=f"ckpt-writer:{path}",
                             daemon=True)
        self._pending[path] = t
        t.start()

    def load(self, path: str, template: Optional[Any] = None) -> Any:
        self.commit(path)
        return self.inner.load(path, template)

    def commit(self, tag: str) -> bool:
        """Finalize saves whose path IS ``tag`` or has ``tag`` as an exact
        path component (a substring match would conflate e.g. 'global_step1'
        with 'global_step10')."""
        for path, t in list(self._pending.items()):
            parts = os.path.normpath(path).split(os.sep)
            if os.path.normpath(tag) == os.path.normpath(path) or tag in parts:
                t.join()
                del self._pending[path]
                if path in self._errors:
                    raise self._errors.pop(path)
        return True

    def wait_all(self) -> None:
        for path in list(self._pending):
            self.commit(path)


def get_checkpoint_engine(name: str = "default", **kw) -> CheckpointEngine:
    """Factory (reference ``runtime/engine.py:_configure_checkpointing :1287``
    + ``model_checkpointing/writer_factory.py``)."""
    if name in ("default", "torch", "orbax", "nebula", "datastates"):
        # nebula/datastates name-parity: both reference engines are external
        # checkpoint services; the orbax engine is the durable stand-in
        return SyncCheckpointEngine()
    if name == "fast":
        return FastCheckpointEngine(buffer_mb=kw.get("writer_buffer_mb", 64))
    if name in ("async", "decoupled"):
        return DecoupledCheckpointEngine()
    raise ValueError(f"unknown checkpoint engine '{name}'")
