"""Offline consolidation of a (possibly ZeRO-sharded) checkpoint into a single
fp32 state dict — reference ``deepspeed/utils/zero_to_fp32.py`` (the recovery
script the reference copies into every checkpoint dir, ``engine.py:4181``).

On TPU the shards were already gathered at save time, so consolidation is
flatten + cast + single-file write. Output: ``.npz`` with dotted-path keys
(loadable anywhere numpy exists — no framework dependency), mirroring the
reference's ``pytorch_model.bin`` consolidation.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...utils.logging import log_dist
from .universal import _path_str, _safe


def get_fp32_state_dict_from_checkpoint(ckpt_dir: str,
                                        tag: Optional[str] = None
                                        ) -> Dict[str, np.ndarray]:
    """Reference ``get_fp32_state_dict_from_zero_checkpoint``."""
    from .saver import read_state_tree, resolve_tag

    tag = resolve_tag(ckpt_dir, tag)
    state = read_state_tree(os.path.join(ckpt_dir, tag))
    out: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state["params"])[0]:
        arr = np.asarray(jax.device_get(leaf))
        if np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(np.float32)
        out[_safe(_path_str(path))] = arr
    return out


def convert_checkpoint_to_fp32_state_dict(ckpt_dir: str, output_file: str,
                                          tag: Optional[str] = None) -> str:
    """Reference ``convert_zero_checkpoint_to_fp32_state_dict`` CLI entry."""
    sd = get_fp32_state_dict_from_checkpoint(ckpt_dir, tag)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    np.savez(output_file if output_file.endswith(".npz")
             else output_file + ".npz", **sd)
    total = sum(v.size for v in sd.values())
    log_dist(f"consolidated {len(sd)} tensors ({total/1e6:.1f}M elements) "
             f"→ {output_file}")
    return output_file


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Consolidate a deepspeed_tpu checkpoint to one fp32 .npz")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    convert_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                          args.output_file, args.tag)


if __name__ == "__main__":
    main()
