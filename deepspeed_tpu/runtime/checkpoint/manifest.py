"""Crash-consistent checkpoint plumbing (reliability subsystem).

The durable-save protocol (two-phase commit, see ``docs/reliability.md``):

1. **stage** — everything is written into ``<tag>.tmp.<pid>`` next to the
   final tag dir; a crash at any point here leaves ``latest`` untouched and
   the torn staging dir invisible to loads (staging names never match
   :func:`tag_candidates`).
2. **seal** — every staged file is fsync'd, then ``manifest.json`` (per-file
   SHA-256 + byte size) is written and fsync'd so load-time verification can
   tell a complete checkpoint from a torn one.
3. **publish** — the staging dir is atomically renamed onto the tag dir and
   only THEN is ``latest`` advanced (itself via write-tmp + fsync + rename).

This module holds the protocol's primitives — hashing/verification, fsync
helpers, atomic publish, tag scanning/walk-back, retention GC, and the
retry-with-backoff wrapper around checkpoint I/O. ``saver.py`` sequences
them; the fault-injection tests in ``tests/test_fault_tolerance.py`` attack
every step.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ...utils.logging import log_dist, logger

MANIFEST_NAME = "manifest.json"
# name fragments that mark in-flight (staging) or displaced (pre-delete) dirs;
# such dirs are never load candidates and are swept opportunistically
_STAGING_MARKERS = (".tmp.", ".old.")


def is_staging_name(name: str) -> bool:
    return any(m in name for m in _STAGING_MARKERS)


def multihost_barrier(name: str) -> None:
    """Block until every JAX process reaches this point (no-op when
    single-process). The saver runs it between the collective state write
    and rank 0's seal/publish: the orbax save has every host writing shards
    into the same staging dir, and none of them may still be writing when
    rank 0 renames it onto the tag dir."""
    import jax

    if jax.process_count() <= 1:
        return
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
    except Exception as e:  # pragma: no cover — multihost only
        logger.warning(f"multihost barrier '{name}' failed: {e}")


def _sha256(path: str, chunk: int = 1 << 22) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for blk in iter(lambda: f.read(chunk), b""):
            h.update(blk)
    return h.hexdigest()


def _fsync_path(path: str) -> None:
    """Best-effort fsync of a file OR directory (directory fsync persists the
    dir entry itself; some filesystems refuse it — never fatal)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def fsync_tree(root: str) -> None:
    """fsync every file and directory under ``root`` (phase-2 'seal': the
    manifest hashes are only meaningful if the hashed bytes are durable)."""
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            _fsync_path(os.path.join(dirpath, fn))
        _fsync_path(dirpath)


def write_manifest(tag_dir: str) -> Dict[str, object]:
    """Hash every file under ``tag_dir`` into ``manifest.json`` (write-tmp +
    fsync + atomic rename, so the manifest itself can't be torn)."""
    files: Dict[str, Dict[str, object]] = {}
    for dirpath, _dirnames, filenames in os.walk(tag_dir):
        for fn in filenames:
            if fn == MANIFEST_NAME or is_staging_name(fn):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, tag_dir).replace(os.sep, "/")
            files[rel] = {"sha256": _sha256(full),
                          "bytes": os.path.getsize(full)}
    doc = {"version": 1, "files": files}
    tmp = os.path.join(tag_dir, f"{MANIFEST_NAME}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(tag_dir, MANIFEST_NAME))
    _fsync_path(tag_dir)
    return doc


def verify_manifest(tag_dir: str) -> Tuple[str, str]:
    """Check ``tag_dir`` against its manifest → ``(status, detail)``.

    status: ``"verified"`` (every listed file exists, size + SHA-256 match),
    ``"legacy"`` (no manifest — a pre-atomic or ``atomic: false`` checkpoint;
    loadable but unverifiable), or ``"corrupt"``. Files NOT listed in the
    manifest (e.g. a ``universal/`` conversion added later) are ignored.
    """
    if not os.path.isdir(tag_dir):
        return "corrupt", "tag directory missing"
    mpath = os.path.join(tag_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return "legacy", "no manifest (pre-atomic checkpoint)"
    try:
        with open(mpath) as f:
            files = json.load(f)["files"]
        items = list(files.items())
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        return "corrupt", f"unreadable manifest: {e}"
    for rel, info in items:
        full = os.path.join(tag_dir, rel.replace("/", os.sep))
        if not os.path.exists(full):
            return "corrupt", f"missing file {rel}"
        try:
            if os.path.getsize(full) != int(info.get("bytes", -1)):
                return "corrupt", f"size mismatch for {rel}"
            if _sha256(full) != info.get("sha256"):
                return "corrupt", f"sha256 mismatch for {rel}"
        except (OSError, ValueError, TypeError) as e:
            return "corrupt", f"unreadable {rel}: {e}"
    return "verified", f"{len(items)} files verified"


def publish_dir(stage_dir: str, final_path: str) -> None:
    """Atomically move the sealed staging dir onto the tag dir. An existing
    tag dir (re-save of the same tag) is displaced to ``.old.<pid>`` first —
    never deleted before its replacement is in place — then reaped."""
    old = None
    if os.path.isdir(final_path):
        old = f"{final_path}.old.{os.getpid()}"
        if os.path.isdir(old):
            shutil.rmtree(old, ignore_errors=True)
        os.rename(final_path, old)
    os.rename(stage_dir, final_path)
    _fsync_path(os.path.dirname(final_path))
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def write_latest(save_dir: str, tag: str) -> None:
    """Advance the ``latest`` pointer durably (write-tmp + fsync + rename):
    a crash mid-update can't leave a torn/empty pointer file."""
    tmp = os.path.join(save_dir, f"latest.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(tag)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, "latest"))
    _fsync_path(save_dir)


def tag_candidates(load_dir: str) -> List[str]:
    """Checkpoint-shaped dirs under ``load_dir``, newest first — ordered by
    ``meta.json`` ``global_steps`` when readable, directory mtime otherwise.
    Staging/displaced dirs and stray files never qualify."""
    scored: List[Tuple[int, float, str]] = []
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    for name in names:
        full = os.path.join(load_dir, name)
        if not os.path.isdir(full) or is_staging_name(name):
            continue
        if not (os.path.isdir(os.path.join(full, "state"))
                or os.path.exists(os.path.join(full, "meta.json"))):
            continue
        steps = -1
        try:
            with open(os.path.join(full, "meta.json")) as f:
                steps = int(json.load(f).get("global_steps", -1))
        except (OSError, ValueError, TypeError):
            pass
        try:
            mtime = os.path.getmtime(full)
        except OSError:
            mtime = 0.0
        scored.append((steps, mtime, name))
    scored.sort(reverse=True)
    return [name for _steps, _mtime, name in scored]


def newest_verifiable_tag(load_dir: str, exclude: Iterable[str] = (),
                          verify: bool = True) -> Optional[str]:
    """Walk-back target: the newest tag under ``load_dir`` that passes
    manifest verification (legacy/no-manifest tags are accepted — they are
    loadable, just unverifiable)."""
    excluded = set(exclude)
    for name in tag_candidates(load_dir):
        if name in excluded:
            continue
        if verify:
            status, detail = verify_manifest(os.path.join(load_dir, name))
            if status == "corrupt":
                logger.warning(
                    f"walk-back: skipping corrupt checkpoint '{name}' "
                    f"({detail})")
                continue
        return name
    return None


def retention_sweep(save_dir: str, keep_last_n: int,
                    protect: Iterable[str] = ()) -> int:
    """``keep_last_n`` garbage collection: drop the oldest tag dirs beyond
    the newest N (0 = keep everything). ``protect`` tags (the one just
    written, the ``latest`` target) are never collected."""
    if keep_last_n <= 0:
        return 0
    tags = tag_candidates(save_dir)
    protected = set(protect)
    latest_path = os.path.join(save_dir, "latest")
    try:
        with open(latest_path) as f:
            protected.add(f.read().strip())
    except OSError:
        pass
    removed = 0
    for name in tags[keep_last_n:]:
        if name in protected:
            continue
        shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
        removed += 1
    if removed:
        log_dist(f"checkpoint retention: removed {removed} old tag(s), "
                 f"keeping last {keep_last_n}")
    return removed


def with_io_retries(fn: Callable[[], object], retries: int = 0,
                    backoff_s: float = 0.5, what: str = "checkpoint I/O",
                    on_retry: Optional[Callable[[int, BaseException], None]]
                    = None):
    """Run ``fn``, retrying transient ``OSError`` up to ``retries`` times
    with exponential backoff + jitter (``backoff_s * 2**attempt`` plus up to
    one extra ``backoff_s``). Non-OSError failures — including the fault
    harness's SimulatedCrash — propagate immediately."""
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            if attempt >= max(0, int(retries)):
                raise
            delay = float(backoff_s) * (2 ** attempt) + \
                random.uniform(0.0, float(backoff_s))
            attempt += 1
            logger.warning(f"{what} failed ({e}); retry {attempt}/{retries} "
                           f"in {delay:.2f}s")
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)
