from .engines import (CheckpointEngine, DecoupledCheckpointEngine,  # noqa: F401
                      FastCheckpointEngine, SyncCheckpointEngine,
                      get_checkpoint_engine)
from .manifest import (MANIFEST_NAME, newest_verifiable_tag,  # noqa: F401
                       retention_sweep, tag_candidates, verify_manifest,
                       with_io_retries, write_manifest)
from .saver import load_checkpoint, resolve_tag, save_checkpoint  # noqa: F401
from .universal import (derive_host_rng, ds_to_universal,  # noqa: F401
                        is_universal_tag, load_universal,
                        load_universal_checkpoint, save_universal,
                        save_universal_checkpoint)
from .zero_to_fp32 import (convert_checkpoint_to_fp32_state_dict,  # noqa: F401
                           get_fp32_state_dict_from_checkpoint)
