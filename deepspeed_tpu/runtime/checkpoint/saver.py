"""Checkpoint save/load (reference: ``runtime/engine.py save_checkpoint :3746 /
load_checkpoint :3398`` + checkpoint-engine selection ``:1287``).

Format: per-tag directory with the full TrainState (fp32 master params,
optimizer state, loss scaler, counters) written by the configured
:class:`CheckpointEngine` (sync orbax / fast single-file / async decoupled),
plus ``meta.json``, a per-file SHA-256 ``manifest.json``, and a ``latest``
tag file. Sharded state saves/restores in parallel from every host and can be
resharded on load — a checkpoint written on one mesh/ZeRO stage loads onto
another (the universal-checkpoint property; the explicit fragment format
lives in ``universal.py``).

Crash consistency (``checkpoint.atomic``, default on): saves stage into
``<tag>.tmp.stage`` — the name is rank-INDEPENDENT because the orbax save is
a multi-process collective where every host writes shards into the same dir —
then fsync, manifest, and an atomic rename publish the tag and only
afterwards does ``latest`` advance — a SIGTERM or I/O error at ANY point
leaves the previous checkpoint fully loadable (two-phase commit; the
protocol primitives live in ``manifest.py``, the whole thing is documented in
``docs/reliability.md`` and attacked by ``tests/test_fault_tolerance.py``).
On multi-host meshes a barrier separates the state write from rank 0's
seal/publish so no peer is still writing when the staging dir is renamed.
Loads verify the manifest (``checkpoint.verify_on_load``) and walk back to
the newest verifiable tag instead of crashing on a corrupt/missing one.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...telemetry.trace import NULL_TRACER
from ...utils.logging import log_dist, logger
from .engines import (CheckpointEngine, FastCheckpointEngine,
                      SyncCheckpointEngine, get_checkpoint_engine)
from .manifest import (multihost_barrier, newest_verifiable_tag, publish_dir,
                       retention_sweep, fsync_tree, verify_manifest,
                       with_io_retries, write_latest, write_manifest)

# Finalization (publish + latest + retention) must be serialized per save
# dir: with the async engine several saves can be in flight at once and their
# writer threads would otherwise race on `latest` and on retention rmtrees.
# `_LATEST_STEPS` additionally keeps `latest` monotonic — an OLDER save
# finalizing after a newer one must not move the pointer backwards.
_FINALIZE_MUTEX = threading.Lock()
_FINALIZE_LOCKS: Dict[str, threading.Lock] = {}
_LATEST_STEPS: Dict[str, int] = {}


def _finalize_lock(save_dir: str) -> threading.Lock:
    with _FINALIZE_MUTEX:
        lock = _FINALIZE_LOCKS.get(save_dir)
        if lock is None:
            lock = _FINALIZE_LOCKS[save_dir] = threading.Lock()
        return lock


def _reliability(engine, name: str, value: float = 1.0,
                 step: Optional[int] = None) -> None:
    """Route a ``Reliability/*`` event through the engine's TelemetryHub
    (absent on bare/test engines — then this is a no-op)."""
    tel = getattr(engine, "telemetry", None)
    if tel is not None and hasattr(tel, "reliability_event"):
        tel.reliability_event(
            name, value, step if step is not None
            else int(getattr(engine, "global_steps", 0)))


def _tracer(engine):
    """The engine's span tracer (flight recorder) — NULL_TRACER on bare/test
    engines so checkpoint spans are an unconditional one-liner."""
    tr = getattr(getattr(engine, "telemetry", None), "tracer", None)
    return tr if tr is not None else NULL_TRACER


def resolve_tag(load_dir: str, tag: Optional[str],
                scan_fallback: bool = True) -> str:
    if tag is not None:
        return tag
    latest = os.path.join(load_dir, "latest")
    if not os.path.exists(latest):
        raise FileNotFoundError(f"no 'latest' file under {load_dir}")
    with open(latest) as f:
        tag = f.read().strip()
    if scan_fallback and not os.path.isdir(os.path.join(load_dir, tag)):
        # a deleted/renamed tag must not brick resume: fall back to the
        # newest checkpoint-shaped dir actually present (verification of its
        # CONTENTS happens in load_checkpoint)
        logger.warning(f"'latest' under {load_dir} names missing tag "
                       f"'{tag}' — scanning for existing checkpoints")
        alt = newest_verifiable_tag(load_dir, exclude={tag}, verify=False)
        if alt is None:
            raise FileNotFoundError(
                f"'latest' names '{tag}' but no checkpoint directories "
                f"exist under {load_dir}")
        return alt
    return tag


def read_state_tree(tag_dir: str) -> Dict[str, Any]:
    """Load the raw state pytree from a tag dir, auto-detecting the writer
    (orbax dir vs fast single-file)."""
    state_path = os.path.join(tag_dir, "state")
    if os.path.exists(os.path.join(state_path, "state.bin")):
        return FastCheckpointEngine().load(state_path)
    return SyncCheckpointEngine().load(state_path)


def _engine_for(engine) -> CheckpointEngine:
    ce = getattr(engine, "checkpoint_engine", None)
    if ce is None:
        cfg = engine.config.checkpoint
        ce = get_checkpoint_engine(cfg.engine,
                                   writer_buffer_mb=cfg.writer_buffer_mb)
        engine.checkpoint_engine = ce
    return ce


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None) -> str:
    ce = _engine_for(engine)
    cfg = engine.config.checkpoint
    tag = tag or f"global_step{engine.global_steps}"
    save_dir = os.path.abspath(save_dir)
    final_path = os.path.join(save_dir, tag)
    atomic = bool(getattr(cfg, "atomic", True))
    # staging name is rank-INDEPENDENT: the orbax save is a multi-process
    # collective — every host must write its shards into the SAME dir (a
    # per-pid suffix would scatter shards across staging dirs and publish
    # only rank 0's)
    stage = os.path.join(save_dir, f"{tag}.tmp.stage") if atomic \
        else final_path
    rank0 = jax.process_index() == 0
    multihost = jax.process_count() > 1
    if atomic and rank0 and os.path.isdir(stage):
        shutil.rmtree(stage)  # stale staging left by a crashed earlier save
    if multihost:
        # the rmtree above must land before any peer starts writing
        multihost_barrier(f"ckpt_stage:{tag}")
    os.makedirs(stage, exist_ok=True)

    state_dict = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }

    # NVMe-streamed optimizer tier: its fp32 masters + moments live in .swp
    # files, not in state.opt_state — stream-copy them into the checkpoint
    nvme = getattr(engine, "_nvme_opt", None)
    if nvme is not None and rank0:
        nvme.save_state_files(os.path.join(stage, "nvme_optimizer"))

    meta = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "client_state": client_state or {},
        "config": engine.config.raw,
        "checkpoint_engine": ce.name,
        "framework_version": "0.1.0",
    }
    # meta lands in the STAGING dir before the state write so the async
    # engine's deferred finalize sees a complete dir to seal + publish
    if rank0:
        with open(os.path.join(stage, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)

    keep_last_n = int(getattr(cfg, "keep_last_n", 0) or 0)
    retries = int(getattr(cfg, "io_retries", 0) or 0)
    backoff_s = float(getattr(cfg, "io_backoff_s", 0.5))
    step_at_save = int(engine.global_steps)

    done = {"synced": False, "durable": False, "published": False}

    def _finalize():
        # two-phase commit, phase 2: runs only once the state bytes are
        # durable (sync engines: inline; async: in the writer thread). Until
        # the rename + latest update below, a crash leaves the previous
        # checkpoint untouched and this save invisible.
        if multihost and not done["synced"]:
            # every host must be done writing its shards before rank 0
            # seals + renames the staging dir (first attempt only — a
            # retry must not wait for peers that already left the barrier)
            multihost_barrier(f"ckpt_seal:{tag}")
            done["synced"] = True
        done["durable"] = True
        if not rank0:
            return
        # publish span may run in an async writer thread — begin/end handle
        # (the tracer ring is thread-safe); a crash mid-publish leaves only
        # the save span in the flight recorder, which is the truth
        span = _tracer(engine).begin("checkpoint/publish", cat="checkpoint",
                                     tag=tag, atomic=atomic)
        try:
            with _finalize_lock(save_dir):
                if atomic and not done["published"]:
                    fsync_tree(stage)
                    write_manifest(stage)
                    publish_dir(stage, final_path)
                done["published"] = True
                prev = _LATEST_STEPS.get(save_dir)
                if prev is None or step_at_save >= prev:
                    write_latest(save_dir, tag)
                    _LATEST_STEPS[save_dir] = step_at_save
                else:
                    logger.warning(
                        f"checkpoint '{tag}' (step {step_at_save}) finalized "
                        f"after a newer save (step {prev}) — leaving 'latest' "
                        f"on the newer tag")
                removed = retention_sweep(save_dir, keep_last_n,
                                          protect=(tag,))
        finally:
            span.end()
        if removed:
            _reliability(engine, "checkpoint_gc", value=removed,
                         step=step_at_save)
        _reliability(engine, "checkpoint_saved", step=step_at_save)
        log_dist(f"saved checkpoint {final_path} (engine={ce.name}, "
                 f"atomic={atomic})")

    state_path = os.path.join(stage, "state")

    def _write():
        if done["durable"]:
            # the state bytes landed on an earlier attempt and only the
            # publish/latest/GC tail failed — re-run just that (a second
            # ce.save would re-stage over the already-published tag)
            _finalize()
            return
        with _tracer(engine).span("checkpoint/save", cat="checkpoint",
                                  tag=tag, engine=ce.name,
                                  step=step_at_save):
            ce.save(state_dict, state_path, on_durable=_finalize)
        if retries or multihost:
            # retries: the policy needs to OBSERVE failures; multihost: the
            # seal barrier in the writer thread must not interleave with
            # training-step collectives on the main thread — either way,
            # confirm this save before returning (trading the decoupled
            # return for guaranteed delivery)
            ce.commit(state_path)

    with_io_retries(
        _write, retries=retries, backoff_s=backoff_s,
        what=f"checkpoint save '{tag}'",
        on_retry=lambda n, e: _reliability(engine, "checkpoint_io_retry",
                                           step=step_at_save))
    return final_path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_universal: Optional[bool] = None, **kw):
    ce = _engine_for(engine)
    cfg = engine.config.checkpoint
    try:
        # in-flight async saves must land before a tag is chosen — otherwise
        # 'latest' may still be mid-advance
        ce.wait_all()
    except Exception as e:
        logger.error(f"pending async checkpoint write failed: {e}")
    # re-arm the monotonic-`latest` guard: it orders concurrent in-flight
    # finalizations (all drained above) — after a restore/rollback, saves on
    # the restored (earlier-step) timeline must be able to advance `latest`
    _LATEST_STEPS.pop(os.path.abspath(load_dir), None)
    explicit_tag = tag is not None
    try:
        tag = resolve_tag(load_dir, tag)
    except FileNotFoundError as e:
        logger.warning(str(e))
        return None, {}
    path = os.path.abspath(os.path.join(load_dir, tag))

    verify = bool(getattr(cfg, "verify_on_load", True))
    if verify:
        status, detail = verify_manifest(path)
        if status == "corrupt":
            logger.warning(f"checkpoint '{tag}' failed verification "
                           f"({detail}) — walking back to the newest "
                           f"verifiable tag")
            _reliability(engine, "checkpoint_rollback")
            alt = newest_verifiable_tag(load_dir, exclude={tag}, verify=True)
            if alt is None:
                if explicit_tag:
                    raise RuntimeError(
                        f"checkpoint '{tag}' under {load_dir} is corrupt "
                        f"({detail}) and no verifiable fallback exists")
                logger.warning(f"no verifiable checkpoint under {load_dir} "
                               f"— starting fresh")
                return None, {}
            log_dist(f"checkpoint rollback: '{tag}' → '{alt}'")
            tag = alt
            path = os.path.abspath(os.path.join(load_dir, tag))

    from .universal import is_universal_tag

    if is_universal_tag(path):
        # the resolved tag is an elastic (fragment-layout) checkpoint —
        # route to the universal loader (reshards onto this topology)
        from .universal import load_universal_checkpoint

        return load_universal_checkpoint(engine, load_dir, tag=tag)

    if load_universal is None:
        load_universal = engine.config.checkpoint.load_universal
    if load_universal:
        from .universal import UNIVERSAL_DIR, load_universal as _load_uni

        params, opt_state, umeta = _load_uni(
            os.path.join(path, UNIVERSAL_DIR), engine.state.params,
            engine.state.opt_state)
        engine.state = engine.state._replace(
            params=params,
            opt_state=opt_state if opt_state is not None else engine.state.opt_state,
            step=jnp_step(engine, umeta.get("global_steps", 0)))
        engine.global_steps = int(umeta.get("global_steps", 0))
        engine.micro_steps = int(umeta.get("micro_steps", 0))
        if "lr_scheduler" in umeta:
            engine.lr_scheduler.load_state_dict(umeta["lr_scheduler"])
        log_dist(f"loaded UNIVERSAL checkpoint {path} at step {engine.global_steps}")
        return path, umeta.get("client_state", {})

    template = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }
    # restore with the CURRENT shardings — topology-independent resume: the
    # checkpoint may have been written on a different mesh/ZeRO stage
    retries = int(getattr(cfg, "io_retries", 0) or 0)
    restored = with_io_retries(
        lambda: ce.load(os.path.join(path, "state"), template),
        retries=retries, backoff_s=float(getattr(cfg, "io_backoff_s", 0.5)),
        what=f"checkpoint load '{tag}'",
        on_retry=lambda n, e: _reliability(engine, "checkpoint_io_retry"))

    # scalars (step/loss-scale) must be replicated over the CURRENT mesh —
    # a single-device committed scalar would conflict with sharded params
    rep = engine.mesh_mgr.replicated()
    small = lambda x: jax.device_put(np.asarray(x), rep)  # noqa: E731
    engine.state = engine.state._replace(
        params=restored["params"], opt_state=restored["opt_state"],
        loss_scale=jax.tree.unflatten(jax.tree.structure(engine.state.loss_scale),
                                      [small(l) for l in
                                       jax.tree.leaves(restored["loss_scale"])]),
        step=small(restored["step"]),
        skipped_steps=small(restored["skipped_steps"]))

    nvme = getattr(engine, "_nvme_opt", None)
    if nvme is not None:
        nvme_dir = os.path.join(path, "nvme_optimizer")
        if os.path.isdir(nvme_dir):
            nvme.load_state_files(nvme_dir)
        else:
            logger.warning(
                f"checkpoint {path} has no nvme_optimizer state — the "
                f"streamed masters/moments keep their current values")

    meta_path = os.path.join(path, "meta.json")
    client_state: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", int(np.asarray(restored["step"])))
        engine.micro_steps = meta.get("micro_steps", 0)
        engine.lr_scheduler.load_state_dict(meta.get("lr_scheduler", {"last_step": 0}))
        client_state = meta.get("client_state", {})
    _reliability(engine, "checkpoint_loaded")
    log_dist(f"loaded checkpoint {path} at step {engine.global_steps}")
    return path, client_state


def jnp_step(engine, step: int):
    import jax.numpy as jnp

    like = engine.state.step
    return jax.device_put(jnp.asarray(step, like.dtype), like.sharding) \
        if hasattr(like, "sharding") else jnp.asarray(step)
