"""Checkpoint save/load (reference: ``runtime/engine.py save_checkpoint :3746 /
load_checkpoint :3398`` + checkpoint-engine selection ``:1287``).

Format: per-tag directory with the full TrainState (fp32 master params,
optimizer state, loss scaler, counters) written by the configured
:class:`CheckpointEngine` (sync orbax / fast single-file / async decoupled),
plus ``meta.json`` and a ``latest`` tag file. Sharded state saves/restores in
parallel from every host and can be resharded on load — a checkpoint written
on one mesh/ZeRO stage loads onto another (the universal-checkpoint property;
the explicit fragment format lives in ``universal.py``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...utils.logging import log_dist, logger
from .engines import (CheckpointEngine, FastCheckpointEngine,
                      SyncCheckpointEngine, get_checkpoint_engine)


def resolve_tag(load_dir: str, tag: Optional[str]) -> str:
    if tag is not None:
        return tag
    latest = os.path.join(load_dir, "latest")
    if not os.path.exists(latest):
        raise FileNotFoundError(f"no 'latest' file under {load_dir}")
    with open(latest) as f:
        return f.read().strip()


def read_state_tree(tag_dir: str) -> Dict[str, Any]:
    """Load the raw state pytree from a tag dir, auto-detecting the writer
    (orbax dir vs fast single-file)."""
    state_path = os.path.join(tag_dir, "state")
    if os.path.exists(os.path.join(state_path, "state.bin")):
        return FastCheckpointEngine().load(state_path)
    return SyncCheckpointEngine().load(state_path)


def _engine_for(engine) -> CheckpointEngine:
    ce = getattr(engine, "checkpoint_engine", None)
    if ce is None:
        cfg = engine.config.checkpoint
        ce = get_checkpoint_engine(cfg.engine,
                                   writer_buffer_mb=cfg.writer_buffer_mb)
        engine.checkpoint_engine = ce
    return ce


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None) -> str:
    ce = _engine_for(engine)
    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.abspath(os.path.join(save_dir, tag))
    os.makedirs(path, exist_ok=True)

    state_dict = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }
    ce.save(state_dict, os.path.join(path, "state"))

    # NVMe-streamed optimizer tier: its fp32 masters + moments live in .swp
    # files, not in state.opt_state — stream-copy them into the checkpoint
    nvme = getattr(engine, "_nvme_opt", None)
    if nvme is not None and jax.process_index() == 0:
        nvme.save_state_files(os.path.join(path, "nvme_optimizer"))

    meta = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "client_state": client_state or {},
        "config": engine.config.raw,
        "checkpoint_engine": ce.name,
        "framework_version": "0.1.0",
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)
    log_dist(f"saved checkpoint {path} (engine={ce.name})")
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_universal: Optional[bool] = None, **kw):
    ce = _engine_for(engine)
    try:
        tag = resolve_tag(load_dir, tag)
    except FileNotFoundError:
        logger.warning(f"no 'latest' file under {load_dir}")
        return None, {}
    path = os.path.abspath(os.path.join(load_dir, tag))

    if load_universal is None:
        load_universal = engine.config.checkpoint.load_universal
    if load_universal:
        from .universal import UNIVERSAL_DIR, load_universal as _load_uni

        params, opt_state, umeta = _load_uni(
            os.path.join(path, UNIVERSAL_DIR), engine.state.params,
            engine.state.opt_state)
        engine.state = engine.state._replace(
            params=params,
            opt_state=opt_state if opt_state is not None else engine.state.opt_state,
            step=jnp_step(engine, umeta.get("global_steps", 0)))
        engine.global_steps = int(umeta.get("global_steps", 0))
        engine.micro_steps = int(umeta.get("micro_steps", 0))
        if "lr_scheduler" in umeta:
            engine.lr_scheduler.load_state_dict(umeta["lr_scheduler"])
        log_dist(f"loaded UNIVERSAL checkpoint {path} at step {engine.global_steps}")
        return path, umeta.get("client_state", {})

    template = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }
    # restore with the CURRENT shardings — topology-independent resume: the
    # checkpoint may have been written on a different mesh/ZeRO stage
    restored = ce.load(os.path.join(path, "state"), template)

    # scalars (step/loss-scale) must be replicated over the CURRENT mesh —
    # a single-device committed scalar would conflict with sharded params
    rep = engine.mesh_mgr.replicated()
    small = lambda x: jax.device_put(np.asarray(x), rep)  # noqa: E731
    engine.state = engine.state._replace(
        params=restored["params"], opt_state=restored["opt_state"],
        loss_scale=jax.tree.unflatten(jax.tree.structure(engine.state.loss_scale),
                                      [small(l) for l in
                                       jax.tree.leaves(restored["loss_scale"])]),
        step=small(restored["step"]),
        skipped_steps=small(restored["skipped_steps"]))

    nvme = getattr(engine, "_nvme_opt", None)
    if nvme is not None:
        nvme_dir = os.path.join(path, "nvme_optimizer")
        if os.path.isdir(nvme_dir):
            nvme.load_state_files(nvme_dir)
        else:
            logger.warning(
                f"checkpoint {path} has no nvme_optimizer state — the "
                f"streamed masters/moments keep their current values")

    meta_path = os.path.join(path, "meta.json")
    client_state: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", int(np.asarray(restored["step"])))
        engine.micro_steps = meta.get("micro_steps", 0)
        engine.lr_scheduler.load_state_dict(meta.get("lr_scheduler", {"last_step": 0}))
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {path} at step {engine.global_steps}")
    return path, client_state


def jnp_step(engine, step: int):
    import jax.numpy as jnp

    like = engine.state.step
    return jax.device_put(jnp.asarray(step, like.dtype), like.sharding) \
        if hasattr(like, "sharding") else jnp.asarray(step)
