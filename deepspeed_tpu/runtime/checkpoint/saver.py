"""Checkpoint save/load via Orbax (reference: ``runtime/engine.py
save_checkpoint :3746 / load_checkpoint :3398`` + checkpoint-engine abstraction
``runtime/checkpoint_engine/``).

Format: per-tag directory containing the full TrainState (params fp32 master,
optimizer state, loss scaler, counters) saved with Orbax — sharding-aware, so
ZeRO-sharded state saves/restores in parallel from every host, and can be
resharded on load (the universal-checkpoint property falls out of Orbax's
``restore_args``: a checkpoint written on one mesh loads onto another).
A ``latest`` tag file mirrors the reference's bookkeeping.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...utils.logging import log_dist, logger


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None) -> str:
    ocp = _ocp()
    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.abspath(os.path.join(save_dir, tag))
    os.makedirs(save_dir, exist_ok=True)

    ckptr = ocp.StandardCheckpointer()
    state_dict = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }
    ckptr.save(os.path.join(path, "state"), state_dict, force=True)
    ckptr.wait_until_finished()

    meta = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "client_state": client_state or {},
        "config": engine.config.raw,
        "framework_version": "0.1.0",
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)
    log_dist(f"saved checkpoint {path}")
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None):
    ocp = _ocp()
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file under {load_dir}")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(load_dir, tag))

    ckptr = ocp.StandardCheckpointer()
    template = {
        "params": engine.state.params,
        "opt_state": engine.state.opt_state,
        "loss_scale": engine.state.loss_scale,
        "step": engine.state.step,
        "skipped_steps": engine.state.skipped_steps,
    }
    # restore with the CURRENT shardings — topology-independent resume: the
    # checkpoint may have been written on a different mesh/ZeRO stage
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x, template)
    restored = ckptr.restore(os.path.join(path, "state"), abstract)

    engine.state = engine.state._replace(
        params=restored["params"], opt_state=restored["opt_state"],
        loss_scale=jax.tree.unflatten(jax.tree.structure(engine.state.loss_scale),
                                      jax.tree.leaves(restored["loss_scale"])),
        step=restored["step"], skipped_steps=restored["skipped_steps"])

    meta_path = os.path.join(path, "meta.json")
    client_state: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", int(restored["step"]))
        engine.micro_steps = meta.get("micro_steps", 0)
        engine.lr_scheduler.load_state_dict(meta.get("lr_scheduler", {"last_step": 0}))
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {path} at step {engine.global_steps}")
    return path, client_state
