"""ZenFlow: stall-free host-offload optimizer.

Reference parity: ``runtime/zenflow/zenflow_stage_1_and_2.py:47
ZenFlowZeroOptimizer`` + ``ops/adam/zenflow_cpu_adam.py`` — gradients are
split by importance: the top-k most important columns update on the
accelerator in the critical path, while the bulk of the optimizer state lives
on the CPU and updates asynchronously, overlapped with the next training
steps (bounded staleness), eliminating >85% of the GPU stall of classic
ZeRO-Offload.

TPU-first redesign:
- importance = per-leaf gradient norm share, refreshed every
  ``select_interval`` steps (reference's top-k channel selection);
- the HOT subtree updates inside the jit step on TPU (donated buffers);
- the COLD subtree's grads stream to host (one async D2H per step) and a
  worker thread runs the SIMD C++ ``DeepSpeedCPUAdam``
  (``csrc/cpu_optimizer.cpp``); refreshed weights upload every
  ``update_interval`` steps — the bounded-staleness window the reference
  calls ``zenflow_overlap``.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cpu_optimizer import DeepSpeedCPUAdam
from ..ops.optimizers import Optimizer, get_optimizer
from ..utils.logging import log_dist, logger
from ..utils.tree import path_to_str


class ZenFlowOptimizer:
    """Split hot/cold optimizer over a param pytree.

    Usage::

        zf = ZenFlowOptimizer(params, hot_fraction=0.1, lr=1e-3)
        for batch in data:
            grads = grad_fn(zf.params, batch)
            zf.step(grads)          # hot: on-device now; cold: async host
        zf.finalize()               # drain the host worker
    """

    def __init__(self, params: Any, *, lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 weight_decay: float = 0.0,
                 hot_fraction: float = 0.1,
                 select_interval: int = 50,
                 update_interval: int = 4,
                 device_optimizer: str = "adamw"):
        self.lr = lr
        self.update_interval = max(1, update_interval)
        self.select_interval = max(1, select_interval)
        self.hot_fraction = hot_fraction
        self.step_count = 0

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.paths = [path_to_str(p, ".") for p, _ in
                      jax.tree_util.tree_flatten_with_path(params)[0]]
        self.leaves: List[Any] = [jnp.asarray(l, jnp.float32) for l in leaves]
        self.n = len(self.leaves)

        self.hot_idx = self._select_hot(None)
        self.device_opt: Optimizer = get_optimizer(
            device_optimizer, lr=lr, betas=betas, weight_decay=weight_decay)
        self._rebuild_partitions(betas, weight_decay)

        self._q: "queue.Queue" = queue.Queue()
        self._results: "queue.Queue" = queue.Queue()
        self._worker = threading.Thread(target=self._cpu_loop, daemon=True,
                                        name="zenflow-cpu-adam")
        self._worker.start()
        self._inflight = 0
        log_dist(f"ZenFlow: {len(self.hot_idx)}/{self.n} hot leaves, "
                 f"update_interval={self.update_interval}")

    # ------------------------------------------------------------------ #
    def _select_hot(self, grads: Optional[List[Any]]) -> List[int]:
        """Top-k leaves by gradient-norm share (param-size share at init)."""
        k = max(1, int(self.n * self.hot_fraction))
        if grads is None:
            scores = [float(np.prod(l.shape)) for l in self.leaves]  # small=hot
        else:
            scores = [-float(jnp.linalg.norm(g)) /
                      max(float(np.prod(g.shape)) ** 0.5, 1.0) for g in grads]
        order = sorted(range(self.n), key=lambda i: scores[i])
        return sorted(order[:k])

    def _extract_moments(self):
        """Per-leaf (exp_avg, exp_avg_sq) from BOTH partitions, as numpy —
        the hand-off that survives re-selection (the reference ZenFlow
        transfers optimizer state across re-selection; discarding moments
        every select_interval changes convergence — ADVICE r1)."""
        m: Dict[int, np.ndarray] = {}
        v: Dict[int, np.ndarray] = {}
        # iterate the STATE's keys (the old hot set): by the time rebuild runs,
        # self.hot_idx already holds the new selection
        hot_state = getattr(self, "_hot_state", None)
        if hot_state is not None and hasattr(hot_state, "mu"):
            for k, arr in hot_state.mu.items():
                m[int(k)] = np.array(arr, np.float32, copy=True)
            if hasattr(hot_state, "nu"):
                for k, arr in hot_state.nu.items():
                    v[int(k)] = np.array(arr, np.float32, copy=True)
        if getattr(self, "_cpu_adam", None) is not None:
            for slot, i in enumerate(self.cold_idx):
                m[i] = self._cpu_adam.exp_avg[slot]
                v[i] = self._cpu_adam.exp_avg_sq[slot]
        return m, v

    def _rebuild_partitions(self, betas=(0.9, 0.999), weight_decay=0.0):
        self._betas, self._wd = betas, weight_decay
        m, v = self._extract_moments()
        self.cold_idx = [i for i in range(self.n) if i not in set(self.hot_idx)]
        hot_params = {str(i): self.leaves[i] for i in self.hot_idx}
        self._hot_state = self.device_opt.init(hot_params)
        # graft carried moments into the fresh device state (leaves that were
        # cold now warm-start from the host moments and vice versa)
        if m and hasattr(self._hot_state, "mu"):
            mu = {k: (jnp.asarray(m[int(k)]) if int(k) in m else z)
                  for k, z in self._hot_state.mu.items()}
            repl = {"mu": mu}
            if hasattr(self._hot_state, "nu"):
                repl["nu"] = {k: (jnp.asarray(v[int(k)]) if int(k) in v else z)
                              for k, z in self._hot_state.nu.items()}
            if hasattr(self._hot_state, "step"):
                repl["step"] = jnp.asarray(self.step_count, jnp.int32)
            self._hot_state = self._hot_state._replace(**repl)
        # cold master copies live on host, updated in place by CPU Adam —
        # MUST be real copies: np.asarray of a CPU jax array can be a
        # zero-copy view, and the worker writes in place
        self._cold_host = [np.array(self.leaves[i], np.float32, copy=True)
                           for i in self.cold_idx]
        self._cpu_adam = DeepSpeedCPUAdam(self._cold_host, lr=self.lr,
                                          betas=betas,
                                          weight_decay=weight_decay)
        if m:
            self._cpu_adam.load_state_dict({
                "step": self.step_count,
                "exp_avg": [m.get(i, np.zeros_like(self._cold_host[s]))
                            for s, i in enumerate(self.cold_idx)],
                "exp_avg_sq": [v.get(i, np.zeros_like(self._cold_host[s]))
                               for s, i in enumerate(self.cold_idx)],
            })

    # ------------------------------------------------------------------ #
    @property
    def params(self) -> Any:
        return jax.tree_util.tree_unflatten(self.treedef, self.leaves)

    def _cpu_loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            grads, lr = item
            try:
                self._cpu_adam.step(grads, lr=lr)
                self._results.put(True)
            except Exception as e:  # surfaced on next step()/finalize()
                self._results.put(e)

    def _drain(self, block: bool = False):
        while self._inflight and (block or not self._results.empty()):
            r = self._results.get()
            self._inflight -= 1
            if isinstance(r, Exception):
                raise r

    # ------------------------------------------------------------------ #
    def step(self, grads: Any, lr: Optional[float] = None) -> None:
        """One optimizer step. Hot leaves update on device immediately; cold
        gradients are queued for the async host update."""
        lr = self.lr if lr is None else lr
        g_leaves = jax.tree_util.tree_flatten(grads)[0]
        self.step_count += 1

        # ---- hot path (on device, blocking — tiny fraction of params) ----
        hot_params = {str(i): self.leaves[i] for i in self.hot_idx}
        hot_grads = {str(i): g_leaves[i] for i in self.hot_idx}
        new_hot, self._hot_state = self.device_opt.update(
            hot_params, hot_grads, self._hot_state, lr_scale=lr / self.lr)
        for i in self.hot_idx:
            self.leaves[i] = new_hot[str(i)]

        # ---- cold path (async host) ----
        self._drain()  # raise worker errors early, free queue slots
        cold = [np.array(g_leaves[i], np.float32, copy=True)
                for i in self.cold_idx]  # D2H copy (owned by the worker)
        self._q.put((cold, lr))
        self._inflight += 1

        # bounded staleness: pull refreshed cold weights periodically
        if self.step_count % self.update_interval == 0:
            self._drain(block=True)
            for slot, i in enumerate(self.cold_idx):
                self.leaves[i] = jnp.array(self._cold_host[slot])

        # periodic importance re-selection (reference select_interval)
        if self.step_count % self.select_interval == 0:
            self._drain(block=True)
            for slot, i in enumerate(self.cold_idx):
                self.leaves[i] = jnp.array(self._cold_host[slot])
            self.hot_idx = self._select_hot(g_leaves)
            self._rebuild_partitions(self._betas, self._wd)

    def finalize(self) -> Any:
        """Drain async updates and return the final params."""
        self._drain(block=True)
        for slot, i in enumerate(self.cold_idx):
            self.leaves[i] = jnp.array(self._cold_host[slot])
        return self.params

    def close(self):
        self._q.put(None)
        self._worker.join(timeout=5)
