"""Hybrid engine: train + generate in one engine (RLHF).

Reference parity: ``runtime/hybrid_engine.py:40 DeepSpeedHybridEngine`` — for
RLHF loops it flips a ZeRO-3-sharded training model into inference-kernel mode
for rollouts and back, juggling gathered/partitioned weights and inference
containers at Python runtime.

TPU-first redesign: "flipping modes" is a sharding change, so it is ONE
jit-compiled reshard — fp32 fsdp-sharded master params → bf16 TP-sharded
inference params (XLA emits the all-gathers; compiled once, reused every
rollout). The KV-cached generation path then runs on the shared inference
engine. Staleness is tracked by the train step counter, so weights re-gather
only after an actual update.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..inference.config import InferenceConfig
from ..inference.engine import InferenceEngine, ModelFamily
from ..utils.logging import log_dist
from .engine import DeepSpeedTPUEngine


class DeepSpeedHybridEngine:
    """Wrap a training engine with a weight-shared inference path.

    Usage (RLHF actor):
        hybrid = DeepSpeedHybridEngine(train_engine, llama, cfg)
        ids = hybrid.generate(prompts, max_new_tokens=64)   # rollout
        train_engine.train_batch(ppo_batch)                 # update
        ids = hybrid.generate(prompts, ...)                 # auto re-gathers
    """

    def __init__(self, engine: DeepSpeedTPUEngine, model_module, model_cfg,
                 inference_config: Optional[Dict] = None):
        self.engine = engine
        self.family = ModelFamily.from_module(model_module, model_cfg)
        inf_cfg = InferenceConfig.from_dict(inference_config or {})
        # inference shares the training mesh: TP axis if present, else
        # replicated-params generation over the data axis. Abstract params —
        # real weights arrive via the jitted reshard at first generate()
        # (no host round-trip, no throwaway HBM copy).
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            engine.state.params)
        self._inference = InferenceEngine(self.family, abstract, inf_cfg,
                                          mesh_mgr=engine.mesh_mgr)
        self._reshard = None
        self._synced_state = None
        self._in_train = True
        log_dist("hybrid engine: inference path attached "
                 f"(tp={engine.mesh_mgr.tp_world_size})")

    # ------------------------------------------------------------------ #
    def _build_reshard(self):
        from ..utils.tree import cast_floating

        shardings = self._inference.param_shardings
        dtype = self._inference.dtype
        with self.engine.mesh_mgr.activate():
            return jax.jit(lambda p: cast_floating(p, dtype),
                           out_shardings=shardings)

    def _sync_inference_params(self) -> None:
        """Re-gather train params into the inference layout if stale
        (reference: gathered-weight refresh before each rollout batch).
        Staleness = state-object identity: the engine replaces ``state``
        on every optimizer step AND on checkpoint load."""
        if self._synced_state is self.engine.state:
            return
        if self._reshard is None:
            self._reshard = self._build_reshard()
        self._inference.params = self._reshard(self.engine.state.params)
        self._synced_state = self.engine.state
        log_dist(f"hybrid engine: weights synced at step "
                 f"{self.engine.global_steps}")

    # ------------------------------------------------------------------ #
    def generate(self, prompts, **kwargs):
        """Rollout with the CURRENT training weights."""
        self._sync_inference_params()
        return self._inference.generate(prompts, **kwargs)

    def forward(self, batch):
        """Mode-dependent (reference hybrid flips containers): train mode →
        the training engine's micro-batch forward (stages grads for
        backward); eval mode → inference-kernel scoring forward."""
        if self._in_train:
            return self.engine.forward(batch)
        self._sync_inference_params()
        return self._inference.forward(batch)

    # --- training passthrough (reference keeps one engine API) --------- #
    def train_batch(self, batch):
        self._in_train = True
        return self.engine.train_batch(batch)

    def backward(self, loss=None):
        return self.engine.backward(loss)

    def step(self):
        return self.engine.step()

    def eval(self):
        self._in_train = False
        return self

    def train(self):
        self._in_train = True
        return self

    def __getattr__(self, name):
        if name == "engine":  # avoid recursion on half-built instances
            raise AttributeError(name)
        return getattr(self.engine, name)
