"""Sparse embedding-gradient communication.

Reference parity: ``runtime/engine.py:3163 sparse_allreduce`` +
``runtime/sparse_tensor.py SparseTensor`` — embedding layers flagged
``sparse_gradients`` allreduce (indices, values) pairs instead of the dense
[V, H] gradient, because one batch touches at most B*S of V rows.

TPU-first redesign: XLA needs static shapes, and a batch's embedding gradient
has a STATIC sparsity bound — exactly ``num_tokens`` rows. So the sparse
form is (tokens [N], per-token grads [N, H]) with NO dynamic compaction:
the scatter-add into [V, H] is deferred to the consumer (optimizer update),
and the cross-device reduction moves 2·N·H + N bytes instead of V·H —
a win whenever ``world · N << V`` (the reference's win condition, same math).

Use inside shard_map over the data axes (the engine's qgZ region shape), or
standalone via :func:`sparse_embedding_grad` under plain jit.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax


class SparseTensor(NamedTuple):
    """COO-ish embedding gradient (reference ``runtime/sparse_tensor.py``):
    row ``indices[i]`` accumulates ``values[i]``; duplicates allowed."""

    indices: jnp.ndarray   # [N] int32 row ids
    values: jnp.ndarray    # [N, H]
    dense_rows: int        # V (static)

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros((self.dense_rows, self.values.shape[-1]),
                        self.values.dtype)
        return out.at[self.indices].add(self.values)


def sparse_embedding_grad(table: jnp.ndarray, tokens: jnp.ndarray,
                          d_out: jnp.ndarray) -> SparseTensor:
    """The embedding lookup's backward in sparse form: tokens [...],
    d_out [..., H] (grad of the gathered rows) → SparseTensor with
    N = tokens.size rows."""
    flat_tok = tokens.reshape(-1).astype(jnp.int32)
    flat_g = d_out.reshape(-1, d_out.shape[-1])
    return SparseTensor(flat_tok, flat_g, int(table.shape[0]))


def sparse_all_reduce(st: SparseTensor,
                      axis_name: Union[str, Sequence[str]]) -> SparseTensor:
    """All-reduce in sparse form INSIDE shard_map: all-gather the (indices,
    values) pairs over the axis — every worker ends with the concatenated
    N·world rows, whose scatter-add equals the dense allreduce. Wire bytes:
    world·N·(H+1) vs V·H dense (reference sparse_allreduce semantics)."""
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    idx, vals = st.indices, st.values
    for a in axes:
        idx = lax.all_gather(idx, a, tiled=True)
        vals = lax.all_gather(vals.astype(jnp.float32), a,
                              tiled=True).astype(st.values.dtype)
    return SparseTensor(idx, vals, st.dense_rows)


def dense_grad_wins(num_tokens: int, world: int, vocab: int) -> bool:
    """The reference's crossover check: dense allreduce moves fewer bytes
    once the gathered sparse rows exceed the table."""
    return world * num_tokens >= vocab
