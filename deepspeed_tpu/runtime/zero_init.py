"""zero.Init equivalent: construct models directly sharded (> 1-chip-HBM
models never materialize unsharded anywhere).

Reference parity: ``runtime/zero/partition_parameters.py:884 zero.Init`` —
the reference monkey-patches ``nn.Module.__init__`` and tensor constructors
so every parameter is partitioned the moment it is created, plus
``GatheredParameters`` for temporary full-weight access and ``OnDevice``
(``utils/init_on_device.py``) for meta-device construction.

TPU-first redesign: construction is a *function*, so no patching is needed —
``jax.jit`` with ``out_shardings`` runs the init function ONCE, SPMD-style:
each device computes and keeps only its shard (XLA partitions the RNG work),
which is exactly the semantic the reference builds with hooks. Abstract
construction (the ``OnDevice(meta)`` analog) is ``jax.eval_shape``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..comm.mesh import MeshManager, get_mesh
from ..utils.logging import log_dist
from .partitioning import Partitioner, shapes_of


def materialize_sharded(init_fn: Callable[[jax.Array], Any], rng: jax.Array,
                        logical_axes: Any, *,
                        mesh_mgr: Optional[MeshManager] = None,
                        zero_stage: int = 3) -> Any:
    """Run ``init_fn`` under jit with ZeRO-``zero_stage`` output shardings:
    parameters are born partitioned (``zero.Init`` semantics — no full copy
    ever exists on any one device)."""
    mesh_mgr = mesh_mgr or get_mesh()
    abstract = jax.eval_shape(init_fn, rng)
    part = Partitioner(mesh_mgr, zero_stage=zero_stage,
                       tensor_parallel=mesh_mgr.tp_world_size > 1)
    specs = part.param_specs(logical_axes,
                             jax.tree.map(lambda a: a.shape, abstract))
    with mesh_mgr.activate():
        params = jax.jit(init_fn,
                         out_shardings=part.shardings(specs))(rng)
    n = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(abstract))
    log_dist(f"zero.Init: materialized {n/1e6:.1f}M params directly sharded "
             f"(stage {zero_stage})")
    return params


class Init(contextlib.AbstractContextManager):
    """Context-manager API shape of the reference ``zero.Init``. Inside the
    context, call :meth:`materialize` (explicit — JAX has no implicit module
    construction to hook)::

        with dst.zero.Init(config_dict_or_path=cfg) as zi:
            params = zi.materialize(init_fn, rng, logical_axes)
    """

    def __init__(self, config_dict_or_path: Any = None,
                 mesh_mgr: Optional[MeshManager] = None, **kw):
        from .config import parse_config

        stage = 3
        if config_dict_or_path is not None:
            cfg = parse_config(config_dict_or_path)
            stage = cfg.zero_config.stage
        self.zero_stage = stage
        self.mesh_mgr = mesh_mgr

    def __exit__(self, *exc):
        return False

    def materialize(self, init_fn, rng, logical_axes):
        return materialize_sharded(init_fn, rng, logical_axes,
                                   mesh_mgr=self.mesh_mgr,
                                   zero_stage=self.zero_stage)


@contextlib.contextmanager
def GatheredParameters(params: Any, modifier_rank: Optional[int] = None):
    """Temporary full (host) view of sharded params (reference
    ``GatheredParameters``): yields a gathered numpy pytree; device state is
    unchanged (JAX params are immutable — mutate-and-rescatter flows should
    instead device_put the edited tree back with the original shardings)."""
    gathered = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    yield gathered


def on_device(init_fn: Callable, rng: jax.Array) -> Any:
    """Meta-device construction (reference ``OnDevice`` ``init_on_device.py``):
    abstract shapes/dtypes only, zero bytes allocated."""
    return jax.eval_shape(init_fn, rng)


def tp_model_init(model_spec, tp_size: int, dtype=None, *,
                  mesh_mgr: Optional[MeshManager] = None, rng=None):
    """Reference ``deepspeed.tp_model_init`` (``deepspeed/__init__.py:391``):
    materialize a model's params already TP-sharded over a tensor axis."""
    from ..comm.mesh import init_mesh

    if mesh_mgr is None:
        n = len(jax.devices())
        if n % tp_size:
            raise ValueError(f"tp_size {tp_size} incompatible with {n} devices")
        mesh_mgr = init_mesh({"tensor": tp_size, "data": n // tp_size})
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_fn = model_spec.init_fn
    if dtype is not None:
        from ..utils.tree import cast_floating

        init_fn = lambda r: cast_floating(model_spec.init_fn(r), dtype)  # noqa: E731
    return materialize_sharded(init_fn, rng,
                               model_spec.logical_axes, mesh_mgr=mesh_mgr,
                               zero_stage=0)
