"""NVMe tensor swapping — the ZeRO-Infinity disk tier.

Capability parity with the reference's ``runtime/swap_tensor/`` stack
(``AsyncPartitionedParameterSwapper`` ``partitioned_param_swapper.py:37``,
``PartitionedOptimizerSwapper`` ``partitioned_optimizer_swapper.py:27``,
``PipelinedOptimizerSwapper`` ``pipelined_optimizer_swapper.py:52``): spill
state tensors to fast local storage and stream them back ahead of use, so the
trainable model size is bounded by disk, not HBM+RAM.

TPU-first shape: swapping operates on *pytrees* (the opt_state / param trees
the jit step consumes), not on hooked torch tensors. Leaves are written
through the async C++ aio engine (``csrc/aio.cpp``), and reads for the next
step can be issued early (``start_swap_in``) to overlap disk I/O with the
TPU step — the same overlap the reference gets from its aio thread pool.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...ops.aio import AIOHandle
from ...utils.logging import log_dist, logger


@dataclass
class SwappedTensorMeta:
    path: str
    shape: Tuple[int, ...]
    dtype: str

    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64) *
                   np.dtype(self.dtype).itemsize) if self.shape else \
            np.dtype(self.dtype).itemsize


def _leaf_name(path) -> str:
    from ...utils.tree import path_to_str

    return path_to_str(path, "_") or "leaf"


class AsyncTensorSwapper:
    """Low-level named-buffer swapper (reference ``AsyncTensorSwapper`` in
    ``partitioned_optimizer_swapper.py``)."""

    def __init__(self, swap_dir: str, aio_handle: Optional[AIOHandle] = None,
                 block_size: int = 1 << 20, num_threads: int = 4):
        self.swap_dir = os.path.abspath(swap_dir)
        os.makedirs(self.swap_dir, exist_ok=True)
        self.aio = aio_handle or AIOHandle(block_size=block_size,
                                           num_threads=num_threads)
        self._pending_bufs: List[Tuple[np.ndarray, SwappedTensorMeta]] = []

    def _path(self, name: str) -> str:
        return os.path.join(self.swap_dir, f"{name}.swp")

    def swap_out(self, name: str, array: np.ndarray) -> SwappedTensorMeta:
        array = np.ascontiguousarray(array)
        meta = SwappedTensorMeta(self._path(name), tuple(array.shape),
                                 str(array.dtype))
        self.aio.pwrite(array, meta.path)
        # keep the buffer alive until wait(); numpy owns it, the caller's
        # reference does — the handle only sees the raw pointer
        self._pending_bufs.append((array, meta))
        return meta

    def start_swap_in(self, meta: SwappedTensorMeta) -> np.ndarray:
        buf = np.empty(meta.shape, np.dtype(meta.dtype))
        self.aio.pread(buf, meta.path)
        return buf

    def wait(self) -> None:
        errs = self.aio.wait()
        self._pending_bufs.clear()
        if errs:
            raise IOError(f"{errs} swap I/O requests failed under "
                          f"{self.swap_dir}")

    def remove(self, meta: SwappedTensorMeta) -> None:
        try:
            os.remove(meta.path)
        except FileNotFoundError:
            pass


class PartitionedOptimizerSwapper:
    """Pytree-level optimizer-state swapper (reference
    ``PartitionedOptimizerSwapper`` ``partitioned_optimizer_swapper.py:27`` +
    pipelined variant :52 — the overlap comes from issuing ``start_swap_in``
    before the consuming step and ``wait()`` just in time).
    """

    def __init__(self, swap_dir: str, **kw):
        self.swapper = AsyncTensorSwapper(swap_dir, **kw)
        self._metas: Optional[Any] = None        # pytree of SwappedTensorMeta
        self._inflight: Optional[Any] = None     # pytree of filling buffers

    @property
    def swapped_out(self) -> bool:
        return self._metas is not None

    def swap_out_optimizer(self, opt_state: Any) -> Any:
        """Write every array leaf to disk; returns the meta tree. The caller
        should drop its reference to the live tree afterwards."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
        metas = []
        for i, (path, leaf) in enumerate(flat):
            arr = np.asarray(leaf)
            # leading index guarantees uniqueness (joined path names can
            # collide, e.g. ('a','b_c') vs ('a_b','c'))
            metas.append(self.swapper.swap_out(
                f"{i:05d}_{_leaf_name(path)}", arr))
        self.swapper.wait()
        self._metas = jax.tree_util.tree_unflatten(treedef, metas)
        log_dist(f"swapped {len(metas)} optimizer tensors -> "
                 f"{self.swapper.swap_dir}")
        return self._metas

    def start_swap_in(self) -> None:
        """Issue async reads for all leaves (call while the TPU computes)."""
        assert self._metas is not None, "nothing swapped out"
        self._inflight = jax.tree.map(
            self.swapper.start_swap_in, self._metas,
            is_leaf=lambda x: isinstance(x, SwappedTensorMeta))

    def swap_in_optimizer(self, device_put: bool = True) -> Any:
        """Drain reads, return the restored tree (optionally on device)."""
        if self._inflight is None:
            self.start_swap_in()
        self.swapper.wait()
        tree = self._inflight
        self._inflight = None
        if device_put:
            tree = jax.tree.map(jax.device_put, tree)
        return tree

    def purge(self) -> None:
        if self._metas is not None:
            jax.tree.map(self.swapper.remove, self._metas,
                         is_leaf=lambda x: isinstance(x, SwappedTensorMeta))
            self._metas = None
