"""NVMe-STREAMED optimizer step — ZeRO-Infinity's disk-resident optimizer.

Reference parity: ``runtime/zero/stage3.py:2412`` (the stage-3 step walks
parameter SUB-GROUPS: swap state in → update → swap out, so optimizer state
larger than host RAM trains), ``stage3.py:679 _configure_tensor_swapping``,
``swap_tensor/partitioned_optimizer_swapper.py:27`` and the overlapped
``pipelined_optimizer_swapper.py:52``.

TPU-first shape: the device jit computes gradients; the optimizer tier runs
on HOST over fp32 master + moment buffers that live on NVMe, streamed per
sub-group through the async C++ aio engine (``csrc/aio.cpp``):

- two ping-pong READ handles prefetch sub-group i+1's state while the SIMD
  Adam kernel updates sub-group i (the pipelined swapper's overlap);
- a WRITE handle drains group i's updated state during group i+1's update;
- peak host residency is O(3 sub-groups), bounded regardless of model size,
  and tracked (``peak_resident_bytes``) so tests can pin it.

The updated bf16 compute copy per leaf is the only full-model-sized output —
exactly the bytes that must reach the device anyway.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...ops.aio import AIOHandle
from ...ops.cpu_optimizer import adam_step_buffers, fp32_to_bf16
from ...utils.logging import log_dist


class _GroupMeta:
    """Per-sub-group NVMe residency: one file per (kind, leaf)."""

    def __init__(self, swap_dir: str, gid: int, leaf_ids: List[int],
                 shapes: List[Tuple[int, ...]]):
        self.leaf_ids = leaf_ids
        self.shapes = shapes
        self.nbytes = sum(int(np.prod(s or (1,))) * 4 for s in shapes) * 3
        self.paths = {
            kind: [os.path.join(swap_dir, f"g{gid:04d}_{kind}_{i}.swp")
                   for i in leaf_ids]
            for kind in ("p", "m", "v")}


class NVMeStreamingOptimizer:
    """AdamW whose fp32 masters + moments live on NVMe, streamed per
    sub-group through the aio engine (see module docstring).

    ``params``: list of numpy fp32 arrays (the initial master values; NOT
    retained — state goes straight to disk group by group).
    ``sub_group_size``: max elements per sub-group (reference zero config
    ``sub_group_size``, stage3.py:679 carving).
    """

    def __init__(self, params: Sequence[np.ndarray], swap_dir: str, *,
                 lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 sub_group_size: int = 1 << 22,
                 aio_block_size: int = 1 << 20, aio_threads: int = 4):
        self.swap_dir = os.path.abspath(swap_dir)
        os.makedirs(self.swap_dir, exist_ok=True)
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay, self.adamw_mode = weight_decay, adamw_mode
        self.step_count = 0
        self.shapes = [tuple(p.shape) for p in params]
        self._read_h = [AIOHandle(block_size=aio_block_size,
                                  num_threads=aio_threads) for _ in range(2)]
        self._write_h = AIOHandle(block_size=aio_block_size,
                                  num_threads=aio_threads)

        # ---- carve sub-groups (stage3.py:679) ----
        self.groups: List[_GroupMeta] = []
        ids, shapes, elems = [], [], 0
        for i, p in enumerate(params):
            if ids and elems + p.size > sub_group_size:
                self.groups.append(_GroupMeta(self.swap_dir, len(self.groups),
                                              ids, shapes))
                ids, shapes, elems = [], [], 0
            ids.append(i)
            shapes.append(tuple(p.shape))
            elems += p.size
        if ids:
            self.groups.append(_GroupMeta(self.swap_dir, len(self.groups),
                                          ids, shapes))

        # ---- residency accounting ----
        self._resident = 0
        self.peak_resident_bytes = 0

        # ---- initial state → NVMe, one group at a time (the fp32 host
        # conversion happens INSIDE the loop so init is bounded too — the
        # caller may pass device arrays or non-fp32 leaves without ever
        # materializing a full duplicate fp32 copy) ----
        for g in self.groups:
            bufs = {"p": [np.ascontiguousarray(np.asarray(params[i]),
                                               np.float32)
                          for i in g.leaf_ids],
                    "m": [np.zeros(s, np.float32) for s in g.shapes],
                    "v": [np.zeros(s, np.float32) for s in g.shapes]}
            self._track(+g.nbytes)
            self._issue_write(g, bufs)
            self._drain_writes()
            self._track(-g.nbytes)
        log_dist(
            f"NVMeStreamingOptimizer: {len(self.shapes)} leaves in "
            f"{len(self.groups)} sub-groups "
            f"({sum(g.nbytes for g in self.groups) / 2**20:.1f} MiB fp32 "
            f"state) -> {self.swap_dir}")

    # ------------------------------------------------------------------ #
    def _track(self, delta: int) -> None:
        self._resident += delta
        self.peak_resident_bytes = max(self.peak_resident_bytes,
                                       self._resident)

    def _issue_read(self, handle: AIOHandle, g: _GroupMeta) -> Dict[str, list]:
        bufs = {kind: [np.empty(s, np.float32) for s in g.shapes]
                for kind in ("p", "m", "v")}
        self._track(+g.nbytes)
        for kind in ("p", "m", "v"):
            for buf, path in zip(bufs[kind], g.paths[kind]):
                handle.pread(buf, path)
        return bufs

    def _issue_write(self, g: _GroupMeta, bufs: Dict[str, list]) -> None:
        for kind in ("p", "m", "v"):
            for buf, path in zip(bufs[kind], g.paths[kind]):
                self._write_h.pwrite(buf, path)
        self._pending_write = (g, bufs)  # keep alive until drained

    def _drain_writes(self) -> None:
        errs = self._write_h.wait()
        if errs:
            raise IOError(f"{errs} NVMe write(s) failed in {self.swap_dir}")
        self._pending_write = None

    # ------------------------------------------------------------------ #
    def step(self, grads: Sequence[np.ndarray], lr: Optional[float] = None,
             out_dtype: str = "bfloat16",
             on_group: Optional[Callable[[List[int], List[np.ndarray]], None]]
             = None) -> List[np.ndarray]:
        """One streamed optimizer step. ``grads``: one fp32 numpy array per
        leaf (same order as the init params). Returns the updated compute
        copies — bf16 uint16 bit-pattern arrays by default (view them as
        bfloat16 on device), or fp32 copies with ``out_dtype='float32'``.

        ``on_group(leaf_ids, out_leaves)`` fires the moment a sub-group's
        update is done — BEFORE the next group's read-wait and Adam — so the
        caller can dispatch async H2D transfers of finished sub-groups while
        the remaining groups still stream (the engine does exactly this;
        reference ``pipelined_optimizer_swapper.py:52`` overlaps swap with
        the step the same way)."""
        lr = self.lr if lr is None else float(lr)
        self.step_count += 1
        n = len(self.groups)
        out: List[Optional[np.ndarray]] = [None] * len(self.shapes)

        inflight = self._issue_read(self._read_h[0], self.groups[0])
        for gi, g in enumerate(self.groups):
            nxt = None
            if gi + 1 < n:  # prefetch while this group updates
                nxt = self._issue_read(self._read_h[(gi + 1) % 2],
                                       self.groups[gi + 1])
            errs = self._read_h[gi % 2].wait()
            if errs:
                raise IOError(f"{errs} NVMe read(s) failed in "
                              f"{self.swap_dir}")
            bufs = inflight
            for j, leaf_id in enumerate(g.leaf_ids):
                grad = np.ascontiguousarray(grads[leaf_id], np.float32)
                adam_step_buffers(
                    bufs["p"][j], grad, bufs["m"][j], bufs["v"][j],
                    lr=lr, betas=self.betas, eps=self.eps,
                    weight_decay=self.weight_decay, step=self.step_count,
                    adamw_mode=self.adamw_mode)
                out[leaf_id] = (fp32_to_bf16(bufs["p"][j])
                                if out_dtype == "bfloat16"
                                else bufs["p"][j].copy())
            if on_group is not None:
                on_group(list(g.leaf_ids),
                         [out[i] for i in g.leaf_ids])
            if self._pending_write is not None:  # drain group gi-1's writes
                prev_g = self._pending_write[0]
                self._drain_writes()
                self._track(-prev_g.nbytes)
            self._issue_write(g, bufs)
            inflight = nxt
        if self._pending_write is not None:
            prev_g = self._pending_write[0]
            self._drain_writes()
            self._track(-prev_g.nbytes)
        return [o for o in out]  # type: ignore[misc]

    # ------------------------------------------------------------------ #
    def state_leaves(self) -> Tuple[List[np.ndarray], List[np.ndarray],
                                    List[np.ndarray]]:
        """Read back the full (p, m, v) state from NVMe — for checkpointing
        and tests; NOT bounded-memory (materializes everything)."""
        ps: List[np.ndarray] = [None] * len(self.shapes)  # type: ignore
        ms: List[np.ndarray] = [None] * len(self.shapes)  # type: ignore
        vs: List[np.ndarray] = [None] * len(self.shapes)  # type: ignore
        for g in self.groups:
            bufs = self._issue_read(self._read_h[0], g)
            errs = self._read_h[0].wait()
            if errs:
                raise IOError(f"{errs} NVMe read(s) failed")
            for j, leaf_id in enumerate(g.leaf_ids):
                ps[leaf_id] = bufs["p"][j]
                ms[leaf_id] = bufs["m"][j]
                vs[leaf_id] = bufs["v"][j]
            self._track(-g.nbytes)
        return ps, ms, vs

    def load_state_leaves(self, ps: Sequence[np.ndarray],
                          ms: Sequence[np.ndarray],
                          vs: Sequence[np.ndarray], step: int) -> None:
        """Write a full (p, m, v) state into the NVMe files (resume)."""
        self.step_count = step
        for g in self.groups:
            bufs = {"p": [np.ascontiguousarray(ps[i], np.float32)
                          for i in g.leaf_ids],
                    "m": [np.ascontiguousarray(ms[i], np.float32)
                          for i in g.leaf_ids],
                    "v": [np.ascontiguousarray(vs[i], np.float32)
                          for i in g.leaf_ids]}
            self._issue_write(g, bufs)
            self._drain_writes()

    def save_state_files(self, dest_dir: str) -> None:
        """Stream-copy the NVMe state into a checkpoint directory — a file
        copy, bounded memory, no tensor materialization."""
        import json
        import shutil

        os.makedirs(dest_dir, exist_ok=True)
        for g in self.groups:
            for kind in ("p", "m", "v"):
                for path in g.paths[kind]:
                    shutil.copyfile(path, os.path.join(
                        dest_dir, os.path.basename(path)))
        with open(os.path.join(dest_dir, "meta.json"), "w") as f:
            json.dump({"step_count": self.step_count}, f)

    def load_state_files(self, src_dir: str) -> None:
        """Restore the NVMe state from a checkpoint directory written by
        :meth:`save_state_files` (same model/partitioning)."""
        import json
        import shutil

        for g in self.groups:
            for kind in ("p", "m", "v"):
                for path in g.paths[kind]:
                    src = os.path.join(src_dir, os.path.basename(path))
                    if not os.path.exists(src):
                        raise FileNotFoundError(
                            f"NVMe optimizer checkpoint missing {src} — "
                            f"was it written with a different model or "
                            f"sub_group_size?")
                    shutil.copyfile(src, path)
        with open(os.path.join(src_dir, "meta.json")) as f:
            self.step_count = int(json.load(f)["step_count"])

    def purge(self) -> None:
        for g in self.groups:
            for kind in ("p", "m", "v"):
                for path in g.paths[kind]:
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
