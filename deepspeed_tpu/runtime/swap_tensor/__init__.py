from .swapper import (AsyncTensorSwapper, PartitionedOptimizerSwapper,
                      SwappedTensorMeta)

__all__ = ["AsyncTensorSwapper", "PartitionedOptimizerSwapper",
           "SwappedTensorMeta"]
