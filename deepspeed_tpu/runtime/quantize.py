"""MoQ: Mixture-of-Quantization training quantizer with precision switching.

Reference parity: ``runtime/quantize.py:14 Quantizer`` and
``runtime/weight_quantizer.py:11 WeightQuantization`` — during training the
weight precision steps down from ``start_bits`` toward ``target_bits`` every
``q_period`` steps; optionally the period stretches for layers with large
Hessian eigenvalues (more sensitive → quantize later). Quantization itself is
the shared straight-through fake-quant (``compression/compress.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..compression.compress import fake_quantize
from ..utils.logging import log_dist


class MoQQuantizer:
    def __init__(self, q_start_bits: int = 16, q_target_bits: int = 8,
                 q_period: int = 100, q_rounding: str = "nearest",
                 q_type: str = "symmetric", eigenvalue_aware: bool = False):
        self.start_bits = q_start_bits
        self.target_bits = q_target_bits
        self.q_period = max(1, q_period)
        self.symmetric = q_type == "symmetric"
        self.eigenvalue_aware = eigenvalue_aware
        self._announced: set = set()

    def bits_at(self, step: int, eigenvalue_scale: float = 1.0) -> int:
        """Precision schedule: one bit down per (period × scale)."""
        period = self.q_period * max(eigenvalue_scale, 1e-6)
        drop = int(step / period)
        return max(self.target_bits, self.start_bits - drop)

    def quantize(self, params: Any, step: int,
                 eigenvalues: Optional[Dict[str, float]] = None) -> Any:
        """Fake-quantize matrix leaves at the scheduled precision. With
        ``eigenvalues`` (per-top-level-key), sensitive blocks keep more bits
        (period scales with eigenvalue / median)."""
        evs = eigenvalues or {}
        med = sorted(evs.values())[len(evs) // 2] if evs else 1.0

        def one_subtree(key, sub):
            scale = (evs.get(key, med) / med) if (self.eigenvalue_aware and evs) \
                else 1.0
            bits = self.bits_at(step, scale)
            if bits >= self.start_bits:
                return sub
            if (key, bits) not in self._announced:
                log_dist(f"MoQ: '{key}' → {bits} bits at step {step}")
                self._announced.add((key, bits))
            return jax.tree.map(
                lambda x: fake_quantize(x, bits, symmetric=self.symmetric,
                                        per_channel=True)
                if hasattr(x, "ndim") and x.ndim >= 2 and
                jnp.issubdtype(x.dtype, jnp.floating) else x, sub)

        if isinstance(params, dict):
            return {k: one_subtree(k, v) for k, v in params.items()}
        return one_subtree("_root", params)
