"""Mixed precision: dtype policy + dynamic loss scaling.

Reference parity:
- ``runtime/fp16/loss_scaler.py`` (``DynamicLossScaler`` :187, ``LossScaler``
  :163): loss scale doubling every ``scale_window`` good steps, halving on
  overflow with hysteresis.
- ``runtime/bf16_optimizer.py``: fp32 master weights for bf16 compute without
  loss scaling.

TPU-first difference: the scaler is a *pytree state threaded through the
jit-compiled step*, and overflow handling is a ``jnp.where`` skip (no Python
branching, no cross-device overflow allreduce — the grads are already global
under SPMD so an ``isfinite`` reduction is free).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class PrecisionPolicy:
    """Which dtypes to use where. Params (and optimizer state) stay fp32 —
    master weights; compute casts per-step."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    @classmethod
    def from_config(cls, cfg) -> "PrecisionPolicy":
        if cfg.fp16.enabled:
            return cls(jnp.float32, jnp.float16, jnp.float32)
        if cfg.bf16.enabled:
            return cls(jnp.float32, jnp.bfloat16, jnp.float32)
        return cls()

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


class LossScaleState(NamedTuple):
    """Dynamic loss scaler state (a jit-compatible pytree)."""

    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 consecutive overflow-free steps
    growth_interval: jnp.ndarray  # i32 (static in practice)
    backoff: jnp.ndarray        # f32 multiplicative backoff (0.5)
    growth: jnp.ndarray         # f32 growth factor (2.0)
    min_scale: jnp.ndarray      # f32
    enabled: jnp.ndarray        # bool — False for bf16/fp32 (scale pinned to 1)


def make_loss_scaler(cfg_fp16) -> LossScaleState:
    """Build from an ``FP16Config``; static scale if ``loss_scale`` > 0."""
    enabled = bool(cfg_fp16.enabled)
    dynamic = enabled and cfg_fp16.dynamic_loss_scale
    init = (2.0 ** cfg_fp16.initial_scale_power) if dynamic else (
        cfg_fp16.loss_scale if enabled and cfg_fp16.loss_scale else 1.0)
    return LossScaleState(
        scale=jnp.asarray(init, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        growth_interval=jnp.asarray(cfg_fp16.loss_scale_window, jnp.int32),
        backoff=jnp.asarray(0.5, jnp.float32),
        growth=jnp.asarray(2.0, jnp.float32),
        min_scale=jnp.asarray(cfg_fp16.min_loss_scale, jnp.float32),
        enabled=jnp.asarray(dynamic, jnp.bool_),
    )


def scale_loss(loss: jnp.ndarray, state: LossScaleState) -> jnp.ndarray:
    return loss * state.scale.astype(loss.dtype)


def grads_finite(grads) -> jnp.ndarray:
    leaves = jax.tree.leaves(grads)
    finite = jnp.asarray(True)
    for leaf in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
    return finite


def unscale_grads(grads, state: LossScaleState):
    inv = 1.0 / state.scale
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * inv), grads)


def update_loss_scale(state: LossScaleState, finite: jnp.ndarray) -> LossScaleState:
    """Pure-functional DynamicLossScaler.update_scale (reference
    ``loss_scaler.py:230``): halve on overflow, double after ``growth_interval``
    consecutive good steps."""
    grown = state.good_steps + 1 >= state.growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grown, state.scale * state.growth, state.scale),
        jnp.maximum(state.scale * state.backoff, state.min_scale))
    new_good = jnp.where(finite, jnp.where(grown, 0, state.good_steps + 1), 0)
    new_scale = jnp.where(state.enabled, new_scale, state.scale)
    new_good = jnp.where(state.enabled, new_good, state.good_steps)
    return state._replace(scale=new_scale, good_steps=new_good.astype(jnp.int32))
