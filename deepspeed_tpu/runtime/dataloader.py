"""Data loading — reference parity with ``runtime/dataloader.py``
(``DeepSpeedDataLoader``: DistributedSampler + curriculum hooks).

On TPU under SPMD, every process feeds *global* batches (each host supplies its
addressable shard); for the single-controller case this loader batches a
dataset/iterable and leaves device placement to the engine's batch sharding.
Curriculum/data-efficiency integration plugs in via ``batch_transform``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


class DeepSpeedTPUDataLoader:
    def __init__(self, dataset: Iterable, batch_size: int,
                 mesh_mgr=None, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 batch_transform: Optional[Callable] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh_mgr = mesh_mgr
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.batch_transform = batch_transform
        self._epoch = 0

    def __len__(self) -> int:
        try:
            n = len(self.dataset)  # type: ignore[arg-type]
        except TypeError:
            raise TypeError("dataset has no __len__; iterate directly")
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __iter__(self) -> Iterator[Any]:
        try:
            n = len(self.dataset)  # type: ignore[arg-type]
            indexable = True
        except TypeError:
            indexable = False

        if indexable:
            order = np.arange(n)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self._epoch)
                rng.shuffle(order)
            for start in range(0, n - self.batch_size + 1 if self.drop_last else n,
                               self.batch_size):
                idx = order[start:start + self.batch_size]
                items = [self.dataset[int(i)] for i in idx]
                batch = self.collate_fn(items)
                if self.batch_transform:
                    batch = self.batch_transform(batch)
                yield batch
        else:
            buf = []
            for item in self.dataset:
                buf.append(item)
                if len(buf) == self.batch_size:
                    batch = self.collate_fn(buf)
                    if self.batch_transform:
                        batch = self.batch_transform(batch)
                    yield batch
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)


def _default_collate(items):
    """Stack dict-of-arrays or arrays along a new leading batch dim."""
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(it[k]) for it in items]) for k in first}
    return np.stack([np.asarray(it) for it in items])
