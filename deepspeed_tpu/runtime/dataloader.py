"""Data loading — reference parity with ``runtime/dataloader.py``
(``DeepSpeedDataLoader``: DistributedSampler + curriculum hooks).

On TPU under SPMD, every process feeds *global* batches (each host supplies its
addressable shard); for the single-controller case this loader batches a
dataset/iterable and leaves device placement to the engine's batch sharding.
Curriculum/data-efficiency integration plugs in via ``batch_transform``.

The loader is **checkpointable** (the elastic training runtime —
docs/reliability.md "Elastic training & universal checkpoint"):
:meth:`state_dict` captures the data cursor ``(epoch, batches served)`` and
:meth:`load_state_dict` fast-forwards the NEXT iteration to it exactly — the
shuffle order is a pure function of ``(seed, epoch)``, so a resumed run (at
any topology, global batch invariant) sees the identical remaining data
order without materializing the skipped batches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from ..utils.logging import logger


class DeepSpeedTPUDataLoader:
    def __init__(self, dataset: Iterable, batch_size: int,
                 mesh_mgr=None, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 batch_transform: Optional[Callable] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh_mgr = mesh_mgr
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.batch_transform = batch_transform
        self._epoch = 0
        # data cursor: batches served in the CURRENT epoch (tracked by the
        # live iterator) + a pending fast-forward target set by
        # load_state_dict and consumed by the next __iter__
        self._batches_served = 0
        self._resume_batch: Optional[int] = None

    def __len__(self) -> int:
        try:
            n = len(self.dataset)  # type: ignore[arg-type]
        except TypeError:
            raise TypeError("dataset has no __len__; iterate directly")
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._batches_served = 0

    # ------------------------------------------------------------------ #
    # checkpointable cursor (universal checkpoint v2)
    # ------------------------------------------------------------------ #
    def state_dict(self) -> Dict[str, Any]:
        """The exact data position: the next ``__iter__`` after a matching
        :meth:`load_state_dict` yields the same remaining batch sequence."""
        return {"epoch": int(self._epoch),
                "batch": int(self._batches_served),
                "seed": int(self.seed),
                "shuffle": bool(self.shuffle),
                "batch_size": int(self.batch_size)}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        """Arm the next iteration to fast-forward to the saved cursor. The
        global batch size must match (the elasticity invariant — a resumed
        job keeps the identical effective batch, so the cursor unit is
        stable across topologies)."""
        if int(sd.get("batch_size", self.batch_size)) != self.batch_size:
            logger.warning(
                f"dataloader cursor was recorded at batch_size "
                f"{sd.get('batch_size')} but this loader batches "
                f"{self.batch_size} — the cursor unit changed; data order "
                f"will NOT replay exactly")
        if int(sd.get("seed", self.seed)) != self.seed or \
                bool(sd.get("shuffle", self.shuffle)) != self.shuffle:
            logger.warning("dataloader cursor was recorded with a different "
                           "seed/shuffle — data order will NOT replay "
                           "exactly")
        self._epoch = int(sd.get("epoch", 0))
        self._resume_batch = int(sd.get("batch", 0))

    def __iter__(self) -> Iterator[Any]:
        try:
            n = len(self.dataset)  # type: ignore[arg-type]
            indexable = True
        except TypeError:
            indexable = False

        skip = self._resume_batch or 0
        self._resume_batch = None
        self._batches_served = skip

        if indexable:
            order = np.arange(n)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self._epoch)
                rng.shuffle(order)
            starts = range(0, n - self.batch_size + 1 if self.drop_last else n,
                           self.batch_size)
            for k, start in enumerate(starts):
                if k < skip:
                    continue  # fast-forward: pure index math, nothing built
                idx = order[start:start + self.batch_size]
                items = [self.dataset[int(i)] for i in idx]
                batch = self.collate_fn(items)
                if self.batch_transform:
                    batch = self.batch_transform(batch)
                self._batches_served += 1
                yield batch
        else:
            buf = []
            skipped = 0
            for item in self.dataset:
                buf.append(item)
                if len(buf) == self.batch_size:
                    if skipped < skip:
                        # non-indexable fast-forward: the iterator must be
                        # consumed, but skipped batches are never collated
                        skipped += 1
                        buf = []
                        continue
                    batch = self.collate_fn(buf)
                    if self.batch_transform:
                        batch = self.batch_transform(batch)
                    self._batches_served += 1
                    yield batch
                    buf = []
            if buf and not self.drop_last:
                self._batches_served += 1
                yield self.collate_fn(buf)


def _default_collate(items):
    """Stack dict-of-arrays or arrays along a new leading batch dim."""
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(it[k]) for it in items]) for k in first}
    return np.stack([np.asarray(it) for it in items])
