"""LR schedules — reference parity with ``runtime/lr_schedules.py``
(LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR, :19-24).

TPU-first shape: a schedule is a pure function ``step -> lr_scale`` (traced
inside the jit step), wrapped in a small object exposing the reference's
``step()/get_lr()`` interface for API compatibility. The schedule returns the
absolute LR; the optimizer's base ``lr`` is multiplied by
``lr / base_lr`` internally via ``lr_scale``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"


def _warmup(step, warmup_num_steps, warmup_type="log"):
    step = jnp.asarray(step, jnp.float32)
    w = max(int(warmup_num_steps), 1)
    frac = jnp.clip(step / w, 0.0, 1.0)
    if warmup_type == "log":
        # reference WarmupLR: log-spaced interpolation min→max
        return jnp.where(step >= w, 1.0, jnp.log1p(step) / math.log1p(w))
    return frac


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log") -> Schedule:
    def sched(step):
        f = _warmup(step, warmup_num_steps, warmup_type)
        return warmup_min_lr + f * (warmup_max_lr - warmup_min_lr)

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) / jnp.maximum(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, base(step), warmup_max_lr * decay)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_num_steps: int = 1000,
                     warmup_min_ratio: float = 0.0, cos_min_ratio: float = 0.0001,
                     warmup_max_lr: float = 1e-3, **_) -> Schedule:
    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        wfrac = jnp.clip(step / jnp.maximum(warmup_num_steps, 1), 0.0, 1.0)
        warm = warmup_min_ratio + wfrac * (1 - warmup_min_ratio)
        progress = jnp.clip((step - warmup_num_steps)
                            / jnp.maximum(total_num_steps - warmup_num_steps, 1), 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(math.pi * progress))
        ratio = jnp.where(step < warmup_num_steps, warm, cos)
        return warmup_max_lr * ratio

    return sched


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> Schedule:
    second = cycle_second_step_size if cycle_second_step_size is not None \
        else cycle_first_step_size

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        total_cycle = cycle_first_step_size + second
        up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / jnp.maximum(second, 1), 0.0, 1.0)
        in_cycle = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.where(
            step <= cycle_first_step_size, up, 1.0 - down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - total_cycle, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
            return jnp.where(step > total_cycle, decayed, in_cycle)
        return jnp.where(step > total_cycle, cycle_min_lr, in_cycle)

    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return sched


def constant(lr: float) -> Schedule:
    def sched(step):
        return jnp.full_like(jnp.asarray(step, jnp.float32), lr)

    return sched


_FACTORY: Dict[str, Callable[..., Schedule]] = {
    WARMUP_LR.lower(): warmup_lr,
    WARMUP_DECAY_LR.lower(): warmup_decay_lr,
    WARMUP_COSINE_LR.lower(): warmup_cosine_lr,
    ONE_CYCLE.lower(): one_cycle,
    LR_RANGE_TEST.lower(): lr_range_test,
}


def get_schedule(type_name: Optional[str], params: Dict[str, Any],
                 base_lr: float) -> Schedule:
    """Build from a DeepSpeed-style scheduler config block. ``None`` → constant
    base LR."""
    if not type_name:
        return constant(base_lr)
    key = type_name.lower()
    if key not in _FACTORY:
        raise ValueError(f"unknown scheduler '{type_name}' (known: {sorted(_FACTORY)})")
    import inspect

    fn = _FACTORY[key]
    accepted = set(inspect.signature(fn).parameters)
    has_kwargs = any(p.kind == inspect.Parameter.VAR_KEYWORD
                     for p in inspect.signature(fn).parameters.values())
    kwargs = {k: v for k, v in params.items() if has_kwargs or k in accepted}
    return fn(**kwargs)


class LRScheduler:
    """Reference-compatible stateful wrapper (``lr_scheduler.step()/get_lr()``)."""

    def __init__(self, schedule: Schedule):
        self.schedule = schedule
        self.last_step = 0

    def step(self, increment: int = 1) -> None:
        self.last_step += increment

    def get_lr(self):
        return [float(self.schedule(jnp.asarray(self.last_step)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]
