"""Typed config infrastructure.

Capability parity with the reference's ``runtime/config_utils.py``
(``DeepSpeedConfigModel``): dict/JSON → typed config objects with unknown-key
warnings, deprecated-key migration, and ``"auto"`` passthrough — implemented with
stdlib dataclasses (no pydantic dependency).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, fields
from typing import Any, ClassVar, Dict, Optional, Type, TypeVar

from ..utils.logging import logger

T = TypeVar("T", bound="ConfigModel")

AUTO = "auto"


def is_auto(value: Any) -> bool:
    return isinstance(value, str) and value == AUTO


@dataclass
class ConfigModel:
    """Base class: construct from a dict, tolerating unknown keys (warn) and
    recursively constructing nested ConfigModel fields.

    Subclasses may define a class attribute ``_DEPRECATED = {"old_key":
    "new_key"}`` for key migration.
    """

    _DEPRECATED: ClassVar[Dict[str, str]] = {}

    @classmethod
    def from_dict(cls: Type[T], d: Optional[Dict[str, Any]]) -> T:
        d = dict(d or {})
        for old, new in cls._DEPRECATED.items():
            if old in d:
                logger.warning(f"Config key '{old}' is deprecated; use '{new}'")
                d.setdefault(new, d.pop(old))
        known = {f.name: f for f in fields(cls)}
        kwargs = {}
        for key, value in d.items():
            if key not in known:
                logger.warning(f"{cls.__name__}: unknown config key '{key}' (ignored)")
                continue
            ftype = known[key].type
            sub = _resolve_config_model(ftype)
            if sub is not None and isinstance(value, dict):
                value = sub.from_dict(value)
            kwargs[key] = value
        return cls(**kwargs)

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            out[f.name] = v.to_dict() if isinstance(v, ConfigModel) else v
        return out


_MODEL_REGISTRY: Dict[str, Type[ConfigModel]] = {}


def _resolve_config_model(ftype: Any) -> Optional[Type[ConfigModel]]:
    """Map a dataclass field annotation to a ConfigModel subclass, if any.

    Annotations may be actual classes or strings (``from __future__ import
    annotations``); registered subclasses are looked up by name.
    """
    if isinstance(ftype, type) and issubclass(ftype, ConfigModel):
        return ftype
    name = ftype if isinstance(ftype, str) else getattr(ftype, "__name__", None)
    if isinstance(name, str):
        name = name.replace("Optional[", "").rstrip("]")
        return _MODEL_REGISTRY.get(name)
    return None


def register_config_model(cls: Type[ConfigModel]) -> Type[ConfigModel]:
    """Decorator registering a ConfigModel so string annotations resolve to it."""
    _MODEL_REGISTRY[cls.__name__] = cls
    return cls


def get_scalar_param(d: Dict[str, Any], key: str, default: Any) -> Any:
    return d.get(key, default)
