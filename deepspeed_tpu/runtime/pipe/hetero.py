"""Heterogeneous pipeline stages (reference ``PipelineModule`` partitioning).

Reference parity: ``runtime/pipe/module.py:86`` — ``PipelineModule`` accepts
an arbitrary ``LayerSpec`` list and partitions it across stages by
``partition_method`` ``'uniform' | 'parameters' | 'type:regex'``
(``module.py:378``); heterogeneous models (mixed block types, mid-model
adapters, tower + head) pipeline through the same engine.

TPU-first redesign: stages still execute under ONE compiled 1F1B SPMD clock
(see ``one_f_one_b.py`` — ppermute rings, recompute-backward, O(S) stash);
per-stage heterogeneity enters as a ``lax.switch`` over the stage index whose
branches are the stages' sub-programs.

Stage-LOCAL parameter placement (reference ``module.py:86``: each rank builds
only its stage's layers — the whole point of PP for >HBM models): every
stage's param pytree is packed into per-dtype flat rows, padded to the
largest stage, and stacked into ``[S, Lpad]`` buffers whose leading dim is
sharded over 'pipe'. Each pipe rank therefore HOLDS only its own stage's
bytes (+ pad to the max stage — the bucketed/padded cost of heterogeneity);
the per-stage tree structure is static unpack metadata (offset/shape slices)
applied inside that stage's ``lax.switch`` branch. Gradients come back in
the same packed pipe-sharded layout, so optimizer state and fp32 masters are
stage-local too, and no cross-'pipe' grad psum is needed (each rank's row
grads are complete locally).

Batch/data axes: the shard_map is partial-manual over {'pipe'} only — the
engine's 'data'-axis batch sharding stays an AUTO axis, so XLA partitions
each micro-batch's compute over 'data' as usual (dp still buys throughput
on this path; 'pipe' replication applies only to the schedule clock).
Verified empirically on a data=4 × pipe=2 mesh: the partitioned HLO holds
the global [32, S] token batch as per-device [8, S] tiles — the data split
survives into the manual region.

Activation contract: every stage boundary carries the SAME activation
shape/dtype (the classic pipeline constraint; the reference's p2p send/recv
requires it too).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm import comm as dist
from ...comm.mesh import get_mesh
from .module import (one_f_one_b_predicates, one_f_one_b_ticks, ring_perms,
                     stage_ids)


# --------------------------------------------------------------------------- #
# layer specs + partitioning (reference module.py:378 partition methods)
# --------------------------------------------------------------------------- #
@dataclass
class LayerSpec:
    """One pipeline-able layer: a typename (for ``type:`` partitioning), its
    params pytree, and ``apply(params, h) -> h``. Reference ``LayerSpec``
    defers construction; here params are a pytree and apply is pure."""

    typename: str
    params: Any
    apply: Callable[[Any, jnp.ndarray], jnp.ndarray]


def _num_params(tree: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def _balanced_partition(weights: Sequence[float], n_parts: int) -> List[int]:
    """Boundaries [b_0=0, ..., b_n=len] of the contiguous partition minimizing
    the max part weight (the reference's ``ds_utils.partition_balanced``).
    Binary search on the bottleneck + greedy feasibility check."""
    w = [float(x) for x in weights]
    n = len(w)
    if n_parts > n:
        raise ValueError(f"cannot split {n} layers into {n_parts} stages")

    def parts_needed(cap: float) -> int:
        parts, acc = 1, 0.0
        for x in w:
            if x > cap:
                return n_parts + 1  # infeasible cap
            if acc + x > cap:
                parts, acc = parts + 1, x
            else:
                acc += x
        return parts

    lo, hi = max(w) if w else 0.0, sum(w)
    for _ in range(60):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= n_parts:
            hi = mid
        else:
            lo = mid
    # greedy emit with cap=hi, then pad empty trailing parts if fewer used —
    # but every stage must own >= 1 layer, so rebalance from the rear
    bounds = [0]
    acc = 0.0
    for i, x in enumerate(w):
        if acc + x > hi and len(bounds) < n_parts:
            bounds.append(i)
            acc = x
        else:
            acc += x
    bounds.append(n)
    while len(bounds) < n_parts + 1:  # fewer parts than requested: split rear
        for j in range(len(bounds) - 1, 0, -1):
            if bounds[j] - bounds[j - 1] > 1:
                bounds.insert(j, bounds[j] - 1)
                break
        else:
            raise ValueError(f"cannot split {n} layers into {n_parts} stages")
    return bounds


def partition_layers(specs: Sequence[LayerSpec], n_stages: int,
                     method: str = "parameters") -> List[int]:
    """Stage boundaries for a LayerSpec list (reference ``module.py:378``):

    - ``'uniform'``    — equal layer counts;
    - ``'parameters'`` — balance per-stage parameter counts;
    - ``'type:regex'`` — balance the count of layers whose typename matches
      ``regex`` (non-matching layers ride with their preceding group).
    Returns ``bounds`` with ``len == n_stages + 1``; stage s owns
    ``specs[bounds[s]:bounds[s+1]]``.
    """
    if method == "uniform":
        weights = [1.0] * len(specs)
    elif method == "parameters":
        weights = [float(_num_params(s.params)) for s in specs]
    elif method.startswith("type:"):
        pat = re.compile(method[len("type:"):], re.IGNORECASE)
        weights = [1.0 if pat.search(s.typename) else 0.0 for s in specs]
        if sum(weights) < n_stages:
            raise ValueError(
                f"partition '{method}': only {int(sum(weights))} matching "
                f"layers for {n_stages} stages")
    else:
        raise ValueError(f"unknown partition_method '{method}' "
                         "(want 'uniform' | 'parameters' | 'type:regex')")
    bounds = _balanced_partition(weights, n_stages)
    return bounds


# --------------------------------------------------------------------------- #
# stage-tree <-> packed pipe-sharded buffer conversion
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class StageLayout:
    """Static unpack metadata for one stage: the tree structure plus, per
    leaf (in flatten order), which dtype-buffer it lives in and at what
    offset/shape."""

    treedef: Any
    entries: Tuple[Tuple[str, int, Tuple[int, ...]], ...]


_PAD_QUANTUM = 1024  # rows pad to a multiple of this so ZeRO axes divide Lpad


def pack_stage_trees(stage_trees: Sequence[Any]
                     ) -> Tuple[dict, List[StageLayout]]:
    """Stage param pytrees → ``({dtype_key: [S, Lpad] array}, layouts)``.

    Leaves are grouped by dtype (a flat buffer needs one dtype), raveled and
    concatenated per stage, zero-padded to the largest stage's length. The
    leading dim is meant to be sharded over 'pipe' (logical axis 'layers'),
    which makes each rank's resident bytes its own stage share + pad.

    Packing happens on HOST (numpy): building a fully-replicated [S, Lpad]
    jnp copy next to the live stage leaves would transiently double the
    whole model on the default device — the exact OOM stage-local placement
    exists to avoid. The engine device_puts the packed result with its
    pipe-sharded layout, so only each rank's row ever lands on a device.
    """
    import ml_dtypes  # noqa: F401  (registers bfloat16 etc. with numpy)

    layouts: List[StageLayout] = []
    per_dtype_len: dict = {}
    for tree in stage_trees:
        leaves, treedef = jax.tree.flatten(tree)
        offs: dict = {}
        entries = []
        for leaf in leaves:
            dt = str(getattr(leaf, "dtype", None) or jnp.asarray(leaf).dtype)
            o = offs.get(dt, 0)
            entries.append((dt, o, tuple(leaf.shape)))
            offs[dt] = o + int(np.prod(leaf.shape))
        layouts.append(StageLayout(treedef, tuple(entries)))
        for dt, end in offs.items():
            per_dtype_len[dt] = max(per_dtype_len.get(dt, 0), end)
    buffers = {}
    for dt, L in per_dtype_len.items():
        Lp = -(-L // _PAD_QUANTUM) * _PAD_QUANTUM
        np_dt = np.dtype(dt)
        rows = np.zeros((len(stage_trees), Lp), np_dt)
        for s, (tree, layout) in enumerate(zip(stage_trees, layouts)):
            leaves = jax.tree.leaves(tree)
            for leaf, (d, off, shape) in zip(leaves, layout.entries):
                if d == dt:
                    n = int(np.prod(shape))
                    rows[s, off:off + n] = np.asarray(leaf).ravel()
        buffers[dt] = rows
    return buffers, layouts


def unpack_stage(rows: dict, layout: StageLayout) -> Any:
    """One stage's param tree from its packed rows ``{dtype_key: [Lpad]}``.
    Pure static slicing/reshaping — differentiable, jit-friendly."""
    leaves = [lax.slice_in_dim(rows[dt], off, off + int(np.prod(shape)))
              .reshape(shape) for dt, off, shape in layout.entries]
    return jax.tree.unflatten(layout.treedef, leaves)


def buffer_logical_axes(buffers: dict):
    """Logical axes for the packed buffers: leading dim is the stage dim
    ('layers' → 'pipe' when PP is active), flat dim left for ZeRO."""
    return {dt: ("layers", None) for dt in buffers}


# --------------------------------------------------------------------------- #
# elastic PP: repartition packed checkpoints across stage counts
# --------------------------------------------------------------------------- #
def _bounds_for(specs: Sequence[LayerSpec], n_stages: int,
                method: str) -> List[int]:
    return [0, len(specs)] if n_stages <= 1 else \
        partition_layers(specs, n_stages, method)


def _layer_slices(specs: Sequence[LayerSpec], bounds: Sequence[int]):
    """Per-layer packed coordinates under a given partitioning:
    ``({layer: [(dtype_key, offset, size), ...in leaf order]}, {layer: stage},
    {dtype_key: (S, Lpad)})``. Offsets and the padded shapes follow the
    exact flatten order / quantum ``pack_stage_trees`` uses — no values are
    touched (pure metadata, O(leaves) not O(model bytes))."""
    slices: Dict[int, list] = {}
    stage_of: Dict[int, int] = {}
    per_dtype_len: Dict[str, int] = {}
    n_stages = len(bounds) - 1
    for s in range(n_stages):
        tree = {str(i): specs[i].params for i in range(bounds[s], bounds[s + 1])}
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        offs: Dict[str, int] = {}
        for path, leaf in flat:
            # same leaf coercion pack_stage_trees applies (plain scalars/lists)
            dt = str(getattr(leaf, "dtype", None) or jnp.asarray(leaf).dtype)
            layer = int(path[0].key)
            n = int(np.prod(np.shape(leaf)))
            slices.setdefault(layer, []).append((dt, offs.get(dt, 0), n))
            stage_of[layer] = s
            offs[dt] = offs.get(dt, 0) + n
        for dt, end in offs.items():
            per_dtype_len[dt] = max(per_dtype_len.get(dt, 0), end)
    shapes = {dt: (n_stages, -(-L // _PAD_QUANTUM) * _PAD_QUANTUM)
              for dt, L in per_dtype_len.items()}
    return slices, stage_of, shapes


def repack_pipeline_arrays(arrays_old: Dict[str, np.ndarray],
                           specs: Sequence[LayerSpec],
                           old_stages: int, new_stages: int,
                           method: str = "parameters"
                           ) -> Dict[str, np.ndarray]:
    """Re-layout packed ``[S_old, Lpad_old]`` arrays (params OR same-keyed
    optimizer moments) for a different stage count. The reference's
    universal checkpoint re-maps per-layer fragments across PP topologies
    (``universal_checkpoint.py:99``); here the per-layer fragments are
    slices of the packed rows, moved between rows as layers change stage."""
    old_sl, old_stage, old_shapes = _layer_slices(
        specs, _bounds_for(specs, old_stages, method))
    new_sl, new_stage, new_shapes = _layer_slices(
        specs, _bounds_for(specs, new_stages, method))
    for dt, arr in arrays_old.items():
        if dt not in old_shapes or tuple(np.shape(arr)) != old_shapes[dt]:
            # wrong old_stages/method would otherwise scramble weights
            # SILENTLY whenever padding happens to cover the bad offsets
            raise ValueError(
                f"packed array '{dt}' has shape {np.shape(arr)} but "
                f"(specs, old_stages={old_stages}, method='{method}') "
                f"implies {old_shapes.get(dt)} — wrong stage count, "
                f"partition method, or LayerSpec list")
    out = {dt: np.zeros(new_shapes[dt], dtype=arrays_old[dt].dtype)
           for dt in new_shapes if dt in arrays_old}
    for layer, old_entries in old_sl.items():
        for (dt, o_old, n), (dt2, o_new, n2) in zip(old_entries,
                                                    new_sl[layer]):
            assert dt == dt2 and n == n2, (layer, dt, dt2, n, n2)
            if dt not in arrays_old:
                continue
            out[dt][new_stage[layer], o_new:o_new + n] = \
                np.asarray(arrays_old[dt])[old_stage[layer], o_old:o_old + n]
    return out


def repartition_universal_pipeline(universal_dir: str,
                                   specs: Sequence[LayerSpec],
                                   old_stages: int, new_stages: int, *,
                                   method: str = "parameters",
                                   out_dir: str) -> str:
    """Rewrite a universal checkpoint of a packed hetero pipeline for a new
    stage count (elastic PP resume). Every fragment whose tree path ends in
    ``pipe_buffers.<dtype>`` — the params AND each optimizer-moment mirror —
    is repacked; everything else (step counters, scalars) copies through.
    ``specs`` must be the same LayerSpec list both models were built from
    (layouts are recomputed from it deterministically)."""
    import json as _json
    import re as _re
    import shutil as _shutil

    from ..checkpoint.universal import UNIVERSAL_DIR

    root = universal_dir
    if os.path.basename(root) != UNIVERSAL_DIR and \
            os.path.isdir(os.path.join(root, UNIVERSAL_DIR)):
        root = os.path.join(root, UNIVERSAL_DIR)
    if os.path.exists(out_dir) and os.listdir(out_dir):
        raise ValueError(f"out_dir {out_dir} exists and is not empty")
    # atomic like save_universal: build in a tmp dir, os.replace at the end,
    # so a mid-repack failure never leaves a loadable half-converted dir
    tmp = os.path.normpath(out_dir) + ".tmp"
    if os.path.exists(tmp):
        _shutil.rmtree(tmp)
    _shutil.copytree(root, tmp)
    try:
        # group fragments by their pipe_buffers dict (a params tree and each
        # moment mirror repack as one unit so dtype-buffer pairs stay aligned)
        pat = _re.compile(r"^(.*?)pipe_buffers\.([A-Za-z0-9_]+)$")
        groups: Dict[str, Dict[str, str]] = {}
        for sub in ("param", "optim"):
            d = os.path.join(tmp, sub)
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                m = pat.match(name)
                if m:
                    groups.setdefault(sub + "/" + m.group(1), {})[m.group(2)] \
                        = os.path.join(d, name, "fp32.npy")
        if not groups:
            raise ValueError(
                f"no pipe_buffers fragments found under {root} — "
                f"not a packed hetero-pipeline checkpoint")
        index_updates: Dict[str, list] = {}
        for _, by_dt in groups.items():
            arrays_old = {dt: np.load(fn) for dt, fn in by_dt.items()}
            arrays_new = repack_pipeline_arrays(arrays_old, specs, old_stages,
                                                new_stages, method)
            for dt, fn in by_dt.items():
                np.save(fn, arrays_new[dt])
                frag = os.path.basename(os.path.dirname(fn))
                index_updates[frag] = list(arrays_new[dt].shape)
        meta_path = os.path.join(tmp, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = _json.load(f)
            for sec in meta.get("index", {}).values():
                for frag, shape in index_updates.items():
                    if frag in sec:
                        sec[frag]["shape"] = shape
            with open(meta_path, "w") as f:
                _json.dump(meta, f, indent=2, default=str)
    except Exception:
        _shutil.rmtree(tmp, ignore_errors=True)
        raise
    os.makedirs(os.path.dirname(os.path.abspath(out_dir)), exist_ok=True)
    os.replace(tmp, out_dir)
    return out_dir


# --------------------------------------------------------------------------- #
# the compiled heterogeneous 1F1B clock
# --------------------------------------------------------------------------- #
def hetero_pipeline_value_and_grad(
        first_fn: Callable[[Any, Any], jnp.ndarray],
        mid_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
        last_fn: Callable[[Any, jnp.ndarray, Any], jnp.ndarray],
        buffers: dict, layouts: Sequence[StageLayout],
        inputs: Any, labels: Any, *,
        num_micro: Optional[int] = None,
        pipe_axis: str = "pipe") -> Tuple[jnp.ndarray, dict]:
    """1F1B over ``S = 2 + len(mid_fns)`` heterogeneous stages with
    stage-LOCAL packed params.

    first_fn(p0, inputs_micro) -> h            (stage 0: embed + its blocks)
    mid_fns[s-1](ps, h) -> h                   (stages 1..S-2)
    last_fn(pS, h, labels_micro) -> sum loss   (last stage: blocks + head)

    ``buffers``: ``{dtype_key: [S, Lpad]}`` packed stage params
    (``pack_stage_trees``); each pipe rank sees only its own row inside the
    manual region. Returns ``(mean-ish loss, packed f32 grads)`` with the
    same ``(1/M)·Σ`` scaling contract as ``pipeline_value_and_grad``.
    Falls back to sequential value_and_grad when the mesh has pipe <= 1.
    """
    mm = get_mesh()
    S = len(layouts)
    if mm.axis_size(pipe_axis) != S and mm.axis_size(pipe_axis) > 1:
        raise ValueError(
            f"model was partitioned into {S} stage(s) but the mesh's "
            f"'{pipe_axis}' axis has size {mm.axis_size(pipe_axis)} — "
            f"build the pipeline model AFTER the mesh exists, or pass "
            f"n_stages={mm.axis_size(pipe_axis)} to build_pipeline_model")
    if S != 2 + len(mid_fns):
        raise ValueError(
            f"stage count mismatch: {S} stage layouts but "
            f"{len(mid_fns)} mid fns (expect S == 2 + len(mid_fns))")

    def stage_rows(bufs, s):
        return {dt: b[s] for dt, b in bufs.items()}

    if mm.axis_size(pipe_axis) <= 1:
        def flat_loss(bufs):
            h = first_fn(unpack_stage(stage_rows(bufs, 0), layouts[0]), inputs)
            for s, fn in enumerate(mid_fns, start=1):
                h = fn(unpack_stage(stage_rows(bufs, s), layouts[s]), h)
            return last_fn(unpack_stage(stage_rows(bufs, S - 1),
                                        layouts[S - 1]), h, labels)

        loss, grads = jax.value_and_grad(flat_loss)(buffers)
        return loss, jax.tree.map(lambda g: g.astype(jnp.float32), grads)

    M = num_micro or S
    B = jax.tree.leaves(inputs)[0].shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by num_micro {M}")
    split = lambda x: x.reshape((M, B // M) + x.shape[1:])  # noqa: E731
    micro_in = jax.tree.map(split, inputs)
    micro_lab = jax.tree.map(split, labels)

    fwd_perm, bwd_perm = ring_perms(S)
    T = one_f_one_b_ticks(S, M)

    # activation template from stage 0 (shape-only)
    probe = jax.eval_shape(
        lambda b, x: first_fn(unpack_stage(stage_rows(b, 0), layouts[0]), x),
        buffers, jax.tree.map(lambda x: x[0], micro_in))

    def pipelined(stage_arr, bufs, micro_in, micro_lab, probe_shape):
        stage = stage_arr[0]   # sharded iota — see module.stage_ids
        # each rank's packed row IS its stage's params (P('pipe') in_spec)
        rows = {dt: b[0] for dt, b in bufs.items()}
        stash = jnp.zeros((S,) + probe_shape.shape, probe_shape.dtype)
        h_next = jnp.zeros_like(probe_shape)
        g_next = jnp.zeros_like(probe_shape)
        g_rows = {dt: jnp.zeros(r.shape, jnp.float32)
                  for dt, r in rows.items()}
        loss_sum = jnp.zeros((), jnp.float32)

        def tick(t, carry):
            stash, h_next, g_next, g_rows, loss_sum = carry
            fwd_on, i_f, bwd_on, i_b = one_f_one_b_predicates(t, stage, S, M)

            # ---- forward tick: lax.switch over the stage's sub-program ----
            # branch s unpacks THIS rank's row with stage s's layout; only
            # the branch matching the rank's stage index ever executes
            def do_fwd(stash, h_next, loss_sum):
                inj = jax.tree.map(lambda x: x[i_f], micro_in)
                lab = jax.tree.map(lambda x: x[i_f], micro_lab)

                def b_first():
                    return (first_fn(unpack_stage(rows, layouts[0]), inj)
                            .astype(probe_shape.dtype),
                            jnp.zeros((), jnp.float32))

                def b_mid(s):
                    def f():
                        return (mid_fns[s - 1](unpack_stage(rows, layouts[s]),
                                               h_next)
                                .astype(probe_shape.dtype),
                                jnp.zeros((), jnp.float32))
                    return f

                def b_last():
                    return (jnp.zeros_like(h_next),
                            last_fn(unpack_stage(rows, layouts[-1]), h_next,
                                    lab)
                            .astype(jnp.float32))

                branches = ([b_first] + [b_mid(s) for s in range(1, S - 1)]
                            + [b_last])
                out, loss_i = lax.switch(stage, branches)
                # stash the stage INPUT for the recompute backward (stage 0
                # re-injects from micro_in instead; slot unused)
                stash = lax.dynamic_update_index_in_dim(stash, h_next,
                                                        i_f % S, 0)
                return stash, out, loss_sum + loss_i

            stash, fwd_out, loss_sum = lax.cond(
                fwd_on, do_fwd,
                lambda stash, h_next, loss_sum: (
                    stash, jnp.zeros_like(h_next), loss_sum),
                stash, h_next, loss_sum)

            # ---- backward tick (recompute + vjp, switch per stage) ----
            # vjp runs w.r.t. the packed rows, so row grads land directly in
            # the stage-local packed layout (zero where other dtypes/pads)
            def do_bwd(g_next, g_rows):
                h_in = lax.dynamic_index_in_dim(stash, i_b % S, 0,
                                                keepdims=False)
                inj = jax.tree.map(lambda x: x[i_b], micro_in)
                lab = jax.tree.map(lambda x: x[i_b], micro_lab)

                def cast_f32(gr):
                    return {dt: g.astype(jnp.float32)
                            for dt, g in gr.items()}

                def b_first():
                    _, vjp = jax.vjp(
                        lambda r: first_fn(unpack_stage(r, layouts[0]), inj)
                        .astype(g_next.dtype), rows)
                    (gr,) = vjp(g_next)
                    return cast_f32(gr), jnp.zeros_like(g_next)

                def b_mid(s):
                    def f():
                        # primal carries the SAME cast as the forward tick so
                        # the cotangent seed dtype always matches, whatever
                        # dtype the stage's apply returns
                        out, vjp = jax.vjp(
                            lambda r, h: mid_fns[s - 1](
                                unpack_stage(r, layouts[s]), h)
                            .astype(probe_shape.dtype), rows, h_in)
                        gr, gh = vjp(g_next.astype(out.dtype))
                        return cast_f32(gr), gh.astype(g_next.dtype)
                    return f

                def b_last():
                    _, vjp = jax.vjp(
                        lambda r, h: (last_fn(unpack_stage(r, layouts[-1]),
                                              h, lab) / M)
                        .astype(jnp.float32), rows, h_in)
                    gr, gh = vjp(jnp.ones((), jnp.float32))
                    return cast_f32(gr), gh.astype(g_next.dtype)

                branches = ([b_first] + [b_mid(s) for s in range(1, S - 1)]
                            + [b_last])
                gr, gh = lax.switch(stage, branches)
                g_rows = jax.tree.map(jnp.add, g_rows, gr)
                return gh, g_rows

            g_out, g_rows = lax.cond(
                bwd_on, do_bwd,
                lambda g_next, g_rows: (jnp.zeros_like(g_next), g_rows),
                g_next, g_rows)

            h_next = lax.ppermute(fwd_out, pipe_axis, fwd_perm)
            g_next = lax.ppermute(g_out, pipe_axis, bwd_perm)
            return stash, h_next, g_next, g_rows, loss_sum

        carry = (stash, h_next, g_next, g_rows, loss_sum)
        carry = lax.fori_loop(0, T, tick, carry)
        _, _, _, g_rows, loss_sum = carry
        loss = lax.psum(loss_sum, pipe_axis) / M
        # each rank's row grads are complete locally (it only ever ran its
        # own stage's branches) — stacking over 'pipe' replaces the old
        # replicated-tree psum; the schedule needs NO cross-stage grad comm
        return loss, {dt: g[None, :] for dt, g in g_rows.items()}

    probe_shape = jnp.zeros(probe.shape, probe.dtype)
    # fully-manual region: partial-manual ppermute CHECK-fails this
    # jax/XLA's SPMD partitioner — see module.pipeline_apply
    loss, grads = dist.shard_map(
        pipelined, mesh=mm.mesh, axis_names=None,
        in_specs=(P(pipe_axis),
                  {dt: P(pipe_axis) for dt in buffers}, P(), P(), P()),
        out_specs=(P(), {dt: P(pipe_axis) for dt in buffers}),
        check_vma=False)(stage_ids(S), buffers, micro_in, micro_lab,
                         probe_shape)
    return loss, grads


# --------------------------------------------------------------------------- #
# PipelineModule analog: LayerSpecs → engine-ready ModelSpec
# --------------------------------------------------------------------------- #
def build_pipeline_model(specs: Sequence[LayerSpec],
                         first_fn: Callable[[Any, Any], jnp.ndarray],
                         loss_head: Callable[[jnp.ndarray, Any], jnp.ndarray],
                         *, n_stages: Optional[int] = None,
                         partition_method: str = "parameters",
                         name: str = "hetero_pipeline"):
    """Reference ``PipelineModule(layers=specs, num_stages=..,
    partition_method=..)`` analog: group the LayerSpecs into stages and
    return an engine-ready ``ModelSpec`` whose ``pipeline_grad_fn`` runs the
    heterogeneous compiled 1F1B clock (and whose ``loss_fn`` runs the same
    stages sequentially off-pipeline).

    ``first_fn(p, batch_inputs) -> h`` embeds the raw micro inputs using the
    FIRST spec's params; ``loss_head(h, labels) -> summed loss`` closes the
    LAST stage. Params are stored PACKED: per-dtype ``[S, Lpad]`` buffers
    whose stage dim shards over 'pipe' (stage-local bytes, reference
    ``module.py:86`` parity); per-stage trees are unpacked on the fly.
    """
    from ..engine import ModelSpec

    mm = None
    try:
        mm = get_mesh()
    except Exception:
        pass
    S = n_stages or (mm.pp_world_size if mm is not None else 1)
    S = max(S, 1)
    # single source of truth with the checkpoint repartitioner: bounds MUST
    # be reproducible from (specs, S, method) alone or repacked checkpoints
    # desynchronize from the engine layout
    bounds = _bounds_for(specs, S, partition_method)

    groups = [list(range(bounds[s], bounds[s + 1])) for s in range(len(bounds) - 1)]
    stage_trees = [{str(i): specs[i].params for i in g} for g in groups]
    buffers, layouts = pack_stage_trees(stage_trees)
    params = {"pipe_buffers": buffers}

    def stage_tree(p, s):
        bufs = p["pipe_buffers"]
        return unpack_stage({dt: b[s] for dt, b in bufs.items()}, layouts[s])

    def run_group(s, p_stage, h, first=False, inputs=None):
        for j, i in enumerate(groups[s]):
            if first and j == 0:
                h = first_fn(p_stage[str(i)], inputs)
            else:
                h = specs[i].apply(p_stage[str(i)], h)
        return h

    def split_batch(batch):
        tokens = batch["tokens"]
        if "labels" in batch:
            return tokens, batch["labels"]
        return tokens[:, :-1], tokens[:, 1:]

    def loss_fn(p, batch):
        inputs, labels = split_batch(batch)
        h = None
        for s in range(len(groups)):
            h = run_group(s, stage_tree(p, s), h, first=(s == 0),
                          inputs=inputs)
        loss = loss_head(h, labels)
        denom = jnp.maximum(jax.tree.leaves(labels)[0].size, 1)
        return loss / denom, {}

    def pipeline_grad_fn(p, batch, loss_scale=None):
        inputs, labels = split_batch(batch)
        scale = 1.0 if loss_scale is None else loss_scale
        n = len(groups)

        def fst(p0, inp):
            return run_group(0, p0, None, first=True, inputs=inp)

        def mid(s):
            return lambda ps, h: run_group(s, ps, h)

        def lst(pl, h, lab):
            return loss_head(run_group(n - 1, pl, h), lab) * scale

        loss, grads = hetero_pipeline_value_and_grad(
            fst, [mid(s) for s in range(1, n - 1)], lst,
            p["pipe_buffers"], layouts, inputs, labels)
        M = max(get_mesh().pp_world_size, 1)
        denom = jnp.maximum(jax.tree.leaves(labels)[0].size, 1) \
            .astype(jnp.float32)
        factor = M / denom
        out_grads = {"pipe_buffers":
                     jax.tree.map(lambda g: g * factor, grads)}
        loss = loss * factor / scale
        return out_grads, loss, {}

    return ModelSpec(loss_fn=loss_fn, params=params, name=name,
                     pipeline_capable=True,
                     logical_axes={"pipe_buffers": buffer_logical_axes(buffers)},
                     pipeline_grad_fn=pipeline_grad_fn)
