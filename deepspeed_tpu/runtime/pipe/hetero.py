"""Heterogeneous pipeline stages (reference ``PipelineModule`` partitioning).

Reference parity: ``runtime/pipe/module.py:86`` — ``PipelineModule`` accepts
an arbitrary ``LayerSpec`` list and partitions it across stages by
``partition_method`` ``'uniform' | 'parameters' | 'type:regex'``
(``module.py:378``); heterogeneous models (mixed block types, mid-model
adapters, tower + head) pipeline through the same engine.

TPU-first redesign: stages still execute under ONE compiled 1F1B SPMD clock
(see ``one_f_one_b.py`` — ppermute rings, recompute-backward, O(S) stash);
per-stage heterogeneity enters as a ``lax.switch`` over the stage index whose
branches are the stages' sub-programs. Stage params ride ``shard_map`` as
explicit inputs, replicated over 'pipe' — ZeRO/TP sharding over the OTHER
mesh axes still applies outside the manual region, so per-rank param bytes
match plain DP. The homogeneous stacked path (``one_f_one_b``) keeps true
stage-local parameter placement and remains the fast path for uniform layer
stacks; this module buys capability (arbitrary stage programs), not memory.

Activation contract: every stage boundary carries the SAME activation
shape/dtype (the classic pipeline constraint; the reference's p2p send/recv
requires it too).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.mesh import get_mesh
from .module import (one_f_one_b_predicates, one_f_one_b_ticks, psum_f32,
                     ring_perms)


# --------------------------------------------------------------------------- #
# layer specs + partitioning (reference module.py:378 partition methods)
# --------------------------------------------------------------------------- #
@dataclass
class LayerSpec:
    """One pipeline-able layer: a typename (for ``type:`` partitioning), its
    params pytree, and ``apply(params, h) -> h``. Reference ``LayerSpec``
    defers construction; here params are a pytree and apply is pure."""

    typename: str
    params: Any
    apply: Callable[[Any, jnp.ndarray], jnp.ndarray]


def _num_params(tree: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def _balanced_partition(weights: Sequence[float], n_parts: int) -> List[int]:
    """Boundaries [b_0=0, ..., b_n=len] of the contiguous partition minimizing
    the max part weight (the reference's ``ds_utils.partition_balanced``).
    Binary search on the bottleneck + greedy feasibility check."""
    w = [float(x) for x in weights]
    n = len(w)
    if n_parts > n:
        raise ValueError(f"cannot split {n} layers into {n_parts} stages")

    def parts_needed(cap: float) -> int:
        parts, acc = 1, 0.0
        for x in w:
            if x > cap:
                return n_parts + 1  # infeasible cap
            if acc + x > cap:
                parts, acc = parts + 1, x
            else:
                acc += x
        return parts

    lo, hi = max(w) if w else 0.0, sum(w)
    for _ in range(60):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= n_parts:
            hi = mid
        else:
            lo = mid
    # greedy emit with cap=hi, then pad empty trailing parts if fewer used —
    # but every stage must own >= 1 layer, so rebalance from the rear
    bounds = [0]
    acc = 0.0
    for i, x in enumerate(w):
        if acc + x > hi and len(bounds) < n_parts:
            bounds.append(i)
            acc = x
        else:
            acc += x
    bounds.append(n)
    while len(bounds) < n_parts + 1:  # fewer parts than requested: split rear
        for j in range(len(bounds) - 1, 0, -1):
            if bounds[j] - bounds[j - 1] > 1:
                bounds.insert(j, bounds[j] - 1)
                break
        else:
            raise ValueError(f"cannot split {n} layers into {n_parts} stages")
    return bounds


def partition_layers(specs: Sequence[LayerSpec], n_stages: int,
                     method: str = "parameters") -> List[int]:
    """Stage boundaries for a LayerSpec list (reference ``module.py:378``):

    - ``'uniform'``    — equal layer counts;
    - ``'parameters'`` — balance per-stage parameter counts;
    - ``'type:regex'`` — balance the count of layers whose typename matches
      ``regex`` (non-matching layers ride with their preceding group).
    Returns ``bounds`` with ``len == n_stages + 1``; stage s owns
    ``specs[bounds[s]:bounds[s+1]]``.
    """
    if method == "uniform":
        weights = [1.0] * len(specs)
    elif method == "parameters":
        weights = [float(_num_params(s.params)) for s in specs]
    elif method.startswith("type:"):
        pat = re.compile(method[len("type:"):], re.IGNORECASE)
        weights = [1.0 if pat.search(s.typename) else 0.0 for s in specs]
        if sum(weights) < n_stages:
            raise ValueError(
                f"partition '{method}': only {int(sum(weights))} matching "
                f"layers for {n_stages} stages")
    else:
        raise ValueError(f"unknown partition_method '{method}' "
                         "(want 'uniform' | 'parameters' | 'type:regex')")
    bounds = _balanced_partition(weights, n_stages)
    return bounds


# --------------------------------------------------------------------------- #
# the compiled heterogeneous 1F1B clock
# --------------------------------------------------------------------------- #
def hetero_pipeline_value_and_grad(
        first_fn: Callable[[Any, Any], jnp.ndarray],
        mid_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
        last_fn: Callable[[Any, jnp.ndarray, Any], jnp.ndarray],
        stage_params: Sequence[Any], inputs: Any, labels: Any, *,
        num_micro: Optional[int] = None,
        pipe_axis: str = "pipe") -> Tuple[jnp.ndarray, Tuple[Any, ...]]:
    """1F1B over ``S = 2 + len(mid_fns)`` heterogeneous stages.

    first_fn(p0, inputs_micro) -> h            (stage 0: embed + its blocks)
    mid_fns[s-1](ps, h) -> h                   (stages 1..S-2)
    last_fn(pS, h, labels_micro) -> sum loss   (last stage: blocks + head)

    Returns ``(mean-ish loss, per-stage grads tuple)`` with the same
    ``(1/M)·Σ`` scaling contract as ``pipeline_value_and_grad``.
    Falls back to sequential value_and_grad when the mesh has pipe <= 1.
    """
    mm = get_mesh()
    S = len(stage_params)
    if mm.axis_size(pipe_axis) != S and mm.axis_size(pipe_axis) > 1:
        raise ValueError(
            f"model was partitioned into {S} stage(s) but the mesh's "
            f"'{pipe_axis}' axis has size {mm.axis_size(pipe_axis)} — "
            f"build the pipeline model AFTER the mesh exists, or pass "
            f"n_stages={mm.axis_size(pipe_axis)} to build_pipeline_model")
    if S != 2 + len(mid_fns):
        raise ValueError(
            f"stage count mismatch: {S} stage param trees but "
            f"{len(mid_fns)} mid fns (expect S == 2 + len(mid_fns))")

    if mm.axis_size(pipe_axis) <= 1:
        def flat_loss(ps):
            h = first_fn(ps[0], inputs)
            for fn, p in zip(mid_fns, ps[1:-1]):
                h = fn(p, h)
            return last_fn(ps[-1], h, labels)

        loss, grads = jax.value_and_grad(flat_loss)(tuple(stage_params))
        return loss, grads

    M = num_micro or S
    B = jax.tree.leaves(inputs)[0].shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by num_micro {M}")
    split = lambda x: x.reshape((M, B // M) + x.shape[1:])  # noqa: E731
    micro_in = jax.tree.map(split, inputs)
    micro_lab = jax.tree.map(split, labels)

    fwd_perm, bwd_perm = ring_perms(S)
    T = one_f_one_b_ticks(S, M)

    # activation template from stage 0 (shape-only)
    probe = jax.eval_shape(first_fn, stage_params[0],
                           jax.tree.map(lambda x: x[0], micro_in))
    f32z = lambda t: jax.tree.map(  # noqa: E731
        lambda x: jnp.zeros(x.shape, jnp.float32), t)

    def pipelined(params, micro_in, micro_lab, probe_shape):
        stage = lax.axis_index(pipe_axis)
        stash = jnp.zeros((S,) + probe_shape.shape, probe_shape.dtype)
        h_next = jnp.zeros_like(probe_shape)
        g_next = jnp.zeros_like(probe_shape)
        g_params = tuple(f32z(p) for p in params)
        loss_sum = jnp.zeros((), jnp.float32)

        def tick(t, carry):
            stash, h_next, g_next, g_params, loss_sum = carry
            fwd_on, i_f, bwd_on, i_b = one_f_one_b_predicates(t, stage, S, M)

            # ---- forward tick: lax.switch over the stage's sub-program ----
            def do_fwd(stash, h_next, loss_sum):
                inj = jax.tree.map(lambda x: x[i_f], micro_in)
                lab = jax.tree.map(lambda x: x[i_f], micro_lab)

                def b_first():
                    return (first_fn(params[0], inj)
                            .astype(probe_shape.dtype),
                            jnp.zeros((), jnp.float32))

                def b_mid(s):
                    def f():
                        return (mid_fns[s - 1](params[s], h_next)
                                .astype(probe_shape.dtype),
                                jnp.zeros((), jnp.float32))
                    return f

                def b_last():
                    return (jnp.zeros_like(h_next),
                            last_fn(params[-1], h_next, lab)
                            .astype(jnp.float32))

                branches = ([b_first] + [b_mid(s) for s in range(1, S - 1)]
                            + [b_last])
                out, loss_i = lax.switch(stage, branches)
                # stash the stage INPUT for the recompute backward (stage 0
                # re-injects from micro_in instead; slot unused)
                stash = lax.dynamic_update_index_in_dim(stash, h_next,
                                                        i_f % S, 0)
                return stash, out, loss_sum + loss_i

            stash, fwd_out, loss_sum = lax.cond(
                fwd_on, do_fwd,
                lambda stash, h_next, loss_sum: (
                    stash, jnp.zeros_like(h_next), loss_sum),
                stash, h_next, loss_sum)

            # ---- backward tick (recompute + vjp, switch per stage) ----
            def do_bwd(g_next, g_params):
                h_in = lax.dynamic_index_in_dim(stash, i_b % S, 0,
                                                keepdims=False)
                inj = jax.tree.map(lambda x: x[i_b], micro_in)
                lab = jax.tree.map(lambda x: x[i_b], micro_lab)
                zeros_g = tuple(f32z(p) for p in params)

                def set_s(tup, s, val):
                    return tuple(val if i == s else x
                                 for i, x in enumerate(tup))

                def b_first():
                    _, vjp = jax.vjp(
                        lambda p: first_fn(p, inj).astype(g_next.dtype),
                        params[0])
                    (gp,) = vjp(g_next)
                    return (set_s(zeros_g, 0,
                                  jax.tree.map(lambda x: x.astype(jnp.float32),
                                               gp)),
                            jnp.zeros_like(g_next))

                def b_mid(s):
                    def f():
                        # primal carries the SAME cast as the forward tick so
                        # the cotangent seed dtype always matches, whatever
                        # dtype the stage's apply returns
                        out, vjp = jax.vjp(
                            lambda p, h: mid_fns[s - 1](p, h)
                            .astype(probe_shape.dtype), params[s], h_in)
                        gp, gh = vjp(g_next.astype(out.dtype))
                        return (set_s(zeros_g, s,
                                      jax.tree.map(
                                          lambda x: x.astype(jnp.float32),
                                          gp)),
                                gh.astype(g_next.dtype))
                    return f

                def b_last():
                    _, vjp = jax.vjp(
                        lambda p, h: (last_fn(p, h, lab) / M)
                        .astype(jnp.float32), params[-1], h_in)
                    gp, gh = vjp(jnp.ones((), jnp.float32))
                    return (set_s(zeros_g, S - 1,
                                  jax.tree.map(lambda x: x.astype(jnp.float32),
                                               gp)),
                            gh.astype(g_next.dtype))

                branches = ([b_first] + [b_mid(s) for s in range(1, S - 1)]
                            + [b_last])
                gp_all, gh = lax.switch(stage, branches)
                g_params = jax.tree.map(jnp.add, g_params, gp_all)
                return gh, g_params

            g_out, g_params = lax.cond(
                bwd_on, do_bwd,
                lambda g_next, g_params: (jnp.zeros_like(g_next), g_params),
                g_next, g_params)

            h_next = lax.ppermute(fwd_out, pipe_axis, fwd_perm)
            g_next = lax.ppermute(g_out, pipe_axis, bwd_perm)
            return stash, h_next, g_next, g_params, loss_sum

        carry = (stash, h_next, g_next, g_params, loss_sum)
        carry = lax.fori_loop(0, T, tick, carry)
        _, _, _, g_params, loss_sum = carry
        loss = lax.psum(loss_sum, pipe_axis) / M
        g_params = jax.tree.map(lambda g: psum_f32(g, pipe_axis), g_params)
        return loss, g_params

    probe_shape = jnp.zeros(probe.shape, probe.dtype)
    params = tuple(stage_params)
    loss, grads = jax.shard_map(
        pipelined, mesh=mm.mesh, axis_names={pipe_axis},
        in_specs=(jax.tree.map(lambda _: P(), params), P(), P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P(), params)),
        check_vma=False)(params, micro_in, micro_lab, probe_shape)
    return loss, grads


# --------------------------------------------------------------------------- #
# PipelineModule analog: LayerSpecs → engine-ready ModelSpec
# --------------------------------------------------------------------------- #
def build_pipeline_model(specs: Sequence[LayerSpec],
                         first_fn: Callable[[Any, Any], jnp.ndarray],
                         loss_head: Callable[[jnp.ndarray, Any], jnp.ndarray],
                         *, n_stages: Optional[int] = None,
                         partition_method: str = "parameters",
                         name: str = "hetero_pipeline"):
    """Reference ``PipelineModule(layers=specs, num_stages=..,
    partition_method=..)`` analog: group the LayerSpecs into stages and
    return an engine-ready ``ModelSpec`` whose ``pipeline_grad_fn`` runs the
    heterogeneous compiled 1F1B clock (and whose ``loss_fn`` runs the same
    stages sequentially off-pipeline).

    ``first_fn(p, batch_inputs) -> h`` embeds the raw micro inputs using the
    FIRST spec's params; ``loss_head(h, labels) -> summed loss`` closes the
    LAST stage. Stage s params live under key ``f"stage{s}"``.
    """
    from ..engine import ModelSpec

    mm = None
    try:
        mm = get_mesh()
    except Exception:
        pass
    S = n_stages or (mm.pp_world_size if mm is not None else 1)
    S = max(S, 1)
    if S == 1:
        bounds = [0, len(specs)]
    else:
        bounds = partition_layers(specs, S, partition_method)

    groups = [list(range(bounds[s], bounds[s + 1])) for s in range(len(bounds) - 1)]
    params = {f"stage{s}": {str(i): specs[i].params for i in g}
              for s, g in enumerate(groups)}

    def run_group(s, p_stage, h, first=False, inputs=None):
        for j, i in enumerate(groups[s]):
            if first and j == 0:
                h = first_fn(p_stage[str(i)], inputs)
            else:
                h = specs[i].apply(p_stage[str(i)], h)
        return h

    def split_batch(batch):
        tokens = batch["tokens"]
        if "labels" in batch:
            return tokens, batch["labels"]
        return tokens[:, :-1], tokens[:, 1:]

    def loss_fn(p, batch):
        inputs, labels = split_batch(batch)
        h = None
        for s in range(len(groups)):
            h = run_group(s, p[f"stage{s}"], h, first=(s == 0),
                          inputs=inputs)
        loss = loss_head(h, labels)
        denom = jnp.maximum(jax.tree.leaves(labels)[0].size, 1)
        return loss / denom, {}

    def pipeline_grad_fn(p, batch, loss_scale=None):
        inputs, labels = split_batch(batch)
        scale = 1.0 if loss_scale is None else loss_scale
        n = len(groups)

        def fst(p0, inp):
            return run_group(0, p0, None, first=True, inputs=inp)

        def mid(s):
            return lambda ps, h: run_group(s, ps, h)

        def lst(pl, h, lab):
            return loss_head(run_group(n - 1, pl, h), lab) * scale

        stage_params = [p[f"stage{s}"] for s in range(n)]
        loss, grads = hetero_pipeline_value_and_grad(
            fst, [mid(s) for s in range(1, n - 1)], lst, stage_params,
            inputs, labels)
        M = max(get_mesh().pp_world_size, 1)
        denom = jnp.maximum(jax.tree.leaves(labels)[0].size, 1) \
            .astype(jnp.float32)
        factor = M / denom
        out_grads = {f"stage{s}": jax.tree.map(lambda g: g * factor, gs)
                     for s, gs in enumerate(grads)}
        loss = loss * factor / scale
        return out_grads, loss, {}

    return ModelSpec(loss_fn=loss_fn, params=params, name=name,
                     pipeline_capable=False,
                     pipeline_grad_fn=pipeline_grad_fn)
