from .module import pipeline_apply

__all__ = ["pipeline_apply"]
