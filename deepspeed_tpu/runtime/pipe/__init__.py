from .hetero import LayerSpec, build_pipeline_model, partition_layers
from .module import pipeline_apply

__all__ = ["pipeline_apply", "LayerSpec", "partition_layers",
           "build_pipeline_model"]
