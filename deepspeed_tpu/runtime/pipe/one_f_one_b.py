"""Compiled 1F1B pipeline schedule (bounded-activation training).

Reference parity: ``runtime/pipe/schedule.py:189 TrainSchedule`` (1F1B
instruction stream), ``pipe/engine.py:60`` (instruction interpreter with p2p
send/recv) and ``pipe/engine.py:274`` (tied-weight grad reduction).

TPU-first redesign — the schedule is a *compiled SPMD clock*, not an
interpreter:

- All stages run one program under ``shard_map`` over the 'pipe' axis for
  ``T = 2M + 2S - 2`` ticks. At tick ``t`` stage ``s`` forwards microbatch
  ``i`` iff ``t == s + 2i`` and backwards microbatch ``i`` iff
  ``t == (2S - 1 - s) + 2i`` — the textbook 1F1B timing, whose fwd/bwd ticks
  have opposite parity per stage so each tick issues exactly one unit of work
  (``lax.cond`` skips the idle half; stages branch independently between the
  collectives, which sit outside the conds).
- Activations move with ``lax.ppermute`` (+1 ring); gradients with the
  reverse ring — the reference's SendActivation/RecvActivation/SendGrad/
  RecvGrad instructions.
- Memory: each stage stashes only the *block-input* activation of in-flight
  microbatches — at most ``S`` live at once (a ``[S, micro, ...]`` ring) —
  and the backward tick recomputes its stage forward under ``jax.vjp``
  (activation-recompute 1F1B). GPipe-by-AD holds O(M) microbatch residuals;
  this holds O(S).
- Tied weights: the embedding is consumed by stage 0's backward and (when
  tied) the head by the last stage's — both grads are partial per stage and
  the closing ``psum`` over 'pipe' is exactly ReduceTiedGrads.

The last stage folds the loss into its forward tick (per-microbatch, summed),
so no O(M) logits/outputs buffer ever exists.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm import comm as dist
from ...comm.mesh import get_mesh
from .module import (_stage_params, one_f_one_b_predicates,
                     one_f_one_b_ticks, psum_f32, ring_perms, stage_ids)


def pipeline_value_and_grad(embed_fn: Callable[[Any, Any], jnp.ndarray],
                            block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                            head_fn: Callable[[Any, jnp.ndarray, Any], jnp.ndarray],
                            params: Any, inputs: Any, labels: Any, *,
                            num_micro: Optional[int] = None,
                            pipe_axis: str = "pipe"):
    """1F1B train step core: returns ``(mean_loss, grads)``.

    params: {"embed": E, "layers": stacked [L, ...] pytree, "head": H}
    embed_fn(E, inputs_micro) -> h [micro, ...]   (stage-0 work)
    block_fn(layer, h) -> h                       (ONE layer, unstacked)
    head_fn(H, h, labels_micro) -> scalar loss    (last-stage work; SUM or
        MEAN over the microbatch — grads scale by 1/M here either way)

    inputs / labels: arrays with leading batch dim B (microbatched as B/M).
    Falls back to plain jax.value_and_grad over a lax.scan when pipe size 1.
    """
    mm = get_mesh()
    S = mm.axis_size(pipe_axis)
    E, layers, H = params["embed"], params["layers"], params["head"]

    if S <= 1:
        def flat_loss(p):
            h = embed_fn(p["embed"], inputs)

            def body(h, layer):
                return block_fn(layer, h), None

            h, _ = lax.scan(body, h, p["layers"])
            return head_fn(p["head"], h, labels)

        return jax.value_and_grad(flat_loss)(params)

    M = num_micro or S
    B = jax.tree.leaves(inputs)[0].shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by num_micro {M}")
    split = lambda x: x.reshape((M, B // M) + x.shape[1:])  # noqa: E731
    micro_in = jax.tree.map(split, inputs)
    micro_lab = jax.tree.map(split, labels)
    staged = _stage_params(layers, S)

    fwd_perm, bwd_perm = ring_perms(S)
    T = one_f_one_b_ticks(S, M)

    def stage_fwd(my_layers, h):
        def body(h, layer):
            return block_fn(layer, h), None

        out, _ = lax.scan(body, h, my_layers)
        return out

    def pipelined(stage_arr, staged_layers, E, H, micro_in, micro_lab,
                  probe_shape):
        stage = stage_arr[0]   # sharded iota — see module.stage_ids
        is_first = stage == 0
        is_last = stage == S - 1
        my_layers = jax.tree.map(lambda l: l[0], staged_layers)

        h_shape = probe_shape  # [micro, ...] activation template (zeros)
        stash = jnp.zeros((S,) + h_shape.shape, h_shape.dtype)
        h_next = jnp.zeros_like(h_shape)    # activation arriving from below
        g_next = jnp.zeros_like(h_shape)    # gradient arriving from above
        # microbatch grads accumulate in fp32 (matching the engine's GAS
        # accumulator) — bf16 sums across M micros drift
        f32 = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jnp.zeros(x.shape, jnp.float32), t)
        g_layers = f32(my_layers)
        g_embed = f32(E)
        g_head = f32(H)
        loss_sum = jnp.zeros((), jnp.float32)

        def tick(t, carry):
            stash, h_next, g_next, g_layers, g_embed, g_head, loss_sum = carry

            # ---- schedule predicates (1F1B clock) ----
            fwd_on, i_f, bwd_on, i_b = one_f_one_b_predicates(t, stage, S, M)

            # ---- forward tick ----
            def do_fwd(stash, h_next, loss_sum):
                inj = jax.tree.map(lambda x: x[i_f], micro_in)
                # stage 0 embeds its injection; others use the ring input
                # (cond: the embed matmul must not run on every stage)
                h_in = lax.cond(
                    is_first,
                    lambda: embed_fn(E, inj).astype(h_next.dtype),
                    lambda: h_next)
                stash = lax.dynamic_update_index_in_dim(stash, h_in,
                                                        i_f % S, 0)
                out = stage_fwd(my_layers, h_in)
                lab = jax.tree.map(lambda x: x[i_f], micro_lab)
                loss_i = lax.cond(
                    is_last,
                    lambda: head_fn(H, out, lab).astype(jnp.float32),
                    lambda: jnp.zeros((), jnp.float32))
                return stash, out, loss_sum + loss_i

            stash, fwd_out, loss_sum = lax.cond(
                fwd_on, do_fwd,
                lambda stash, h_next, loss_sum: (stash,
                                                 jnp.zeros_like(h_next),
                                                 loss_sum),
                stash, h_next, loss_sum)

            # ---- backward tick (recompute + vjp; 1/M grad scaling) ----
            def do_bwd(g_next, g_layers, g_embed, g_head):
                h_in = lax.dynamic_index_in_dim(stash, i_b % S, 0,
                                                keepdims=False)
                inj = jax.tree.map(lambda x: x[i_b], micro_in)
                lab = jax.tree.map(lambda x: x[i_b], micro_lab)

                # last stage seeds backward from its loss; others from g_next
                # (cond: exactly ONE recompute+vjp of the stage per tick)
                def last_branch():
                    def f(layers_, h_, H_):
                        return head_fn(H_, stage_fwd(layers_, h_), lab) / M

                    _, vjp = jax.vjp(f, my_layers, h_in, H)
                    return vjp(jnp.ones((), jnp.float32))

                def mid_branch():
                    def f(layers_, h_, H_):
                        del H_
                        return stage_fwd(layers_, h_)

                    out, vjp = jax.vjp(f, my_layers, h_in, H)
                    return vjp(g_next.astype(out.dtype))

                gl, gh, gH = lax.cond(is_last, last_branch, mid_branch)
                acc = lambda a, g: a + g.astype(jnp.float32)  # noqa: E731
                g_layers = jax.tree.map(acc, g_layers, gl)
                g_head = jax.tree.map(acc, g_head, gH)

                # stage 0: push the activation grad through the embedding
                def embed_branch():
                    _, vjp_e = jax.vjp(lambda E_: embed_fn(E_, inj)
                                       .astype(gh.dtype), E)
                    return vjp_e(gh)[0]

                ge = lax.cond(is_first, embed_branch,
                              lambda: jax.tree.map(jnp.zeros_like, E))
                g_embed = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                       g_embed, ge)
                return gh, g_layers, g_embed, g_head

            g_out, g_layers, g_embed, g_head = lax.cond(
                bwd_on, do_bwd,
                lambda g_next, g_layers, g_embed, g_head: (
                    jnp.zeros_like(g_next), g_layers, g_embed, g_head),
                g_next, g_layers, g_embed, g_head)

            # ---- ring transfers (Send/Recv Activation+Grad) ----
            h_next = lax.ppermute(fwd_out, pipe_axis, fwd_perm)
            g_next = lax.ppermute(g_out, pipe_axis, bwd_perm)
            return (stash, h_next, g_next, g_layers, g_embed, g_head,
                    loss_sum)

        carry = (stash, h_next, g_next, g_layers, g_embed, g_head, loss_sum)
        carry = lax.fori_loop(0, T, tick, carry)
        _, _, _, g_layers, g_embed, g_head, loss_sum = carry

        # loss lives on the last stage; tied/replicated params' grads are
        # partial per stage → psum over 'pipe' is ReduceTiedGrads
        loss = lax.psum(loss_sum, pipe_axis) / M
        g_embed = jax.tree.map(lambda g: psum_f32(g, pipe_axis), g_embed)
        g_head = jax.tree.map(lambda g: psum_f32(g, pipe_axis), g_head)
        g_staged = jax.tree.map(lambda g: g[None], g_layers)
        return loss, g_staged, g_embed, g_head

    # activation template: microbatch embedded at stage 0 (zeros probe keeps
    # it shape-only; never executed eagerly under jit)
    probe = jax.eval_shape(lambda E_, x: embed_fn(E_, x), E,
                           jax.tree.map(lambda x: x[0], micro_in))
    probe_shape = jnp.zeros(probe.shape, probe.dtype)

    # fully-manual region: partial-manual ppermute CHECK-fails this
    # jax/XLA's SPMD partitioner — see module.pipeline_apply
    loss, g_staged, g_embed, g_head = dist.shard_map(
        pipelined, mesh=mm.mesh, axis_names=None,
        in_specs=(P(pipe_axis),
                  jax.tree.map(lambda _: P(pipe_axis), staged),
                  P(), P(), P(), P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P(pipe_axis), staged),
                   P(), P()),
        check_vma=False)(stage_ids(S), staged, E, H, micro_in, micro_lab,
                         probe_shape)

    L = jax.tree.leaves(layers)[0].shape[0]
    g_layers = jax.tree.map(
        lambda g: g.reshape((L,) + g.shape[2:]), g_staged)
    grads = {"embed": g_embed, "layers": g_layers, "head": g_head}
    return loss, grads
