"""Pipeline parallelism — collective GPipe over the 'pipe' mesh axis.

Reference parity: ``runtime/pipe/`` — ``PipelineModule`` (``module.py:86``)
partitions a LayerSpec list across stages; ``PipelineEngine``
(``engine.py:60``) executes instruction schedules (``schedule.py``:
LoadMicroBatch/ForwardPass/SendActivation/RecvActivation/...) with p2p
send/recv between adjacent ranks (``p2p.py``).

TPU-first: there is no instruction interpreter or p2p runtime. The schedule is
*compiled*: all stages run the same SPMD program under ``shard_map`` over the
'pipe' axis; activations move between stages with ``lax.ppermute`` (neighbor
ICI transfers); microbatches stream through a rotating buffer for
``M + S - 1`` ticks (GPipe); autodiff through the loop yields the backward
schedule automatically, with ppermute transposing to the reverse permute —
the reference's SendGrad/RecvGrad instructions fall out of AD.

Layer assignment: stacked layer params [L, ...] reshape to [S, L/S, ...] and
shard the leading dim over 'pipe' — the reference's ``partition_method=
"uniform"``. (Parameter-count balancing is meaningless here because stacked
layers are homogeneous by construction.)
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm import comm as dist
from ...comm.mesh import get_mesh
from ...utils.logging import logger


def psum_f32(x, axis_name: str):
    """psum with an fp32 payload. Grad/output sums deserve fp32, and XLA:CPU
    crashes ("Invalid binary instruction opcode copy") on bf16 psum inside a
    partial-manual shard_map region."""
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
        return lax.psum(x.astype(jnp.float32), axis_name).astype(x.dtype)
    return lax.psum(x, axis_name)


def stage_ids(S: int) -> jnp.ndarray:
    """``[S]`` int32 stage indices, passed through shard_map with in_spec
    ``P(pipe_axis)`` so each stage reads its own index from its shard
    (``stage_arr[0]``). This replaces ``lax.axis_index`` inside the
    pipeline regions: under a PARTIAL-manual shard_map (manual over 'pipe'
    only, data/tensor/... still automatic) axis_index lowers to a
    ``PartitionId`` HLO op that the SPMD partitioner rejects outright
    ("meaning is ambiguous"), which failed every pipeline schedule at jit
    time. An explicitly sharded iota carries the same information with no
    partition-dependent instruction."""
    return jnp.arange(S, dtype=jnp.int32)


def ring_perms(S: int):
    """(forward, backward) neighbor rings over the pipe axis — the
    SendActivation/RecvActivation and SendGrad/RecvGrad channels."""
    fwd = [(i, (i + 1) % S) for i in range(S)]
    return fwd, [(dst, src) for src, dst in fwd]


def one_f_one_b_ticks(S: int, M: int) -> int:
    """Total clock ticks of the 1F1B schedule: 2M + 2S - 2."""
    return 2 * M + 2 * S - 2


def one_f_one_b_predicates(t, stage, S: int, M: int):
    """The 1F1B clock: at tick ``t`` stage ``s`` forwards microbatch ``i``
    iff ``t == s + 2i`` and backwards ``i`` iff ``t == (2S - 1 - s) + 2i``
    (fwd/bwd ticks have opposite parity per stage, so each tick issues at
    most one unit of work). Returns ``(fwd_on, i_f, bwd_on, i_b)`` with the
    microbatch indices clipped into [0, M)."""
    df = t - stage
    fwd_on = jnp.logical_and(df >= 0,
                             jnp.logical_and(df % 2 == 0, df < 2 * M))
    i_f = jnp.clip(df // 2, 0, M - 1)
    db = t - (2 * S - 1 - stage)
    bwd_on = jnp.logical_and(db >= 0,
                             jnp.logical_and(db % 2 == 0, db < 2 * M))
    i_b = jnp.clip(db // 2, 0, M - 1)
    return fwd_on, i_f, bwd_on, i_b


def _stage_params(layers: Any, stages: int) -> Any:
    """[L, ...] → [S, L/S, ...] on every leaf."""

    def reshape(x):
        L = x.shape[0]
        if L % stages != 0:
            raise ValueError(f"num_layers {L} not divisible by pipeline stages {stages}")
        return x.reshape((stages, L // stages) + x.shape[1:])

    return jax.tree.map(reshape, layers)


def pipeline_apply(block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   layers: Any, x: jnp.ndarray, *,
                   num_micro: Optional[int] = None,
                   pipe_axis: str = "pipe") -> jnp.ndarray:
    """Run stacked layers over the pipeline mesh axis.

    block_fn(layer_params, x) -> x : ONE layer's computation (unstacked).
    layers: pytree with leading layer dim [L, ...].
    x: [B, ...] activations entering layer 0.
    num_micro: microbatches (default = pipe size; B must divide).

    Falls back to a plain lax.scan when the mesh has no pipe axis.
    """
    mm = get_mesh()
    S = mm.axis_size(pipe_axis)
    if S <= 1:
        def scan_body(h, layer):
            return block_fn(layer, h), None

        out, _ = lax.scan(scan_body, x, layers)
        return out

    M = num_micro or S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by num_micro {M}")
    micro = x.reshape((M, B // M) + x.shape[1:])
    staged = _stage_params(layers, S)

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def stage_fn(stage_layers, h):
        """L/S layers on this stage."""

        def scan_body(h, layer):
            return block_fn(layer, h), None

        out, _ = lax.scan(scan_body, h, stage_layers)
        return out

    def pipelined(stage_arr, staged_layers, micro_local):
        """Inside shard_map over 'pipe': staged_layers are THIS stage's layer
        params [1, L/S, ...]; micro_local: all microbatches (replicated)."""
        stage = stage_arr[0]
        my_layers = jax.tree.map(lambda l: l[0], staged_layers)
        mb_shape = micro_local.shape[1:]
        state = jnp.zeros(mb_shape, micro_local.dtype)   # rotating buffer
        outputs = jnp.zeros_like(micro_local)            # filled at last stage

        def tick(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (clamped index keeps it static-safe)
            inject = micro_local[jnp.clip(t, 0, M - 1)]
            h = jnp.where(stage == 0, inject, state)
            out = stage_fn(my_layers, h)
            # last stage records its finished microbatch m = t - (S-1)
            m = t - (S - 1)
            is_done = jnp.logical_and(stage == S - 1, m >= 0)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(is_done, out, lax.dynamic_index_in_dim(
                    outputs, jnp.clip(m, 0, M - 1), 0, keepdims=False)),
                jnp.clip(m, 0, M - 1), 0)
            state = lax.ppermute(out, pipe_axis, fwd_perm)
            return state, outputs

        state, outputs = lax.fori_loop(0, M + S - 1, tick, (state, outputs))
        # non-last stages hold zeros; psum over 'pipe' broadcasts the results
        return psum_f32(outputs, pipe_axis)

    # FULLY manual region (axis_names=None): partial-manual (manual over
    # 'pipe' only, auto= on 0.4-era jax) fatally CHECK-fails XLA's SPMD
    # partitioner on every ppermute in this jax/XLA version
    # ("target.IsManualSubgroup() == sharding().IsManualSubgroup()"), and
    # lax.axis_index lowers to an unpartitionable PartitionId there — the
    # pipeline schedule never compiled. Fully manual, P() inputs replicate
    # over the non-pipe axes (each data shard computes every microbatch —
    # redundant on CPU test meshes, identical results) and the stage index
    # arrives as a sharded iota (stage_ids).
    out = dist.shard_map(
        pipelined, mesh=mm.mesh, axis_names=None,
        in_specs=(P(pipe_axis),
                  jax.tree.map(lambda _: P(pipe_axis), staged), P()),
        out_specs=P(), check_vma=False)(stage_ids(S), staged, micro)
    return out.reshape((B,) + out.shape[2:])
