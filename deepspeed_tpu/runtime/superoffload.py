"""SuperOffload: full host-offloaded optimizer with speculative updates and
clipping rollback.

Reference parity: ``runtime/superoffload/superoffload_stage3.py:27
SuperOffloadOptimizer_Stage3`` + CPU worker ``superoffload_utils.py`` —
built for superchips (GH200) where CPU↔accelerator bandwidth makes a fully
host-resident optimizer viable: the CPU updates run asynchronously,
overlapped with the next forward/backward, and a ROLLBACK mechanism undoes a
speculative update when the (late-arriving) global grad norm demands
clipping rescale.

TPU-first: the host worker runs the SIMD C++ ``DeepSpeedCPUAdam``; gradients
stream D2H once per step; the speculative update keeps a pre-update snapshot
of the host masters, and ``step()`` issues a rollback+replay with the scaled
gradients when the device-computed norm exceeds ``clip_norm``.

Host residency and per-step D2H gradient traffic are accounted through the
tiered memory subsystem (``deepspeed_tpu/memory``; docs/memory.md): pass a
``TieredStore`` (or let one be created) and the masters/moments register as
host-tier resident bytes, every gradient stream lands in
``transfer_d2h_bytes``, and the async update window is bracketed as device
compute so ``Memory/tier/overlap_frac`` covers this optimizer too.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cpu_optimizer import DeepSpeedCPUAdam
from ..utils.logging import log_dist


class SuperOffloadOptimizer:
    def __init__(self, params: Any, *, lr: float = 1e-3,
                 betas=(0.9, 0.999), weight_decay: float = 0.0,
                 clip_norm: Optional[float] = None,
                 max_inflight: int = 2, store: Optional[Any] = None):
        if store is None:
            from ..memory import TieredStore

            store = TieredStore()
        self.store = store
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.host = [np.array(l, np.float32, copy=True) for l in leaves]
        # masters + both moment buffers live host-side for the optimizer's
        # lifetime — register them on the store's host tier
        self._host_bytes = 3 * sum(h.nbytes for h in self.host)
        store._track("resident_bytes_host", self._host_bytes)
        self.cpu_adam = DeepSpeedCPUAdam(self.host, lr=lr, betas=betas,
                                         weight_decay=weight_decay)
        self.clip_norm = clip_norm
        self.lr = lr
        self.step_count = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=max_inflight)
        self._results: "queue.Queue" = queue.Queue()
        self._inflight = 0
        self._last_snapshot = None
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="superoffload-cpu")
        self._worker.start()
        log_dist(f"SuperOffload: {sum(h.size for h in self.host)/1e6:.1f}M "
                 f"params host-resident, clip={clip_norm}")

    # ------------------------------------------------------------------ #
    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            grads, lr, snapshot = item
            try:
                if snapshot is not None:  # keep rollback point: params AND moments
                    for dst_, src in zip(snapshot["params"], self.host):
                        np.copyto(dst_, src)
                    for dst_, src in zip(snapshot["exp_avg"],
                                         self.cpu_adam.exp_avg):
                        np.copyto(dst_, src)
                    for dst_, src in zip(snapshot["exp_avg_sq"],
                                         self.cpu_adam.exp_avg_sq):
                        np.copyto(dst_, src)
                self.cpu_adam.step(grads, lr=lr)
                self._results.put((grads, snapshot, None))
            except Exception as e:
                self._results.put((grads, snapshot, e))

    def _drain(self, block: bool):
        out = []
        while self._inflight and (block or not self._results.empty()):
            grads, snap, err = self._results.get()
            self._inflight -= 1
            if err is not None:
                raise err
            out.append((grads, snap))
        return out

    # ------------------------------------------------------------------ #
    def step(self, grads: Any, lr: Optional[float] = None) -> None:
        """Speculatively enqueue the async host update. The global norm is
        computed on device; if it exceeds ``clip_norm``, the just-enqueued
        update is rolled back and replayed with rescaled gradients
        (reference rollback path) — the common no-clip case never stalls."""
        lr = self.lr if lr is None else lr
        g_leaves = [np.array(g, np.float32, copy=True)
                    for g in jax.tree_util.tree_flatten(grads)[0]]
        self.store._track("transfer_d2h_bytes",
                          sum(g.nbytes for g in g_leaves))
        self.step_count += 1
        self._drain(block=False)

        scale = 1.0
        if self.clip_norm is not None:
            norm = float(np.sqrt(sum(float((g.astype(np.float64) ** 2).sum())
                                     for g in g_leaves)))
            if norm > self.clip_norm:
                scale = self.clip_norm / (norm + 1e-6)
        snapshot = {"params": [np.empty_like(h) for h in self.host],
                    "exp_avg": [np.empty_like(h) for h in self.host],
                    "exp_avg_sq": [np.empty_like(h) for h in self.host]} \
            if self.clip_norm is not None else None
        if scale != 1.0:
            # norm known before enqueue here (device math is sync by the
            # time grads are host-side) — rescale up front; the snapshot
            # machinery still exercises the rollback path in replay()
            g_leaves = [g * scale for g in g_leaves]
        self._q.put((g_leaves, lr, snapshot))
        self._inflight += 1
        self._last_snapshot = snapshot

    def rollback_and_replay(self, grads_scaled: Any,
                            lr: Optional[float] = None) -> None:
        """Undo the most recent (speculative) update and re-apply with the
        caller's corrected gradients (reference rollback mechanism)."""
        self._drain(block=True)
        if self._last_snapshot is None:
            raise RuntimeError("no snapshot: construct with clip_norm set "
                               "and take at least one step first")
        for h, s in zip(self.host, self._last_snapshot["params"]):
            np.copyto(h, s)
        for m, s in zip(self.cpu_adam.exp_avg, self._last_snapshot["exp_avg"]):
            np.copyto(m, s)
        for v, s in zip(self.cpu_adam.exp_avg_sq,
                        self._last_snapshot["exp_avg_sq"]):
            np.copyto(v, s)
        self.cpu_adam.step_count -= 1
        g_leaves = [np.array(g, np.float32, copy=True)
                    for g in jax.tree_util.tree_flatten(grads_scaled)[0]]
        self.cpu_adam.step(g_leaves, lr=self.lr if lr is None else lr)

    def params(self, like: Optional[Any] = None) -> Any:
        """Drain and return current params as a device pytree."""
        self._drain(block=True)
        leaves = [jnp.array(h) for h in self.host]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def close(self):
        self._q.put(None)
        self._worker.join(timeout=5)
        self.store._track("resident_bytes_host", -self._host_bytes)
        self._host_bytes = 0
