"""Config key constants and defaults.

Mirrors the role of the reference's ``runtime/constants.py``: the canonical JSON
key names users put in their config file, so configs written for the reference
map 1:1 onto this framework.
"""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"

FP16 = "fp16"
BF16 = "bf16"
GRADIENT_CLIPPING = "gradient_clipping"
ZERO_OPTIMIZATION = "zero_optimization"

STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MEMORY_BREAKDOWN = "memory_breakdown"

PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"

TENSOR_PARALLEL = "tensor_parallel"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
PIPELINE = "pipeline"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

MESH = "mesh"

COMMS_LOGGER = "comms_logger"
COMMS_OVERLAP = "comms_overlap"

ZERO_STAGE_0 = 0
ZERO_STAGE_1 = 1
ZERO_STAGE_2 = 2
ZERO_STAGE_3 = 3

OFFLOAD_CPU = "cpu"
OFFLOAD_NVME = "nvme"
OFFLOAD_NONE = "none"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
