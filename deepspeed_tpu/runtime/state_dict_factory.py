"""TP-degree-changing checkpoint loaders (Megatron-style state dicts).

Reference parity: ``runtime/state_dict_factory.py`` (``SDLoaderFactory`` :21,
``SDLoaderBase`` :48, ``MegatronSDLoader`` :190). The reference re-slices
Megatron mp_rank_XX checkpoint shards at inference-load time so a checkpoint
written at TP degree P can serve at degree Q: row-parallel weights concat on
the input dim, column-parallel on the output dim, fused QKV per version-
specific head grouping.

TPU-first shape: everything is numpy on host (weights then feed the sharded
``jax.device_put`` path of the engines); no torch dependency unless the
shards are ``.pt`` files. The merge/split key rules are the reference's
(Megatron naming); arbitrary un-annotated models instead go through the
AutoTP rule pass (``module_inject/auto_tp.py``) + the universal checkpoint,
which reshard by logical axis rather than by key name.
"""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Sequence, Tuple, Union

import numpy as np

from ..utils.logging import log_dist

StateDict = Dict[str, Any]

# Megatron key substrings → shard category (reference MegatronSDLoader rules)
_ROW_PARALLEL = ("attention.dense.weight", "mlp.dense_4h_to_h.weight")
_COL_PARALLEL = ("mlp.dense_h_to_4h.weight", "mlp.dense_h_to_4h.bias",
                 "word_embeddings.weight", "final_linear.weight")
_QKV = ("attention.query_key_value",)


def _to_numpy(v):
    if isinstance(v, np.ndarray):
        return v
    try:
        return v.detach().cpu().numpy()
    except AttributeError:
        return np.asarray(v)


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file: Union[str, dict]):
        """Resolve a ds_inference checkpoint description (json path or dict)
        to (loader-or-dict, type, version). Mirrors reference :24."""
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        else:
            data = json_file
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        if sd_type.lower() in ("bloom", "ds_model"):
            return data  # consumed directly by the HF import path
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list: Sequence, sd_type: str = "Megatron",
                      version=None) -> "SDLoaderBase":
        if sd_type == "Megatron":
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"unsupported checkpoint type {sd_type!r}")


class SDLoaderBase(ABC):
    """Holds the TP-sharded checkpoint list; ``load`` merges or splits to the
    requested degree. ``ckpt_list`` items are file paths (.pt/.npz) or
    in-memory state dicts."""

    def __init__(self, ckpt_list: Sequence, version=None):
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self.check_ckpt_list()

    def check_ckpt_list(self):
        assert len(self.ckpt_list) > 0, "empty checkpoint list"

    def _read(self, item) -> StateDict:
        if isinstance(item, dict):
            sd = item
        elif isinstance(item, str) and item.endswith(".npz"):
            sd = dict(np.load(item, allow_pickle=True))
        elif isinstance(item, str):
            import torch

            sd = torch.load(item, map_location="cpu", weights_only=False)
        else:
            raise TypeError(f"cannot read checkpoint shard from {type(item)}")
        return sd

    def get_module(self, sd: StateDict) -> StateDict:
        return sd.get("module", sd)

    def set_module(self, sd: StateDict, module: StateDict) -> StateDict:
        if "module" in sd:
            sd = dict(sd)
            sd["module"] = module
            return sd
        return module

    def get_checkpoint_version(self, sd: StateDict):
        if self.version is not None:
            return self.version
        return sd.get("checkpoint_version", 0)

    def load(self, mp_world_size: int, mp_rank: int) -> Tuple[StateDict, int]:
        """Return (state dict for ``mp_rank`` at degree ``mp_world_size``,
        number of source shards consumed)."""
        src = len(self.ckpt_list)
        if src == mp_world_size:
            sd = self._read(self.ckpt_list[mp_rank])
            module = {k: _to_numpy(v)
                      for k, v in self.get_module(sd).items()}
            return self.set_module(sd, module), 1
        if src > mp_world_size:
            return self.merge_state_dict(mp_world_size, mp_rank)
        return self.split_state_dict(mp_world_size, mp_rank)

    @abstractmethod
    def merge_state_dict(self, mp_world_size: int, mp_rank: int): ...

    @abstractmethod
    def split_state_dict(self, mp_world_size: int, mp_rank: int): ...


class MegatronSDLoader(SDLoaderBase):
    """Merge/split Megatron mp_rank shards by key-name category.

    QKV layouts by checkpoint version (reference :220):
      v0   [(3·np·hn), h] — q/k/v stacked whole-tensor; merge interleaves
      v1/2 [(np·…·3·…), h] — per-head grouped; plain concat on dim 0
    """

    def merge_query_key_value(self, params: List[np.ndarray], ckpt_ver):
        if ckpt_ver == 0:
            assert params[0].shape[0] % 3 == 0
            thirds = [np.split(p, 3, axis=0) for p in params]
            return np.concatenate(
                [np.concatenate([t[i] for t in thirds], axis=0)
                 for i in range(3)], axis=0)
        if ckpt_ver in (1.0, 2.0, 1, 2):
            return np.concatenate(params, axis=0)
        raise ValueError(f"unsupported checkpoint version {ckpt_ver}")

    def split_query_key_value(self, param: np.ndarray, num_to_split: int,
                              offset: int, ckpt_ver):
        if ckpt_ver == 0:
            assert param.shape[0] % 3 == 0
            thirds = np.split(param, 3, axis=0)
            assert thirds[0].shape[0] % num_to_split == 0
            return np.concatenate(
                [np.split(t, num_to_split, axis=0)[offset] for t in thirds],
                axis=0)
        if ckpt_ver in (1.0, 2.0, 1, 2):
            assert param.shape[0] % num_to_split == 0
            return np.split(param, num_to_split, axis=0)[offset]
        raise ValueError(f"unsupported checkpoint version {ckpt_ver}")

    def merge_state_dict(self, mp_world_size: int, mp_rank: int):
        src = len(self.ckpt_list)
        assert src % mp_world_size == 0, (src, mp_world_size)
        num_to_merge = src // mp_world_size
        shards = self.ckpt_list[mp_rank * num_to_merge:
                                (mp_rank + 1) * num_to_merge]
        sd_list = [self._read(s) for s in shards]
        client_list = [{k: _to_numpy(v) for k, v in self.get_module(sd).items()}
                       for sd in sd_list]
        ckpt_ver = self.get_checkpoint_version(sd_list[0])
        merged: StateDict = {}
        for key in client_list[0]:
            vals = [c[key] for c in client_list]
            if any(s in key for s in _ROW_PARALLEL):
                merged[key] = np.concatenate(vals, axis=1)
            elif any(s in key for s in _QKV):
                merged[key] = self.merge_query_key_value(vals, ckpt_ver)
            elif any(s in key for s in _COL_PARALLEL):
                merged[key] = np.concatenate(vals, axis=0)
            else:
                merged[key] = vals[0]
        log_dist(f"state_dict_factory: merged {num_to_merge} shards → "
                 f"rank {mp_rank}/{mp_world_size} (ckpt_ver={ckpt_ver})")
        return self.set_module(sd_list[0], merged), num_to_merge

    def split_state_dict(self, mp_world_size: int, mp_rank: int):
        src = len(self.ckpt_list)
        assert mp_world_size % src == 0, (src, mp_world_size)
        num_to_split = mp_world_size // src
        ckpt_index = mp_rank // num_to_split
        offset = mp_rank % num_to_split
        sd = self._read(self.ckpt_list[ckpt_index])
        client = {k: _to_numpy(v) for k, v in self.get_module(sd).items()}
        ckpt_ver = self.get_checkpoint_version(sd)
        out: StateDict = {}
        for key, value in client.items():
            if any(s in key for s in _ROW_PARALLEL):
                assert value.shape[1] % num_to_split == 0
                out[key] = np.split(value, num_to_split, axis=1)[offset]
            elif any(s in key for s in _QKV):
                out[key] = self.split_query_key_value(
                    value, num_to_split, offset, ckpt_ver)
            elif any(s in key for s in _COL_PARALLEL):
                assert value.shape[0] % num_to_split == 0
                out[key] = np.split(value, num_to_split, axis=0)[offset]
            else:
                out[key] = value
        log_dist(f"state_dict_factory: split shard {ckpt_index} "
                 f"{num_to_split}-way → rank {mp_rank}/{mp_world_size}")
        return self.set_module(sd, out), 1
