"""Runtime state offload/reload — ZeRO-Offload's ``offload_states`` API.

Capability parity with the reference engine API
(``runtime/engine.py:4533 offload_states / :4564 reload_states`` and the
ZeRO-1/2 implementation ``runtime/zero/stage_1_and_2.py:2725``): move selected
engine-owned state tensors out of accelerator memory between steps and bring
them back on demand.

Since PR 12 this module is a thin consumer of the tiered memory subsystem
(``deepspeed_tpu/memory``; docs/memory.md): ``device='cpu'`` places the
selected trees on the TieredStore's HOST tier (real ``pinned_host`` memory
kinds where the backend has a host space, ``HostBuffer`` numpy residency on
the single-memory CPU mesh — same API, and host-tier leaves leave the device
allocator either way), ``device='nvme'`` spills through the FILE tier (the
``swap_tensor`` aio stack; leaves become ``SwappedTensorMeta`` records).
Reload restores the exact sharded device state through the store's
prefetch/restore path — transfers ride the shared transfer worker.
"""

from __future__ import annotations

import enum
import os
from typing import Any, Iterable, Optional, Set

from ..memory.placement import offloaded_memory_kinds  # noqa: F401 (re-export)
from ..utils.logging import log_dist


class OffloadStateTypeEnum(str, enum.Enum):
    """Reference: ``runtime/zero/offload_states.py`` enum (optim_states,
    hp_params, lp_params, lp_grads, contiguous_grad_buffer)."""

    optim_states = "optim_states"
    hp_params = "hp_params"
    lp_params = "lp_params"
    lp_grads = "lp_grads"
    contiguous_grad_buffer = "contiguous_grad_buffer"


class OffloadDeviceEnum(str, enum.Enum):
    """Reference: ``runtime/zero/offload_config.py:14``."""

    none = "none"
    cpu = "cpu"
    nvme = "nvme"


def _engine_store(engine):
    """The engine's TieredStore (created by engine init when the
    ``memory.tiering`` block is on; lazily here otherwise)."""
    store = getattr(engine, "tiered_store", None)
    if store is None:
        from ..memory import TieredStore

        store = TieredStore(getattr(getattr(engine, "config", None),
                                    "memory", None) and
                            engine.config.memory.tiering)
        engine.tiered_store = store
    return store


def _nvme_dir(engine) -> str:
    import tempfile

    zc = getattr(engine, "config", None)
    swap_dir = None
    if zc is not None:
        oo = getattr(zc.zero_config, "offload_optimizer", None)
        swap_dir = getattr(oo, "nvme_path", None)
        mt = getattr(getattr(zc, "memory", None), "tiering", None)
        swap_dir = swap_dir or getattr(mt, "nvme_path", None)
    return swap_dir or os.path.join(tempfile.gettempdir(),
                                    "dstpu_offload_states")


def offload_engine_states(engine, include: Optional[Iterable] = None,
                          device: str = "cpu", pin_memory: bool = True,
                          non_blocking: bool = False) -> None:
    """Move the selected state groups to the host (or file) tier.

    ``non_blocking`` keeps parity with the reference signature; the tiered
    store's transfers are asynchronous either way (device_put DMA on
    host-space backends, transfer-worker copies on the CPU mesh), so it is
    accepted and ignored.
    """
    if device == OffloadDeviceEnum.none:
        return
    if getattr(engine, "_offloaded_tiers", None):
        # offload is NOT idempotent across tiers (a second pass would try to
        # move the already-replaced leaf trees themselves)
        log_dist("offload_states: states already offloaded; skipping")
        return
    if include is None:
        include = {OffloadStateTypeEnum.optim_states,
                   OffloadStateTypeEnum.hp_params}
    else:
        include = {OffloadStateTypeEnum(s) for s in include}
    st = engine.state
    store = _engine_store(engine)

    if device == OffloadDeviceEnum.nvme:
        # disk tier: spill through the store's FILE tier (ZeRO-Infinity
        # analog — the swap_tensor aio stack underneath). The live leaves
        # are replaced by SwappedTensorMeta trees; reload streams them back
        # and re-shards.
        store.nvme_dir = store.nvme_dir or _nvme_dir(engine)
        tier = "file"
    else:
        tier = "host"
    store.pin = bool(pin_memory)

    if OffloadStateTypeEnum.optim_states in include:
        st = st._replace(opt_state=store.offload(
            st.opt_state, tier, name="optim_states"))
    if OffloadStateTypeEnum.hp_params in include:
        st = st._replace(params=store.offload(
            st.params, tier, name="hp_params"))
    # lp_params / lp_grads / contiguous_grad_buffer: the compiled step neither
    # keeps low-precision shadows nor a persistent grad buffer between steps
    # (grads live only inside the jit step), so these are vacuously offloaded.
    engine.state = st
    engine._offloaded_tiers = {s.value: tier for s in include}
    engine._states_offloaded = True
    log_dist(f"offloaded {sorted(s.value for s in include)} -> {tier} tier"
             + (f" ({store.nvme_dir})" if tier == "file" else ""))


def reload_engine_states(engine, non_blocking: bool = False) -> None:
    """Reference ``reload_states``: bring everything back to device memory.
    Both trees prefetch FIRST (every transfer in flight on the worker)
    before either waits — the double-buffered restore."""
    st = engine.state
    store = _engine_store(engine)
    tiers = getattr(engine, "_offloaded_tiers", None) or {}

    handles = {}
    if "optim_states" in tiers or tiers == {}:
        sh = None
        if "optim_states" in tiers and hasattr(engine, "opt_state_specs"):
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            try:
                sh = jax.tree.map(
                    lambda s: NamedSharding(engine.mesh_mgr.mesh, s),
                    engine.opt_state_specs,
                    is_leaf=lambda x: isinstance(x, P))
            except Exception:
                sh = None
        handles["opt_state"] = store.prefetch(st.opt_state, sh)
    if "hp_params" in tiers or tiers == {}:
        sh = getattr(engine, "_master_shardings", None) \
            if "hp_params" in tiers else None
        handles["params"] = store.prefetch(st.params, sh)
    if "opt_state" in handles:
        st = st._replace(opt_state=handles["opt_state"].wait())
    if "params" in handles:
        st = st._replace(params=handles["params"].wait())
    engine.state = st
    engine._offloaded_tiers = None
    engine._states_offloaded = False
    log_dist("reloaded offloaded states -> device")
