"""Runtime state offload/reload — ZeRO-Offload's ``offload_states`` API.

Capability parity with the reference engine API
(``runtime/engine.py:4533 offload_states / :4564 reload_states`` and the
ZeRO-1/2 implementation ``runtime/zero/stage_1_and_2.py:2725``): move selected
engine-owned state tensors out of accelerator memory between steps and bring
them back on demand.

TPU-first: there is no ``.to('cpu')`` — arrays move by ``jax.device_put`` onto
the SAME sharding with ``memory_kind='pinned_host'``; the transfer is async
DMA over PCIe, sharding (ZeRO partitioning) is preserved, and a subsequent
donated-jit step can consume host-resident inputs with XLA streaming them
back. ``pin_memory=False`` selects ``unpinned_host``.
"""

from __future__ import annotations

import enum
import os
from typing import Any, Iterable, Optional, Set

import jax

from ..utils.logging import log_dist


class OffloadStateTypeEnum(str, enum.Enum):
    """Reference: ``runtime/zero/offload_states.py`` enum (optim_states,
    hp_params, lp_params, lp_grads, contiguous_grad_buffer)."""

    optim_states = "optim_states"
    hp_params = "hp_params"
    lp_params = "lp_params"
    lp_grads = "lp_grads"
    contiguous_grad_buffer = "contiguous_grad_buffer"


class OffloadDeviceEnum(str, enum.Enum):
    """Reference: ``runtime/zero/offload_config.py:14``."""

    none = "none"
    cpu = "cpu"
    nvme = "nvme"


def _move_tree(tree: Any, memory_kind: str) -> Any:
    """device_put every array leaf onto its own sharding with a new memory
    kind — a no-op for leaves already there."""

    def move(leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        sh = leaf.sharding
        if getattr(sh, "memory_kind", None) == memory_kind:
            return leaf
        return jax.device_put(leaf, sh.with_memory_kind(memory_kind))

    return jax.tree.map(move, tree)


def offloaded_memory_kinds(tree: Any) -> Set[str]:
    kinds: Set[str] = set()
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            kinds.add(getattr(leaf.sharding, "memory_kind", "device"))
    return kinds


def offload_engine_states(engine, include: Optional[Iterable] = None,
                          device: str = "cpu", pin_memory: bool = True,
                          non_blocking: bool = False) -> None:
    """Move the selected state groups to host memory.

    ``non_blocking`` keeps parity with the reference signature; device_put is
    always async in JAX (dispatch returns immediately), so it is accepted and
    ignored.
    """
    if device == OffloadDeviceEnum.none:
        return
    if getattr(engine, "_nvme_swappers", None):
        # nvme offload is NOT idempotent (a second pass would try to swap the
        # meta trees themselves and leak the first swapper's files)
        log_dist("offload_states: states already nvme-offloaded; skipping")
        return
    if include is None:
        include = {OffloadStateTypeEnum.optim_states,
                   OffloadStateTypeEnum.hp_params}
    else:
        include = {OffloadStateTypeEnum(s) for s in include}
    st = engine.state

    if device == OffloadDeviceEnum.nvme:
        # disk tier: spill through the swap_tensor stack (ZeRO-Infinity
        # analog — reference routes offload_states device='nvme' to the
        # partitioned swappers). The live leaves are replaced by their
        # SwappedTensorMeta trees; reload streams them back and re-shards.
        import tempfile

        from .swap_tensor.swapper import PartitionedOptimizerSwapper

        zc = getattr(engine, "config", None)
        swap_dir = None
        if zc is not None:
            oo = getattr(zc.zero_config, "offload_optimizer", None)
            swap_dir = getattr(oo, "nvme_path", None)
        swap_dir = swap_dir or os.path.join(tempfile.gettempdir(),
                                            "dstpu_offload_states")
        engine._nvme_swappers = {}
        if OffloadStateTypeEnum.optim_states in include:
            sw = PartitionedOptimizerSwapper(os.path.join(swap_dir, "opt"))
            st = st._replace(opt_state=sw.swap_out_optimizer(st.opt_state))
            engine._nvme_swappers["optim_states"] = sw
        if OffloadStateTypeEnum.hp_params in include:
            sw = PartitionedOptimizerSwapper(os.path.join(swap_dir, "params"))
            st = st._replace(params=sw.swap_out_optimizer(st.params))
            engine._nvme_swappers["hp_params"] = sw
        engine.state = st
        engine._states_offloaded = True
        log_dist(f"offloaded {sorted(s.value for s in include)} -> nvme "
                 f"({swap_dir})")
        return

    kind = "pinned_host" if pin_memory else "unpinned_host"
    if OffloadStateTypeEnum.optim_states in include:
        st = st._replace(opt_state=_move_tree(st.opt_state, kind))
    if OffloadStateTypeEnum.hp_params in include:
        st = st._replace(params=_move_tree(st.params, kind))
    # lp_params / lp_grads / contiguous_grad_buffer: the compiled step neither
    # keeps low-precision shadows nor a persistent grad buffer between steps
    # (grads live only inside the jit step), so these are vacuously offloaded.
    engine.state = st
    engine._states_offloaded = True
    log_dist(f"offloaded {sorted(s.value for s in include)} -> {kind}")


def _nvme_reload(engine, st):
    """Stream swapped trees back from disk and restore device shardings."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    swappers = engine._nvme_swappers

    def shardings_for(specs):
        return jax.tree.map(
            lambda s: NamedSharding(engine.mesh_mgr.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

    if "optim_states" in swappers:
        sw = swappers.pop("optim_states")
        host = sw.swap_in_optimizer(device_put=False)
        sh = shardings_for(engine.opt_state_specs)
        st = st._replace(opt_state=jax.tree.map(jax.device_put, host, sh))
        sw.purge()
    if "hp_params" in swappers:
        sw = swappers.pop("hp_params")
        host = sw.swap_in_optimizer(device_put=False)
        st = st._replace(params=jax.tree.map(
            jax.device_put, host, engine._master_shardings))
        sw.purge()
    return st


def reload_engine_states(engine, non_blocking: bool = False) -> None:
    """Reference ``reload_states``: bring everything back to device memory."""
    st = engine.state
    if getattr(engine, "_nvme_swappers", None):
        st = _nvme_reload(engine, st)
        engine.state = st._replace(
            params=_move_tree(st.params, "device"),
            opt_state=_move_tree(st.opt_state, "device"))
        engine._states_offloaded = False
        log_dist("reloaded nvme-offloaded states -> device")
        return
    engine.state = st._replace(
        params=_move_tree(st.params, "device"),
        opt_state=_move_tree(st.opt_state, "device"))
    engine._states_offloaded = False
    log_dist("reloaded offloaded states -> device")
