"""Hessian max-eigenvalue estimation by power iteration.

Reference parity: ``runtime/eigenvalue.py:13 Eigenvalue`` — estimates the
largest eigenvalue of each block's Hessian to modulate MoQ quantization
periods. The reference builds Hessian-vector products from retained autograd
graphs; in JAX an HVP is one ``jax.jvp``-of-``grad`` composition, and the
whole power iteration jit-compiles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in jax.tree.leaves(tree)))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree.map(lambda l: l / norm, tree), norm


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iterations: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.max_iterations = max_iterations
        self.tol = tol
        self.stability = stability
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn: Callable[[Any], jnp.ndarray],
                           params: Any, rng: Optional[jax.Array] = None
                           ) -> Tuple[float, Any]:
        """Power iteration on the Hessian of ``loss_fn`` at ``params`` →
        (max eigenvalue estimate, eigenvector pytree)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        keys = jax.random.split(rng, len(jax.tree.leaves(params)))
        flat, treedef = jax.tree_util.tree_flatten(params)
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, flat)])
        v, _ = _normalize(v)
        grad_fn = jax.grad(loss_fn)

        @jax.jit
        def hvp(p, vec):
            return jax.jvp(grad_fn, (p,), (vec,))[1]

        eig = jnp.asarray(0.0)
        for i in range(self.max_iterations):
            hv = hvp(params, v)
            v, norm = _normalize(hv)
            prev, eig = eig, norm
            if i > 0 and abs(float(eig - prev)) / max(float(eig), 1e-12) < self.tol:
                break
        if self.verbose:
            log_dist(f"eigenvalue converged in {i + 1} iters: {float(eig):.4g}")
        return float(eig) + self.stability, v

    def compute_layer_eigenvalues(self, loss_fn: Callable,
                                  params: Dict[str, Any],
                                  rng: Optional[jax.Array] = None
                                  ) -> Dict[str, float]:
        """Per-top-level-subtree eigenvalues (reference iterates layer
        blocks): other subtrees are held fixed."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        out = {}
        for i, key in enumerate(params):
            sub_loss = lambda sub: loss_fn({**params, key: sub})  # noqa: E731
            out[key], _ = self.compute_eigenvalue(
                sub_loss, params[key], jax.random.fold_in(rng, i))
        return out
