"""Activation checkpointing (rematerialization) — TPU-native.

Capability parity with the reference's Megatron-compatible reimplementation
(``deepspeed/runtime/activation_checkpointing/checkpointing.py``, flags at
:42-45: ``PARTITION_ACTIVATIONS``, ``CPU_CHECKPOINT``, ``CONTIGUOUS_CHECKPOINTING``,
``SYNCHRONIZE``, ``PROFILE_TIME``), redesigned for XLA:

- the reference re-runs the forward in backward by stashing inputs (optionally
  partitioned across TP ranks and/or offloaded to CPU) and replaying with a
  tracked RNG state; under ``jax.checkpoint`` the SAME trade is expressed as a
  *policy* — which intermediates to save vs recompute — and XLA schedules the
  recompute; RNG replay is free because JAX RNG is explicit (no state tracker
  needed — ``get_cuda_rng_tracker`` has no analog by design);
- ``partition_activations`` → saved residuals carry their sharding (they are
  already TP/SP-sharded under SPMD; nothing to do at save time);
- ``cpu_checkpointing`` → ``save_and_offload_only_these_names`` /
  ``offload_checkpoint`` policies that park residuals in host memory
  (``memory_kind='pinned_host'``) between forward and backward.

Policies are selected by name from the config block
(``ActivationCheckpointingConfig.policy``) so models stay policy-agnostic.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax

from ...utils.logging import logger

_config: Optional[Any] = None

# names models may attach via jax.ad_checkpoint.checkpoint_name to mark
# offloadable / saveable residuals. The model families EMIT (training
# blocks, all O(batch·seq) — never the O(seq²) attention internals):
#
#   "qkv_proj" — the q/k/v projection outputs (pre-rotary),
#   "attn_mix" — the attention output BEFORE the wo projection (what the
#                wo backward consumes — saving it is what actually spares
#                the attention recompute),
#   "attn_out" — the attention output projection,
#   "mlp_gate"/"mlp_up" — the FFN gate/up projections (pre-activation),
#   "mlp_out" — the FFN down-projection.
#
# A tier-1 lint test pins that every name a registered policy saves is
# actually emitted by the model families, so a model edit cannot silently
# turn a policy into a no-op.
CHECKPOINT_NAMES = ("residual", "attn_out", "mlp_out", "block_out")
MATMUL_CHECKPOINT_NAMES = ("qkv_proj", "attn_mix", "attn_out",
                           "mlp_gate", "mlp_up", "mlp_out")

# policy name -> the checkpoint names it saves (name-based policies only;
# shared with the schema registry + the model-emission lint test)
POLICY_SAVED_NAMES = {
    "save_names": CHECKPOINT_NAMES,
    "offload": CHECKPOINT_NAMES,
    # break the recompute CHAIN cheaply: with the attention branch output
    # saved, everything downstream of it (the MLP half) recomputes without
    # re-running attention — but attention's own backward still replays it
    "save_attn_out": ("attn_out",),
    # save EVERY big per-layer MXU dot result: the backward recomputes only
    # cheap elementwise work (norms, rotary, silu) plus the one QK^T dot
    # the O(seq²) probs would otherwise cost in memory — the bounded-HBM
    # analog of dots_saveable (which also saves the quadratic scores)
    "save_big_matmuls": MATMUL_CHECKPOINT_NAMES,
}


def _host_offload_policy(names: Sequence[str]):
    """Save the named residuals, but in host memory — the ``CPU_CHECKPOINT``
    analog: residuals stream to host after forward and back before backward,
    overlapped by XLA's async copy scheduling."""
    cp = jax.checkpoint_policies
    if hasattr(cp, "save_and_offload_only_these_names"):
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(names),
            offload_src="device", offload_dst="pinned_host")
    logger.warning("offload remat policy unavailable; using save-names policy")
    return cp.save_only_these_names(*names)


POLICIES: dict = {}


def _register_policies():
    cp = jax.checkpoint_policies
    POLICIES.update({
        # recompute everything (the reference's default checkpoint() behavior)
        "full": cp.nothing_saveable,
        "none": None,                       # no remat at all
        # save matmul outputs, recompute cheap elementwise — the usual best
        # trade on TPU (MXU results are expensive to recompute, VPU ops cheap)
        "dots_saveable": cp.dots_saveable,
        "dots_with_no_batch_dims": cp.checkpoint_dots_with_no_batch_dims,
        "save_names": cp.save_only_these_names(*CHECKPOINT_NAMES),
        # selective remat (the HBM-vs-step-time middle ground between
        # "full" — the ~8N-flops-accounted-as-6N tax — and "none"): see
        # POLICY_SAVED_NAMES for exactly what each saves and why
        "save_attn_out": cp.save_only_these_names(
            *POLICY_SAVED_NAMES["save_attn_out"]),
        "save_big_matmuls": cp.save_only_these_names(
            *POLICY_SAVED_NAMES["save_big_matmuls"]),
        "offload": _host_offload_policy(CHECKPOINT_NAMES),
        "offload_dots": (cp.offload_dot_with_no_batch_dims("device", "pinned_host")
                         if hasattr(cp, "offload_dot_with_no_batch_dims")
                         else _host_offload_policy(CHECKPOINT_NAMES)),
    })


_register_policies()


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None):
    """API-parity shim for the reference's ``configure``
    (``checkpointing.py`` module-level). Stores the config; the knobs map to a
    remat policy choice rather than runtime buffer management."""
    global _config
    import types

    src = deepspeed_config
    if src is not None and hasattr(src, "activation_checkpointing"):
        src = src.activation_checkpointing
    # copy into module-local state — never mutate the caller's config object
    cfg = types.SimpleNamespace(
        policy=getattr(src, "policy", "full") if src is not None else "full",
        cpu_checkpointing=bool(checkpoint_in_cpu
                               or getattr(src, "cpu_checkpointing", False)),
        partition_activations=bool(partition_activations
                                   or getattr(src, "partition_activations",
                                              False)))
    if cfg.cpu_checkpointing:
        cfg.policy = "offload"
    _config = cfg
    return _config


def is_configured() -> bool:
    return _config is not None


def reset():
    """Reference ``reset()`` frees stashed buffers; JAX holds none."""
    global _config
    _config = None


def get_policy(name: Optional[str] = None):
    """Resolve a policy name (or the configured one) to a jax.checkpoint policy."""
    if name is None:
        name = getattr(_config, "policy", "full") if _config else "full"
    if name not in POLICIES:
        raise ValueError(f"unknown remat policy {name!r}; have {sorted(POLICIES)}")
    return POLICIES[name]


def checkpoint(function: Callable, *args, policy: Optional[str] = None,
               prevent_cse: bool = True, static_argnums=()):
    """Reference ``checkpoint(function, *args)``: run ``function`` under
    rematerialization. Returns the function's output; gradients recompute the
    forward according to the selected policy."""
    name = policy or (getattr(_config, "policy", "full") if _config else "full")
    if name == "none":
        return function(*args)
    wrapped = jax.checkpoint(function, policy=get_policy(name),
                             prevent_cse=prevent_cse,
                             static_argnums=static_argnums)
    # Bare remat executes its body (and the backward's replay) as ONE fused
    # XLA computation, whose scheduling can differ from op-by-op eager
    # dispatch by float-noise; the jit wrapper makes checkpoint() grads
    # match plain jax.grad exactly, eagerly and under autodiff traces, and
    # is a semantic no-op (inlined pjit) under an outer jit.
    wrapped = jax.jit(wrapped, static_argnums=static_argnums)
    return wrapped(*args)


def checkpoint_wrapper(function: Callable, policy: Optional[str] = None,
                       static_argnums=()) -> Callable:
    """Decorator form: wrap a layer-apply fn once, call many times (plays well
    with ``lax.scan`` over stacked layers)."""
    name = policy or (getattr(_config, "policy", "full") if _config else "full")
    if name == "none":
        return function
    return jax.checkpoint(function, policy=get_policy(name),
                          static_argnums=static_argnums)


class CheckpointFunction:
    """Name-parity shim for the reference's autograd.Function
    (``checkpointing.py CheckpointFunction``)."""

    @staticmethod
    def apply(run_function, *args):
        return checkpoint(run_function, *args)


def saved_bytes(function: Callable, *args,
                policy: Optional[str] = None) -> Optional[int]:
    """Total bytes of NON-ARGUMENT residuals the backward of ``function``
    keeps alive under the named ``policy`` — the trace-time, exact
    measurement behind the HBM-vs-step-time sweep (``bench.py`` remat sweep,
    ``Train/remat/saved_bytes_<policy>`` telemetry) and the policy-ordering
    tests: ``none`` (no remat) saves every needed intermediate,
    ``save_big_matmuls`` ⊇ ``save_attn_out``, ``full`` saves nothing.

    ``policy=None``/``"none"`` measures the un-rematerialized function.
    Returns None when jax's saved-residuals introspection is unavailable
    (the sweep then falls back to allocator stats)."""
    try:
        from jax.ad_checkpoint import saved_residuals  # newer jax
    except ImportError:
        try:
            from jax._src.ad_checkpoint import saved_residuals
        except ImportError:  # pragma: no cover - depends on jax version
            return None
    wrapped = function
    if policy not in (None, "none"):
        wrapped = jax.checkpoint(function, policy=get_policy(policy))
    total = 0
    for aval, desc in saved_residuals(wrapped, *args):
        if "argument" in desc:
            continue  # inputs are resident either way
        n = 1
        for d in aval.shape:
            n *= int(d)
        total += n * aval.dtype.itemsize
    return total


def model_parallel_cuda_manual_seed(seed: int):
    """Reference RNG tracker entry (``checkpointing.py
    model_parallel_cuda_manual_seed``): JAX threads PRNG keys explicitly, so a
    global tracker is unnecessary; kept for API parity — returns a key."""
    return jax.random.PRNGKey(seed)
