from .checkpointing import (CheckpointFunction, checkpoint, configure,
                            get_policy, is_configured, model_parallel_cuda_manual_seed,
                            reset)

__all__ = [
    "CheckpointFunction", "checkpoint", "configure", "get_policy",
    "is_configured", "model_parallel_cuda_manual_seed", "reset",
]
