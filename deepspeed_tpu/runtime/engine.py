"""Training engine: config → sharded, jit-compiled train step.

Capability parity with the reference's ``DeepSpeedEngine``
(``runtime/engine.py:208``) and ``deepspeed.initialize``
(``deepspeed/__init__.py:80``) — redesigned TPU-first:

- the reference orchestrates forward/backward/step at Python runtime with
  hooks, bucketed allreduce streams and loss-scale bookkeeping; here the whole
  micro-step loop (GAS accumulation, loss scaling, overflow skip, grad
  clipping, optimizer update, LR schedule) is ONE jit-compiled function with
  donated buffers — XLA overlaps the ZeRO collectives it implies with compute;
- ZeRO stages are sharding specs from ``runtime/partitioning.py`` — no
  partitioning code in the hot path at all;
- ``forward()/backward()/step()`` are provided as API-parity shims over the
  compiled step (they stage micro-batches and execute at the GAS boundary).

The engine still owns the runtime-side concerns that XLA cannot: dataloading,
checkpoint save/load, monitoring, timers, elasticity.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm import comm as dist
from ..comm.mesh import BATCH_AXES, MeshManager, init_mesh
from ..ops.optimizers import Optimizer, get_optimizer
from ..telemetry.profiler import annotate as _annotate
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, BACKWARD_MICRO_TIMER,
                           FORWARD_GLOBAL_TIMER, FORWARD_MICRO_TIMER,
                           STEP_GLOBAL_TIMER, STEP_MICRO_TIMER,
                           TRAIN_BATCH_TIMER, SynchronizedWallClockTimer,
                           ThroughputTimer)
from .config import DeepSpeedTPUConfig, parse_config
from .lr_schedules import LRScheduler, Schedule, constant, get_schedule
from .partitioning import Partitioner, shapes_of
from .precision import (LossScaleState, PrecisionPolicy, grads_finite,
                        make_loss_scaler, scale_loss, unscale_grads,
                        update_loss_scale)


# --------------------------------------------------------------------------- #
# model description — what the engine needs from a user model
# --------------------------------------------------------------------------- #
@dataclass
class ModelSpec:
    """The JAX-native counterpart of passing an ``nn.Module`` to
    ``deepspeed.initialize``: a pure loss function over a param pytree, plus
    optional init / logical-sharding metadata."""

    loss_fn: Callable[..., Tuple[jnp.ndarray, Dict[str, Any]]]
    init_fn: Optional[Callable[[jax.Array], Any]] = None
    params: Optional[Any] = None
    logical_axes: Optional[Any] = None
    apply_fn: Optional[Callable[..., Any]] = None
    name: str = "model"
    # whether the model routes its stacked layers through pipeline_apply when
    # the mesh has a pipe axis — keeps the partitioner's 'layers'->'pipe' rule
    # in sync with the model's actual execution path
    pipeline_capable: bool = True
    # optional 1F1B train-step grads: (params, batch, loss_scale) ->
    # (grads_of_scaled_loss, unscaled_loss, aux). Used instead of jax.grad
    # when the mesh has pipe >= 2 (runtime/pipe/one_f_one_b.py)
    pipeline_grad_fn: Optional[Callable[..., Any]] = None
    # optional fused unembed+CE loss: (params, batch, *, shards) ->
    # (loss, aux) that never materializes the [B, S, V] logits tensor.
    # Routed instead of loss_fn when config.sequence.tiled_loss is on
    # (sequence/tiled.py tiled_fused_logits_loss).
    tiled_loss_fn: Optional[Callable[..., Any]] = None

    def materialize(self, rng: jax.Array):
        if self.params is not None:
            return self.params
        if self.init_fn is None:
            raise ValueError("ModelSpec needs params or init_fn")
        return self.init_fn(rng)


class TrainState(NamedTuple):
    """The full jit-carried state (a pytree)."""

    step: jnp.ndarray
    params: Any            # fp32 master params
    opt_state: Any
    loss_scale: LossScaleState
    skipped_steps: jnp.ndarray
    # LoCo error-feedback residuals (comms_overlap.loco + qgZ): one fp32
    # array of global shape [dp_world, *leaf.shape] per quantized-reduce
    # leaf, sharded over the batch axes so each device carries ITS OWN
    # quantization error. Empty tuple (no pytree leaves) when disabled, so
    # the default step's compiled program is unchanged.
    loco_residual: Any = ()


class StepOutput(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    lr: jnp.ndarray
    loss_scale: jnp.ndarray
    overflow: jnp.ndarray
    aux: Dict[str, Any]


from .utils import global_norm as _global_norm  # shared with runtime.utils


def _enable_compile_cache(config) -> None:
    """Persistent XLA compilation cache: re-runs skip the multi-minute TPU
    compiles. ``compile_cache_dir``: None → fall back to
    ``$DSTPU_COMPILE_CACHE``; "" → explicitly OFF even with the env var set.
    A cache problem must never break training — best-effort only."""
    path = getattr(config, "compile_cache_dir", None)
    if path is None:
        path = os.environ.get("DSTPU_COMPILE_CACHE", "")
    if not path:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as e:
        log_dist(f"compile cache unavailable ({e}); continuing without")
        return
    try:  # optional knob — its absence must not disable the active cache
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass
    log_dist(f"persistent compilation cache: {path}")


class DeepSpeedTPUEngine:
    """See module docstring. Construct via :func:`initialize`."""

    def __init__(self, model: ModelSpec, config: DeepSpeedTPUConfig,
                 mesh_mgr: MeshManager, optimizer: Optional[Optimizer] = None,
                 lr_schedule: Optional[Schedule] = None,
                 training_data: Optional[Iterable] = None,
                 rng: Optional[jax.Array] = None):
        self.model = model
        self.config = config
        self.mesh_mgr = mesh_mgr
        _enable_compile_cache(config)
        self.global_steps = 0
        self.skipped_steps = 0
        self.micro_steps = 0
        # host-side token counter (universal checkpoint v2 carries it so an
        # elastic resume keeps the token budget accounting exact)
        self.global_tokens = 0
        self._staged_batches: List[Any] = []
        self._staged_loss: Optional[jnp.ndarray] = None
        self.training_dataloader = None

        # --- precision ---
        self.precision = PrecisionPolicy.from_config(config)

        # --- optimizer (reference _configure_optimizer :1597) ---
        # one construction site: a config with param_groups defers building
        # until params materialize (leaf names drive the group match); a
        # user-supplied optimizer always wins, but dropping the config's
        # param_groups silently would be a trap — warn.
        config_groups = config.optimizer.param_groups
        if optimizer is not None and config_groups:
            logger.warning(
                "optimizer.param_groups in the config are IGNORED because an "
                "optimizer object was passed to initialize()")
        build_grouped = optimizer is None and bool(config_groups)
        if optimizer is None and not build_grouped:
            optimizer = get_optimizer(config.optimizer.type or "adamw",
                                      **config.optimizer.params)

        # --- params + sharding ---
        rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        params = model.materialize(rng)
        params = jax.tree.map(
            lambda p: p.astype(self.precision.param_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)

        if build_grouped:
            # param-group analog (reference torch param_groups): per-group
            # hyper overrides by leaf-path pattern — needs the materialized
            # tree for leaf names, hence after materialize
            from ..ops.optimizers import grouped_optimizer

            optimizer = grouped_optimizer(
                config.optimizer.type or "adamw", params,
                config_groups, **config.optimizer.params)
            # abstract leaves only — the wrapper needs paths/structure, and
            # holding real arrays here would pin the initial params forever
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
            self._grouped_ctor = (config.optimizer.type or "adamw",
                                  [dict(g) for g in config_groups],
                                  dict(config.optimizer.params), abstract)
        self.optimizer = optimizer
        self.base_lr = float(optimizer.hyperparams.get("lr", 1.0)) or 1.0
        if lr_schedule is None:
            lr_schedule = get_schedule(config.scheduler.type,
                                       config.scheduler.params,
                                       base_lr=self.base_lr)
        self.lr_schedule = lr_schedule
        self.lr_scheduler = LRScheduler(lr_schedule)
        # set_lr pin, fed to the compiled step as a TRACED scalar (< 0 =
        # follow the schedule) so changing the LR never triggers a recompile
        self._lr_override = jnp.asarray(-1.0, jnp.float32)

        if config.zero_config.zero_quantized_gradients and \
                config.zero_config.stage not in (2,):
            raise ValueError(
                "zero_quantized_gradients (qgZ) requires ZeRO stage 2 — the "
                "quantized reduce-scatter produces grads in the stage-2 "
                "sharded layout (stage 3 param gathering is a separate path)")
        if config.zero_config.zero_quantized_weights and \
                config.zero_config.stage >= 3 and \
                int(config.zero_config.zero_hpz_partition_size) <= 1 and \
                not (config.comms_overlap.enabled
                     and config.comms_overlap.layer_prefetch):
            logger.warning(
                "zero_quantized_weights at ZeRO-3 has no quantization "
                "boundary without comms_overlap.layer_prefetch (per-layer "
                "quantized gathers) or zero_hpz_partition_size > 1 "
                "(quantized primary gather) — params gather at use in full "
                "precision")

        # --- comms_overlap: gradient-comm overlap engine (comm/overlap.py) ---
        co = config.comms_overlap
        self.comms_overlap_flags: List[str] = []
        self._overlap_plan_cache = None
        if co.enabled:
            if config.zero_config.stage >= 3 and not co.layer_prefetch:
                raise ValueError(
                    "comms_overlap requires ZeRO stage <= 2: stage 3's "
                    "gather-on-use parameter sharding conflicts with the "
                    "manual data-parallel reduction region (set "
                    "comms_overlap.layer_prefetch for the ZeRO-3 per-layer "
                    "all-gather prefetch instead)")
            if config.zero_config.stage >= 3:
                log_dist("comms_overlap: ZeRO-3 — gradient-reduction overlap "
                         "engine disabled (params gather on use); per-layer "
                         "all-gather prefetch + XLA flags active")
            if mesh_mgr.pp_world_size > 1:
                log_dist("comms_overlap: pipeline axis active — the overlap "
                         "engine is disabled (1F1B owns its own reduction); "
                         "XLA flags still apply")
            if co.loco and not (config.zero_config.zero_quantized_gradients
                                or co.quantized_all_reduce):
                logger.warning(
                    "comms_overlap.loco has no effect without "
                    "zero_quantized_gradients (qgZ) or quantized_all_reduce "
                    "— there is no quantizer to error-compensate")
            from ..comm.overlap import apply_xla_overlap_flags

            self.comms_overlap_flags = apply_xla_overlap_flags(co)

        from ..comm.mesh import ZERO_AXES as _ZERO_AXES

        zero_axes = _ZERO_AXES
        secondary_axes = None
        if mesh_mgr.mics_shard_size > 1:
            hpz = int(config.zero_config.zero_hpz_partition_size) > 1 and \
                int(config.zero_config.mics_shard_size) <= 1
            if hpz and config.zero_config.stage >= 3:
                # ZeRO++ hpZ: PRIMARY partition (masters / opt state / grad
                # reduce-scatter) over the full ZeRO axes — no memory is
                # given back — plus a SECONDARY parameter partition inside
                # the 'zero_shard' (ICI island) sub-axis, so every fwd/bwd
                # all-gather resolves intra-island and only the once-per-
                # step primary gather crosses 'data' (the DCN tier).
                secondary_axes = tuple(a for a in _ZERO_AXES if a != "data")
                log_dist(
                    "ZeRO++ hpZ: secondary param partition over "
                    f"{secondary_axes} (size {mesh_mgr.mics_shard_size}); "
                    "primary partition keeps the full ZeRO axes")
            else:
                # MiCS: shard within the 'zero_shard' group, replicate
                # across 'data' groups (reference runtime/zero/mics.py:63).
                # hpZ below stage 3 also lands here: without gather-on-use
                # params there is no secondary gather to keep intra-island.
                zero_axes = tuple(a for a in _ZERO_AXES if a != "data")
                if hpz:
                    log_dist("zero_hpz_partition_size below ZeRO stage 3: "
                             "falling back to MiCS semantics (shard within "
                             "the group, replicate across 'data')")
        self.partitioner = Partitioner(
            mesh_mgr, zero_stage=config.zero_config.stage,
            zero_axes=zero_axes, secondary_axes=secondary_axes,
            tensor_parallel=mesh_mgr.tp_world_size > 1,
            pipeline_layers=model.pipeline_capable)
        shapes = shapes_of(params)
        if model.logical_axes is not None:
            axes = model.logical_axes
        elif mesh_mgr.tp_world_size > 1:
            # un-annotated model on a TP mesh: infer row/col-parallel rules
            # from param names (AutoTP — module_inject/auto_tp.py:194 analog)
            from ..module_inject import infer_logical_axes

            axes = infer_logical_axes(params)
            log_dist("AutoTP: inferred tensor-parallel sharding rules from "
                     "param names (no logical_axes on the ModelSpec)")
        else:
            # no metadata, no TP: replicate params (ZeRO still shards
            # masters/opt state over the largest divisible dim of each leaf)
            axes = jax.tree.map(lambda s: tuple([None] * len(s)), shapes,
                                is_leaf=lambda x: isinstance(x, tuple))
        # compute-time specs (TP always; +ZeRO at stage 3 — gather-on-use)
        param_specs = self.partitioner.param_specs(axes, shapes)
        # gradient specs: reduce-scattered from stage 2 (reference
        # stage_1_and_2.py:126 grad partitioning)
        grad_specs = self.partitioner.grad_specs(axes, shapes)
        # fp32 master + optimizer-state specs: sharded from stage 1
        # (reference bf16_optimizer.py:36 sharded fp32 masters)
        opt_specs = self.partitioner.opt_state_specs(axes, shapes)
        self.param_specs = param_specs
        self.grad_specs = grad_specs
        self.opt_param_specs = opt_specs
        # gathered (TP-only) layout — the target of the ZeRO all-gather:
        # feeds the layer-prefetch shardings AND the qwZ per-layer quantize
        # descriptors (_layer_prefetch_quant)
        self._qw_gather_specs = self.partitioner.gathered_param_specs(
            axes, shapes)
        self._param_shardings = self.partitioner.shardings(param_specs)
        self._grad_shardings = self.partitioner.shardings(grad_specs)
        self._master_shardings = self.partitioner.shardings(opt_specs)
        self._log_zero_sharding_summary(shapes, opt_specs)

        # --- ZeRO-Infinity: NVMe-streamed optimizer tier (reference
        # stage3.py:2412 sub-group swap cycle; offload_config device=nvme,
        # also reachable via memory.tiering.optimizer_tier=nvme) ---
        mt = config.memory.tiering
        self._nvme_opt = None
        if config.zero_config.offload_optimizer.device == "nvme" or \
                (mt.enabled and mt.optimizer_tier == "nvme"):
            self._configure_nvme_optimizer(params)
        # --- tiered memory: host-resident optimizer state with prefetch
        # overlapped under fwd/bwd (memory.tiering; docs/memory.md) ---
        self._tiered_opt = bool(mt.enabled and mt.optimizer_tier == "host")
        self._tiered_grad_step = None
        if self._tiered_opt:
            if self._nvme_opt is not None:
                raise ValueError("memory.tiering.optimizer_tier=host and an "
                                 "nvme optimizer tier are mutually exclusive")
            if jax.process_count() > 1:
                raise ValueError(
                    "memory.tiering.optimizer_tier=host is single-host for "
                    "now: the host tier materializes full numpy leaves, "
                    "which fails on non-addressable multi-host arrays")

        with mesh_mgr.activate():
            if self._nvme_opt is not None:
                # fp32 masters + moments live on NVMe; the device holds ONLY
                # the bf16/compute copy (stage layout — ZeRO-sharded at 3)
                params = jax.jit(
                    self.precision.cast_to_compute,
                    out_shardings=self._param_shardings)(params)
                opt_state = ()
                self.opt_state_specs = ()
            else:
                # masters live ZeRO-sharded from stage 1 up; the bf16 compute
                # copy is gathered per step in _loss (cast + constraint)
                params = jax.jit(
                    lambda p: p, out_shardings=self._master_shardings)(params)
                opt_state = self._init_opt_state(params)
            # scalars go through a jitted identity with explicit replicated
            # out_shardings: freshly-built uncommitted scalars would otherwise
            # differ from the step outputs' committed NamedSharding avals and
            # the SECOND train_batch would re-lower + re-COMPILE the whole
            # step (minutes on a tunnel TPU). Measured: 2 step_fn XLA
            # compilations without this, 1 with it.
            loss_scale = make_loss_scaler(config.fp16)
            repl = NamedSharding(mesh_mgr.mesh, P())
            step0, loss_scale, skipped0 = jax.jit(
                lambda s: s,
                out_shardings=jax.tree.map(lambda _: repl,
                                           (0, loss_scale, 0)))(
                (jnp.zeros((), jnp.int32), loss_scale,
                 jnp.zeros((), jnp.int32)))
            self.state = TrainState(
                step=step0,
                params=params,
                opt_state=opt_state,
                loss_scale=loss_scale,
                skipped_steps=skipped0,
            )

        # --- tiered store (deepspeed_tpu/memory): owns the transfer worker,
        # tier byte accounting and the Memory/tier/* telemetry. Cheap when
        # tiering is off (no thread until a tier is used); offload_states()
        # routes through it either way. ---
        from ..memory import TieredStore

        self.tiered_store = TieredStore(mt)
        if self._tiered_opt:
            # the optimizer state leaves the device between steps from the
            # very first train_batch (restored under the step's grad phase)
            self.state = self.state._replace(
                opt_state=self.tiered_store.offload(
                    self.state.opt_state, "host", name="optim_states"))
            log_dist("memory.tiering: optimizer state host-resident "
                     f"(pin_memory={mt.pin_memory}); H2D prefetch overlaps "
                     "fwd/bwd, D2H writeback overlaps the next step")

        if self._overlap_active():
            self._overlap_setup()  # static routing, cached for engine life
            if co.loco and (config.zero_config.zero_quantized_gradients
                            or co.quantized_all_reduce):
                self._init_loco_residuals()

        # --- comms_overlap.layer_prefetch: ZeRO-3 per-layer all-gather
        # prefetch (T3). Published process-wide (latest engine wins, like
        # activation_checkpointing.configure) so the model families' stacked
        # -layer scans pick it up at the next train-step trace. ---
        from ..comm.overlap import configure_layer_prefetch

        self._layer_prefetch_on = bool(
            co.enabled and co.layer_prefetch
            and config.zero_config.stage >= 3
            and mesh_mgr.pp_world_size <= 1)
        if co.enabled and co.layer_prefetch and not self._layer_prefetch_on:
            log_dist("comms_overlap.layer_prefetch has no effect here: it "
                     "needs ZeRO stage 3 (gather-on-use params) and no "
                     "pipeline axis — plain scan retained")
        # the per-layer gathers resolve over the axes the compute-param
        # layout is sharded on: the hpZ secondary (ICI) axes when set, the
        # full ZeRO axes otherwise — feeds the prefetch telemetry link class
        _gaxes = tuple(
            a for a in (self.partitioner.secondary_axes
                        if self.partitioner.secondary_axes is not None
                        else self.partitioner.zero_axes)
            if mesh_mgr.axis_size(a) > 1)
        # memory.tiering.param_tier=host composes here: the stacked layer
        # shards park in host memory and each layer's host→HBM copy-in is
        # issued by the SAME prefetch pipeline as the all-gather (identity
        # on single-memory backends — docs/memory.md compose rules)
        _param_host = bool(mt.enabled and mt.param_tier == "host"
                           and self._layer_prefetch_on)
        if mt.enabled and mt.param_tier == "host" and not _param_host:
            log_dist("memory.tiering.param_tier=host has no effect here: it "
                     "rides the comms_overlap.layer_prefetch pipeline "
                     "(ZeRO stage 3 + layer_prefetch required)")
        configure_layer_prefetch(
            self._layer_prefetch_on,
            depth=max(1, int(co.prefetch_depth)),
            shardings=(self._layer_prefetch_shardings()
                       if self._layer_prefetch_on else None),
            quantize=(self._layer_prefetch_quant()
                      if self._layer_prefetch_on else None),
            gather_axes=_gaxes if self._layer_prefetch_on else (),
            host_tier=_param_host)
        if self._layer_prefetch_on:
            log_dist(f"comms_overlap: per-layer all-gather prefetch armed "
                     f"(depth={max(1, int(co.prefetch_depth))}"
                     + (", qwZ int8 gathers"
                        if config.zero_config.zero_quantized_weights
                        else "") + ")")
        # ZeRO-3 gather-at-use: pin each PLAIN-scan layer slice to the
        # gathered compute layout. Without the pin, GSPMD may repartition
        # the stacked-layer scan when it fuses the backward in — which has
        # produced a numerically wrong forward for pure-DP ZeRO-3 (the
        # forward-only program is correct; the grads-live one is not). The
        # constraint states what stage 3 means anyway — all-gather the
        # layer at use — so TP/SP layouts are preserved and the prefetch
        # path (which already pins the same layout) is unchanged.
        from ..comm.overlap import configure_scan_slice_layout

        _scan_slice_on = bool(
            config.zero_config.stage >= 3 and mesh_mgr.pp_world_size <= 1
            and any(mesh_mgr.axis_size(a) > 1
                    for a in self.partitioner.zero_axes))
        configure_scan_slice_layout(
            self._layer_prefetch_shardings() if _scan_slice_on else None)

        # --- compiled steps ---
        self._train_step = None
        self._grad_step = None
        self._apply_step = None
        # breakdown-mode phase steps (wall_clock_breakdown: true)
        self._fwd_step = None
        self._bwd_step = None
        self._flops_estimated = False

        # --- dataloader ---
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)

        # make the config's remat policy the process-wide default for
        # activation_checkpointing.checkpoint() (reference engine wires
        # checkpointing.configure at init, runtime/engine.py:395-408 region)
        if config.activation_checkpointing.policy != "none" or \
                config.activation_checkpointing.cpu_checkpointing:
            from .activation_checkpointing import checkpointing as _ac

            _ac.configure(deepspeed_config=config)

        # --- attention.gqa_native: publish the native-GQA kernel gate
        # process-wide (latest engine wins, same contract as the remat
        # registry above; docs/performance.md "Native GQA attention").
        # Default OFF → every attention program stays byte-identical to
        # the K/V-widening path.
        from ..ops.attention import configure_gqa_native

        configure_gqa_native(bool(config.attention.gqa_native))
        if config.attention.gqa_native:
            log_dist("attention.gqa_native: narrow-KV flash kernels armed "
                     "(KV HBM traffic scales with kv_heads, not num_heads)")

        # --- sequence.ring: publish the ring-attention schedule knobs
        # process-wide (same latest-engine-wins contract as the gate above;
        # sequence/ring.py). Defaults (contiguous, no overlap) leave every
        # ring program unchanged.
        from ..sequence.ring import configure_ring

        configure_ring(layout=config.sequence.ring.layout,
                       overlap=bool(config.sequence.ring.overlap))
        if config.sequence.ring.layout != "contiguous" or \
                config.sequence.ring.overlap:
            log_dist(
                f"sequence.ring: layout={config.sequence.ring.layout} "
                f"overlap={config.sequence.ring.overlap}")
        if config.sequence.tiled_loss:
            if getattr(model, "tiled_loss_fn", None) is None:
                log_dist("sequence.tiled_loss: ON but model spec has no "
                         "tiled_loss_fn — falling back to dense loss_fn")
            else:
                log_dist("sequence.tiled_loss: fused unembed+CE armed "
                         f"(shards={config.sequence.tiled_loss_shards}; "
                         "[B, S, V] logits never materialized)")

        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=config.steps_per_print)

        # --- monitoring + flops profiler (reference MonitorMaster :293,
        # flops_profiler engine hooks :2278,:2850) ---
        from ..monitor import MonitorMaster

        self.monitor = MonitorMaster(config)
        from ..profiling import FlopsProfiler

        self.flops_profiler = FlopsProfiler(config.flops_profiler, engine=self)

        # --- telemetry hub: step breakdown + comms logger + HBM memory +
        # trace sessions, fanned out through the monitor (telemetry/hub.py) ---
        from ..telemetry import TelemetryHub

        self.timers = SynchronizedWallClockTimer()
        self.telemetry = TelemetryHub(config, monitor=self.monitor,
                                      timers=self.timers,
                                      tput_timer=self.tput_timer)

        # Train/overlap/* gauges (registered in telemetry/schema.py): the
        # prefetch configuration + per-step gathered bytes, so the comm-
        # efficiency report can attribute hidden comm to the prefetch
        if self._layer_prefetch_on:
            depth = max(1, int(co.prefetch_depth))
            self.telemetry.train_event("overlap/prefetch_depth", depth)
            lp = self.state.params.get("layers") \
                if isinstance(self.state.params, dict) else None
            if lp is not None:
                leaves = jax.tree.leaves(lp)
                if leaves:
                    itemsize = jnp.dtype(self.precision.compute_dtype).itemsize
                    self.telemetry.train_event(
                        "overlap/prefetch_layers", float(leaves[0].shape[0]))
                    self.telemetry.train_event(
                        "overlap/prefetch_bytes",
                        float(sum(l.size for l in leaves) * itemsize))

        # --- online self-tuning (tuning/tuner.py; docs/tuning.md): the
        # telemetry-scored knob search stepping at the optimizer-step seam.
        # Opt-in: with the block disabled (the default) no tuner exists and
        # the train step program is byte-identical to pre-tuning behavior
        # (pinned by tests/test_tuning.py) ---
        self.tuning = None
        if getattr(config, "tuning", None) is not None and \
                config.tuning.enabled:
            from ..tuning import OnlineTuner

            self.tuning = OnlineTuner.for_engine(self, config.tuning)

        # --- training watchdog (runtime/watchdog.py): consecutive-skip /
        # non-finite-loss / stall detection on host-visible step outputs.
        # Opt-in: its observe() forces a host sync on the loss, so the
        # default step must never pay for it ---
        self.watchdog = None
        if config.watchdog.enabled:
            from .watchdog import TrainingWatchdog

            self.watchdog = TrainingWatchdog(config.watchdog,
                                             telemetry=self.telemetry)

        # --- numerics integrity plane (reliability/integrity.py): SDC
        # detection via cross-replica digest votes + shadow recompute
        # audits. Opt-in: with the block disabled the step program carries
        # no digest computation — byte-identical to the pre-integrity
        # program (pinned by tests/test_integrity.py) ---
        self.integrity = None
        if config.reliability.integrity.enabled:
            from ..reliability.integrity import IntegrityPlane

            self.integrity = IntegrityPlane(config,
                                            telemetry=self.telemetry)

        # --- curriculum learning (reference engine hooks :395-408 wire the
        # curriculum scheduler into the forward prologue) ---
        self.curriculum_scheduler = None
        de = config.data_efficiency or {}
        cl = de.get("data_sampling", {}).get("curriculum_learning") or \
            de.get("curriculum_learning", {})
        if cl.get("enabled"):
            from .data_pipeline import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(cl)
        log_dist(
            f"engine ready: zero_stage={config.zero_config.stage} "
            f"dtype={config.compute_dtype} mesh={dict(mesh_mgr.mesh.shape)} "
            f"micro_batch={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps()}")

    def _configure_nvme_optimizer(self, params) -> None:
        """ZeRO-Infinity optimizer tier: fp32 masters + Adam moments live on
        NVMe and STREAM through the step per sub-group (reference
        ``stage3.py:2412`` swap_in → update → swap_out; ``:679``
        ``_configure_tensor_swapping``). The training flow becomes: device
        jit computes grads → host clip/overflow check → streamed host Adam →
        updated bf16 copies return to the device. save/load_checkpoint
        stream-copy the NVMe state files alongside the TrainState
        (``saver.py`` → ``save_state_files``/``load_state_files``)."""
        import tempfile

        from .swap_tensor.streaming_optimizer import NVMeStreamingOptimizer

        cfg = self.config
        if jax.process_count() > 1:
            raise ValueError(
                "offload_optimizer device=nvme is single-host for now: the "
                "streamed tier gathers grads to host numpy (fails on "
                "non-addressable multi-host arrays) and writes state files "
                "on process 0 only — per-host sharded streaming is not "
                "implemented")
        if cfg.fp16.enabled:
            raise ValueError(
                "offload_optimizer device=nvme supports bf16/fp32 training "
                "(dynamic fp16 loss scaling is not wired through the host "
                "optimizer tier)")
        opt_type = (cfg.optimizer.type or "adamw").lower()
        if opt_type not in ("adam", "adamw"):
            raise ValueError(
                f"offload_optimizer device=nvme streams Adam state; got "
                f"optimizer type '{opt_type}'")
        hp = dict(cfg.optimizer.params)
        swap_dir = cfg.zero_config.offload_optimizer.nvme_path or \
            cfg.memory.tiering.nvme_path or \
            os.path.join(tempfile.gettempdir(), "dstpu_nvme_opt")
        leaves, self._nvme_treedef = jax.tree_util.tree_flatten(params)
        # leaves pass through unconverted — the optimizer converts to fp32
        # per sub-group inside its init loop, keeping bring-up bounded too
        self._nvme_opt = NVMeStreamingOptimizer(
            leaves,
            os.path.join(swap_dir, "opt_state"),
            lr=float(hp.get("lr", 1e-3)),
            betas=tuple(hp.get("betas", (0.9, 0.999))),
            eps=float(hp.get("eps", 1e-8)),
            weight_decay=float(hp.get("weight_decay", 0.0)),
            adamw_mode=(opt_type == "adamw"),
            sub_group_size=int(cfg.zero_config.sub_group_size))

    def _train_batch_nvme(self, batch) -> StepOutput:
        """train_batch when the optimizer state streams through NVMe."""
        import ml_dtypes

        cfg = self.config
        if not hasattr(self, "_nvme_grad_step"):
            def grad_fn(params, b, ls):
                return self._accumulate(params, b, ls)

            with self.mesh_mgr.activate():
                self._nvme_grad_step = self.telemetry.compile.jit(
                    "nvme_grad_step", grad_fn)
        self.tput_timer.start()
        self.telemetry.step_begin(self.global_steps + 1)
        if self.watchdog is not None:
            self.watchdog.step_started()
        breakdown = self.wall_clock_breakdown()
        if self.curriculum_scheduler is not None:
            batch = self.curriculum_scheduler.truncate(batch,
                                                       self.global_steps)
        batch = self._shard_batch(batch, with_gas_dim=True)
        if breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).start(sync=True)
        with self.telemetry.tracer.span("train/bwd", cat="train",
                                        step=self.global_steps + 1):
            grads, loss, aux = self._nvme_grad_step(self.state.params, batch,
                                                    self.state.loss_scale)
        if breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).stop(sync=True)
            self.timers(STEP_GLOBAL_TIMER).start()
        g_dev = jax.tree.leaves(grads)
        for g in g_dev:  # start ALL D2H copies before the first blocking
            if hasattr(g, "copy_to_host_async"):  # np.asarray (overlapped
                g.copy_to_host_async()  # transfers, not one full-tree sync)
        g_leaves = [np.asarray(g, np.float32) for g in g_dev]
        sq = sum(float(np.vdot(g, g)) for g in g_leaves)
        grad_norm = float(np.sqrt(sq))
        finite = np.isfinite(grad_norm)
        # schedule driven by state.step (like the compiled path) so a
        # skipped non-finite step does not advance the LR
        lr_t = float(self.lr_schedule(jnp.asarray(int(self.state.step),
                                                  jnp.float32)))
        if float(self._lr_override) >= 0:
            lr_t = float(self._lr_override)
        if finite:
            if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                coef = min(1.0, float(cfg.gradient_clipping) /
                           (grad_norm + 1e-6))
                if coef < 1.0:
                    g_leaves = [g * np.float32(coef) for g in g_leaves]
            bf16 = self.precision.compute_dtype == jnp.bfloat16
            flat_shardings = jax.tree.leaves(
                self._param_shardings,
                is_leaf=lambda x: isinstance(x, NamedSharding))
            new_leaves: list = [None] * len(g_leaves)

            def h2d_group(leaf_ids, outs):
                # fires per finished sub-group INSIDE the streamed step:
                # device_put dispatch is async, so these H2D transfers run
                # while the later sub-groups are still reading/updating
                # (reference pipelined_optimizer_swapper.py:52 overlap)
                for lid, u in zip(leaf_ids, outs):
                    if bf16:
                        u = u.view(ml_dtypes.bfloat16)
                    new_leaves[lid] = jax.device_put(u, flat_shardings[lid])

            self._nvme_opt.step(
                g_leaves, lr=lr_t,
                out_dtype="bfloat16" if bf16 else "float32",
                on_group=h2d_group)
            new_params = jax.tree_util.tree_unflatten(self._nvme_treedef,
                                                      new_leaves)
            self.state = self.state._replace(
                params=new_params,
                step=self.state.step + 1)
        else:
            self.skipped_steps += 1
            self.state = self.state._replace(
                skipped_steps=self.state.skipped_steps + 1)
        out = StepOutput(loss=loss, grad_norm=jnp.float32(grad_norm),
                         lr=jnp.float32(lr_t),
                         loss_scale=jnp.float32(1.0),
                         overflow=jnp.asarray(not finite),
                         aux=aux)
        self.global_steps += 1
        self._last_grad_norm = grad_norm
        self.lr_scheduler.last_step = self.global_steps
        if breakdown:
            self.timers(STEP_GLOBAL_TIMER).stop(sync=True)
        self.tput_timer.stop()
        self._write_monitor_events(out)
        self.telemetry.step_end(self.global_steps,
                                step_time_s=self.tput_timer.avg_step_time()
                                or None)
        if cfg.steps_per_print and \
                self.global_steps % cfg.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(out.loss):.4f} "
                     f"lr={lr_t:.3e} gnorm={grad_norm:.3f} [nvme-opt]")
        if self.watchdog is not None:
            self.watchdog.observe(self, out)
        return out

    def _train_batch_tiered(self, batch) -> StepOutput:
        """train_batch when the optimizer state lives on the HOST tier
        (``memory.tiering.optimizer_tier=host``; docs/memory.md).

        Between steps the opt-state leaves are host-resident (off the device
        allocator). Per step: (1) the H2D restore is enqueued on the
        transfer worker FIRST, (2) the grad computation dispatches — the
        copies stream under it, (3) the jitted apply consumes the restored
        state, (4) the updated state's D2H writeback is enqueued and
        overlaps the NEXT step's compute. The store's compute window
        brackets (2)-(3) so ``Memory/tier/overlap_frac`` measures how much
        of the transfer time was actually hidden."""
        store = self.tiered_store
        if self._tiered_grad_step is None:
            def grad_fn(params, b, ls):
                return self._accumulate(params, b, ls)

            with self.mesh_mgr.activate():
                self._tiered_grad_step = self.telemetry.compile.jit(
                    "tiered_grad_step", grad_fn)
            self._ensure_apply_step()
        self.tput_timer.start()
        self.telemetry.step_begin(self.global_steps + 1)
        if self.watchdog is not None:
            self.watchdog.step_started()
        if self.curriculum_scheduler is not None:
            batch = self.curriculum_scheduler.truncate(batch,
                                                       self.global_steps)
        batch = self._shard_batch(batch, with_gas_dim=True)
        with self.telemetry.tracer.span("train/train_batch", cat="train",
                                        step=self.global_steps + 1):
            store.worker.compute_begin()
            try:
                # (1) H2D prefetch of the host-resident optimizer state —
                # HostBuffer leaves carry their exact shardings, so no
                # override tree is needed
                handle = store.prefetch(self.state.opt_state)
                # (2) grads dispatch; the prefetch copies run under them
                grads, loss, aux = self._tiered_grad_step(
                    self.state.params, batch, self.state.loss_scale)
                opt_dev = handle.wait()
                # (3) optimizer apply over the restored state
                new_state, out = self._apply_step(
                    self.state._replace(opt_state=opt_dev), grads, loss,
                    self._lr_override)
                jax.block_until_ready(out.loss)
            finally:
                store.worker.compute_end()
        # (4) async D2H writeback — overlaps the next step's compute
        self.state = new_state._replace(
            opt_state=store.offload(new_state.opt_state, "host",
                                    name="optim_states"))
        self.global_steps += 1
        self._last_grad_norm = out.grad_norm
        self.lr_scheduler.last_step = self.global_steps
        self.tput_timer.stop()
        self._write_monitor_events(out)
        self.telemetry.memory_tier_events(store, self.global_steps)
        self.telemetry.step_end(self.global_steps,
                                step_time_s=self.tput_timer.avg_step_time()
                                or None)
        if self.config.steps_per_print and \
                self.global_steps % self.config.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(out.loss):.4f} "
                     f"lr={float(out.lr):.3e} "
                     f"gnorm={float(out.grad_norm):.3f} [tiered-opt "
                     f"overlap={store.overlap_frac():.2f}]")
        if self.watchdog is not None:
            self.watchdog.observe(self, out)
        return out

    def _log_zero_sharding_summary(self, shapes, opt_specs) -> None:
        """One bring-up line saying how much master/optimizer state actually
        got ZeRO-sharded — indivisible leaves silently stay replicated
        (`_add_zero_axes`), which at scale is exactly the class of memory
        regression the reference's partitioner errors on. Make it visible."""
        part = self.partitioner
        if self.config.zero_config.stage < 1 or part.zero_size <= 1:
            return
        zero_axes = set(part.zero_axes)
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        shape_leaves = jax.tree.leaves(
            shapes, is_leaf=lambda x: isinstance(x, tuple))
        spec_paths = jax.tree_util.tree_flatten_with_path(
            opt_specs, is_leaf=is_p)[0]
        n_zero = n_model = n_repl = 0
        bytes_zero = bytes_model = bytes_repl = 0
        repl_names: List[str] = []
        for shape, (path, spec) in zip(shape_leaves, spec_paths):
            axes_used = set()
            for e in spec:
                axes_used.update(e if isinstance(e, tuple) else (e,))
            axes_used.discard(None)
            nbytes = int(np.prod(shape or (1,))) * 4  # fp32 master
            if axes_used & zero_axes:
                n_zero += 1
                bytes_zero += nbytes
            elif axes_used:  # TP/expert/pipe-sharded, just not over ZeRO axes
                n_model += 1
                bytes_model += nbytes
            else:
                n_repl += 1
                bytes_repl += nbytes
                if len(repl_names) < 5:
                    repl_names.append(jax.tree_util.keystr(path))
        msg = (f"ZeRO-{self.config.zero_config.stage} partitioning over "
               f"{tuple(part.zero_axes)} (world {part.zero_size}): "
               f"{n_zero} leaves ZeRO-sharded "
               f"({bytes_zero / 2**20:.1f} MiB fp32)")
        if n_model:
            msg += (f", {n_model} model-parallel-sharded only "
                    f"({bytes_model / 2**20:.1f} MiB fp32)")
        msg += f", {n_repl} replicated ({bytes_repl / 2**20:.1f} MiB fp32)"
        if n_repl:
            msg += (f" — replicated (indivisible or rule-pinned): "
                    f"{', '.join(repl_names)}"
                    + (", …" if n_repl > len(repl_names) else ""))
        log_dist(msg)

    # ------------------------------------------------------------------ #
    # reference property accessors (engine.py:770-1252 parity, abridged)
    # ------------------------------------------------------------------ #
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def zero_optimization_stage(self) -> int:
        return self.config.zero_config.stage

    def get_lr(self) -> List[float]:
        return [float(self.lr_schedule(jnp.asarray(self.global_steps, jnp.float32)))]

    def get_global_grad_norm(self) -> float:
        return getattr(self, "_last_grad_norm", 0.0)

    @property
    def loss_scale(self) -> float:
        return float(self.state.loss_scale.scale)

    # --- further reference accessors (engine.py:770-1252) ---
    def get_batch_info(self):
        """(train_batch_size, micro_batch_per_gpu, gradient_accumulation)."""
        return (self.train_batch_size(),
                self.train_micro_batch_size_per_gpu(),
                self.gradient_accumulation_steps())

    @property
    def global_samples(self) -> int:
        return self.global_steps * self.train_batch_size()

    def zero_optimization(self) -> bool:
        return self.config.zero_config.stage > 0

    def bfloat16_enabled(self) -> bool:
        return self.config.bf16.enabled

    def fp16_enabled(self) -> bool:
        return self.config.fp16.enabled

    def gradient_clipping_value(self) -> float:
        return float(self.config.gradient_clipping or 0.0)

    def steps_per_print(self) -> int:
        return self.config.steps_per_print

    def wall_clock_breakdown(self) -> bool:
        return bool(getattr(self.config, "wall_clock_breakdown", False))

    @property
    def module(self):
        """The user model (reference returns the wrapped nn.Module)."""
        return self.model

    def set_lr(self, lr: float) -> None:
        """Pin the LR to a constant (reference ``engine.set_lr`` writes the
        value into EVERY param group). base_lr must stay the optimizer's
        factory lr — the step computes ``lr_scale = sched(t)/base_lr`` and
        the optimizer multiplies by its own lr, so resetting base_lr here
        would cancel the scale and silently keep the old rate.

        The pinned value flows into the compiled step as a traced scalar
        (``self._lr_override``), so per-interval set_lr (the RLHF pattern)
        never thrashes recompiles."""
        self.lr_schedule = constant(float(lr))
        self.lr_scheduler = LRScheduler(self.lr_schedule)
        if getattr(self, "_grouped_ctor", None) is not None:
            # grouped optimizers have per-group lrs; reference semantics are
            # uniform after set_lr → rebuild with every group pinned to lr.
            # This changes the optimizer itself, so the cached steps must go.
            from ..ops.optimizers import grouped_optimizer

            name, groups, kwargs, ptree = self._grouped_ctor
            kwargs = {**kwargs, "lr": float(lr)}
            groups = [{k: v for k, v in g.items() if k != "lr"}
                      for g in groups]
            self.optimizer = grouped_optimizer(name, ptree, groups, **kwargs)
            # guard lr=0 (freeze): base_lr=0 would make lr_scale 0/0 = NaN
            self.base_lr = float(lr) or 1.0
            self._train_step = None
            self._apply_step = None
        self._lr_override = jnp.asarray(float(lr), jnp.float32)

    def get_mom(self) -> List[float]:
        b = self.optimizer.hyperparams.get("betas", (0.9, 0.999))
        return [float(b[0] if isinstance(b, (tuple, list)) else b)]

    def dp_world_size(self) -> int:
        return self.mesh_mgr.dp_world_size

    def mp_world_size(self) -> int:
        return self.mesh_mgr.tp_world_size

    # ------------------------------------------------------------------ #
    # opt state init (sharded)
    # ------------------------------------------------------------------ #
    def _init_opt_state(self, params):
        opt_shapes = jax.eval_shape(self.optimizer.init, params)
        # optimizer state leaves mirror param structure inside (mu/nu/...).
        # We shard any leaf whose shape matches a param leaf's shape with that
        # param's opt-state spec; scalars stay replicated.
        param_leaves = jax.tree.leaves(params)
        spec_leaves = jax.tree.leaves(self.opt_param_specs,
                                      is_leaf=lambda x: isinstance(x, P))
        shape_to_spec = {}
        for pl, sp in zip(param_leaves, spec_leaves):
            shape_to_spec.setdefault(tuple(pl.shape), sp)

        def leaf_spec(l):
            return shape_to_spec.get(tuple(l.shape), P())

        opt_specs = jax.tree.map(leaf_spec, opt_shapes)
        self.opt_state_specs = opt_specs
        shardings = jax.tree.map(lambda s: NamedSharding(self.mesh_mgr.mesh, s), opt_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        return jax.jit(self.optimizer.init, out_shardings=shardings)(params)

    # ------------------------------------------------------------------ #
    # the compiled train step
    # ------------------------------------------------------------------ #
    def _cast_gather(self, params):
        """Compute-cast + gather-to-compute-layout.

        ZeRO stages 1/2: masters are sharded over the ZeRO axes but compute
        wants the TP-only layout — the constraint makes XLA all-gather the
        low-precision copy (the reference's post-step allgather of updated
        partitions, stage_1_and_2.py:2223, moved to gather-on-compute-cast).
        At stage 3 the constraint keeps params sharded; XLA gathers at use —
        except under hpZ (``zero_hpz_partition_size``), where the constraint
        is the once-per-step PRIMARY gather from the full master partition
        into the intra-island secondary partition (the only collective that
        crosses the 'data'/DCN tier; fwd/bwd gathers then resolve over the
        secondary axes only).

        ZeRO++ qwZ (``zero_quantized_weights``, reference
        ``runtime/zero/config.py:309`` + ``csrc/quantization/
        swizzled_quantize.cu``): wherever the master layout differs from the
        compute-param layout — a real gather boundary — the tensor that
        crosses it is int8 with per-row fp32 scales
        (``compressed.quantized_gather``), quartering the fp32 wire bytes.
        At stage 3 the per-layer use-site gathers quantize through
        ``overlap.prefetch_scan`` instead (the explicit gather seam)."""
        compute = self.precision.cast_to_compute(params)
        zc = self.config.zero_config
        mm = self.mesh_mgr
        part = self.partitioner
        secondary = tuple(getattr(part, "secondary_axes", None) or ())
        qwz = bool(zc.zero_quantized_weights and mm.zero_world_size > 1)
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        pspec_leaves = jax.tree.leaves(self.param_specs, is_leaf=is_p)
        mspec_leaves = jax.tree.leaves(self.opt_param_specs, is_leaf=is_p)

        def quantizes(leaf, pspec, mspec):
            # quantize only where a gather boundary actually exists (the
            # master/opt layout differs from the compute-param layout) — at
            # stage 0, or for leaves ZeRO left unsharded (indivisible dims),
            # the int8 roundtrip would cost precision and save zero wire
            # bytes. Plain stage 3 has no boundary HERE (params stay sharded,
            # gather-at-use); hpZ's primary gather is one.
            return (qwz and isinstance(leaf, jnp.ndarray)
                    and jnp.issubdtype(leaf.dtype, jnp.floating)
                    and leaf.ndim >= 2 and mspec != pspec)

        # comms-logger: the constraint below makes XLA all-gather the
        # ZeRO-sharded low-precision params — record that implied collective
        # at trace time. The gather crosses the primary axes NOT covered by
        # the secondary partition ('data' only, under hpZ); qwZ leaves ride
        # the wire as int8 + per-row fp32 scales, recorded as such so
        # algo_bytes reflects the actual quantized wire volume.
        tel = dist.get_telemetry()
        if tel.enabled and zc.stage >= 1 and mm.zero_world_size > 1:
            gather_axes = tuple(a for a in part.zero_axes
                                if mm.axis_size(a) > 1
                                and a not in secondary)
            q_leaves, plain = [], []
            for leaf, ps, ms in zip(jax.tree.leaves(compute), pspec_leaves,
                                    mspec_leaves):
                (q_leaves if quantizes(leaf, ps, ms) else plain).append(leaf)
            if gather_axes:
                if plain:
                    tel.record("all_gather_params", gather_axes, plain)
                if q_leaves:
                    payload = [
                        (jax.ShapeDtypeStruct(l.shape, jnp.int8),
                         jax.ShapeDtypeStruct(l.shape[:-1] + (1,),
                                              jnp.float32))
                        for l in q_leaves]
                    tel.record("all_gather_params_q", gather_axes, payload,
                               fp32_equiv=sum(l.size for l in q_leaves) * 4)
            if secondary and zc.stage >= 3 and \
                    not getattr(self, "_layer_prefetch_on", False):
                # hpZ: the at-use fwd/bwd gathers resolve inside the
                # secondary (ICI) island — trace-time estimate of their
                # volume (with layer_prefetch on, prefetch_scan records the
                # explicit per-layer gathers instead)
                tel.record("all_gather_params_secondary", secondary, compute)

        if not qwz:
            return jax.lax.with_sharding_constraint(
                compute, self._param_shardings)

        from ..comm.compressed import quantized_gather

        def one(leaf, param_sharding, pspec, mspec):
            if not quantizes(leaf, pspec, mspec):
                return jax.lax.with_sharding_constraint(leaf, param_sharding)
            sspec = list(pspec)[:leaf.ndim]
            sspec += [None] * (leaf.ndim - len(sspec))
            if sspec:
                sspec[-1] = None  # scales' trailing dim is size 1
            scale_sharding = mm.sharding(*sspec)
            return quantized_gather(leaf, param_sharding, scale_sharding)

        # tree.map follows `compute`'s structure, so the P leaves of the
        # spec trees are taken whole (not flattened as tuples). Matrix
        # leaves with a real gather boundary land in the compute-param
        # layout via the int8 wire; everything else keeps the normal
        # constraint (plain stage-3 gather-on-use included).
        return jax.tree.map(one, compute, self._param_shardings,
                            self.param_specs, self.opt_param_specs)

    def _raw_loss(self, compute_params, batch):
        """Model loss on already-cast/gathered compute params. Routes
        through the tiled fused logits+loss head when
        ``sequence.tiled_loss`` is on — the [B, S, V] logits tensor is
        never materialized (sequence/tiled.py). With the knob off (the
        default) this is exactly ``model.loss_fn``: the trace, and hence
        the compiled train step, is byte-identical to before."""
        seq = self.config.sequence
        if seq.tiled_loss and self.model.tiled_loss_fn is not None:
            return self.model.tiled_loss_fn(compute_params, batch,
                                            shards=seq.tiled_loss_shards)
        return self.model.loss_fn(compute_params, batch)

    def _loss(self, params, batch):
        compute_params = self._cast_gather(params)
        out = self._raw_loss(compute_params, batch)
        if isinstance(out, tuple):
            loss, aux = out
        else:
            loss, aux = out, {}
        return loss.astype(jnp.float32), aux

    def _grads_one_micro(self, params, batch, loss_scale):
        from ..comm.mesh import BATCH_AXES as _BA

        if self.config.zero_config.zero_quantized_gradients and \
                self.mesh_mgr.pp_world_size <= 1 and \
                any(self.mesh_mgr.axis_size(a) > 1 for a in _BA):
            return self._qgz_one_micro(params, batch, loss_scale)
        if self.model.pipeline_grad_fn is not None and \
                self.mesh_mgr.pp_world_size > 1:
            # 1F1B pipeline schedule (bounded activations) — the model owns
            # the stage decomposition; the engine supplies the compute cast
            compute_params = self._cast_gather(params)
            grads, loss, aux = self.model.pipeline_grad_fn(
                compute_params, batch, loss_scale.scale)
            return grads, loss.astype(jnp.float32), aux

        def scaled_loss(p):
            loss, aux = self._loss(p, batch)
            return scale_loss(loss, loss_scale), (loss, aux)

        grads, (loss, aux) = jax.grad(scaled_loss, has_aux=True)(params)
        return grads, loss, aux

    def _qgz_one_micro(self, params, batch, loss_scale):
        """ZeRO++ qgZ (``zero_quantized_gradients``): per-device LOCAL grads,
        reduced with a hierarchical int8 quantize → reduce-scatter →
        dequantize over the batch axes (reference
        ``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``,
        ``csrc/quantization/quant_reduce.cu``). The wire moves int8 (+ tiny
        fp32 group scales) instead of fp32 — the DCN-crossing story. Leaves
        whose target spec is replicated (and the 'data' axis under MiCS, which
        replicates) reduce with a plain fp32 psum."""
        from ..comm import overlap as ov
        from ..comm.mesh import BATCH_AXES
        from ..comm.compressed import quantized_reduce_scatter_dim

        mm = self.mesh_mgr
        manual = tuple(a for a in BATCH_AXES if mm.axis_size(a) > 1)
        assert manual, "qgZ dispatch requires a >1 batch axis (see caller)"
        n_total = int(np.prod([mm.axis_size(a) for a in manual]))

        # cast + TP-layout gather OUTSIDE the manual region: compute params
        # carry no batch-axis sharding below stage 3
        compute = self._cast_gather(params)

        is_p = lambda x: isinstance(x, P)  # noqa: E731
        flat_specs = jax.tree.leaves(self.grad_specs, is_leaf=is_p)
        param_leaves = jax.tree.leaves(params)  # grad shapes == param shapes

        # per-leaf plan (shared with the comms_overlap engine — overlap.py)
        plans = ov.make_reduce_plans(param_leaves, flat_specs, manual,
                                     mm.axis_size)

        gdef_template = jax.tree_util.tree_structure(params)
        out_gspecs = jax.tree_util.tree_unflatten(
            gdef_template,
            [ov.plan_out_spec(leaf.ndim, plan)
             for leaf, plan in zip(param_leaves, plans)])
        batch_specs = jax.tree.map(lambda x: P(manual), batch)

        def local(compute_params, lbatch):
            def scaled(p):
                out = self._raw_loss(p, lbatch)
                loss, aux = out if isinstance(out, tuple) else (out, {})
                loss = loss.astype(jnp.float32)
                return scale_loss(loss, loss_scale), (loss, aux)

            grads, (loss, aux) = jax.grad(scaled, has_aux=True)(compute_params)
            gleaves, gdef = jax.tree_util.tree_flatten(grads)
            red = []
            for g, (d, scatter, residual) in zip(gleaves, plans):
                g = g.astype(jnp.float32)
                if d is not None:
                    g = quantized_reduce_scatter_dim(g, d, scatter)
                if residual:
                    g = jax.lax.psum(g, residual)
                red.append(g / n_total)
            grads = jax.tree_util.tree_unflatten(gdef, red)
            loss = jax.lax.psum(loss, manual) / n_total
            aux = jax.tree.map(
                lambda a: jax.lax.psum(a.astype(jnp.float32), manual) / n_total
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                else jax.lax.psum(jnp.asarray(a), manual), aux)
            return grads, loss, aux

        return dist.shard_map(
            local, mesh=mm.mesh, axis_names=set(manual),
            in_specs=(P(), batch_specs),
            out_specs=(out_gspecs, P(), P()),
            check_vma=False)(compute, batch)

    def _constrain_grads(self, grads, record: bool = True,
                         repeats: int = 1):
        """Apply the stage's gradient sharding (reduce-scatter from stage 2 —
        reference stage_1_and_2.py:126): XLA fuses the implied psum over the
        data axes with this placement into a reduce-scatter.

        ``repeats``: execution count of the enclosing trace region (a scan
        body over GAS micros executes per micro) so the telemetry's per-step
        volume stays honest; ``record=False`` for constraints that imply no
        reduction (placing a fresh zeros accumulator)."""
        # comms-logger: the batch-sharded loss implies a grad reduction over
        # the batch axes — record it at trace time so data-parallel volume
        # shows up in the per-op summary even though XLA inserts the op
        tel = dist.get_telemetry()
        if tel.enabled and record:
            axes = tuple(a for a in BATCH_AXES
                         if self.mesh_mgr.axis_size(a) > 1)
            if axes:
                op = ("reduce_scatter_grads"
                      if self.config.zero_config.stage >= 2
                      else "all_reduce_grads")
                tel.record(op, axes, grads, repeats=repeats)
        return jax.lax.with_sharding_constraint(grads, self._grad_shardings)

    def _accumulate(self, params, batch, loss_scale):
        """GAS micro-batch loop under lax.scan; batch leading dim = gas.
        The PER-MICRO reduction path: each micro's implied grad reduce fires
        inside the scan body (gas collectives per step). The comms_overlap
        config block swaps this for :meth:`_accumulate_overlap`."""
        gas = self.gradient_accumulation_steps()
        if gas == 1:
            grads, loss, aux = self._grads_one_micro(params, batch, loss_scale)
            return self._constrain_grads(grads), loss, aux

        def body(carry, micro):
            acc = carry
            grads, loss, aux = self._grads_one_micro(params, micro, loss_scale)
            acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32), acc, grads)
            # keep the accumulator in the stage's grad layout between micros
            # (stage>=2: sharded — the API-parity path stays O(params/N));
            # the body executes once per micro → repeats=gas
            return self._constrain_grads(acc, repeats=gas), (loss, aux)

        zeros = self._constrain_grads(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            record=False)
        acc, (losses, auxes) = jax.lax.scan(body, zeros, batch)
        grads = jax.tree.map(lambda g: g / gas, acc)
        # aux: mean over micros for floats, sum otherwise (token counts etc.)
        aux = jax.tree.map(
            lambda a: jnp.mean(a, axis=0) if jnp.issubdtype(a.dtype, jnp.inexact)
            else jnp.sum(a, axis=0), auxes)
        return grads, jnp.mean(losses), aux

    # ------------------------------------------------------------------ #
    # comms_overlap: deferred / bucketed / LoCo gradient reduction
    # ------------------------------------------------------------------ #
    def _overlap_active(self) -> bool:
        """The comms_overlap reduction engine replaces ``_accumulate`` when
        the block is enabled, a data-parallel axis exists, and no pipeline
        schedule owns the backward."""
        co = self.config.comms_overlap
        if not co.enabled:
            return False
        if self.config.zero_config.stage >= 3:
            return False  # stage 3: only layer_prefetch + XLA flags apply
        if self.mesh_mgr.pp_world_size > 1:
            return False  # 1F1B owns its reduction (logged at init)
        return any(self.mesh_mgr.axis_size(a) > 1 for a in BATCH_AXES)

    def _overlap_setup(self):
        """Static per-leaf routing for the overlap engine, computed once:
        (manual axes, world, reduce plans, flat buckets, bucketed set, LoCo
        leaf indices). Shapes only — safe to cache for the engine's life."""
        if self._overlap_plan_cache is not None:
            return self._overlap_plan_cache
        from ..comm import overlap as ov

        co = self.config.comms_overlap
        mm = self.mesh_mgr
        manual = tuple(a for a in BATCH_AXES if mm.axis_size(a) > 1)
        n_total = int(np.prod([mm.axis_size(a) for a in manual]))
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        flat_specs = jax.tree.leaves(self.grad_specs, is_leaf=is_p)
        leaves = jax.tree.leaves(self.state.params)
        plans = ov.make_reduce_plans(leaves, flat_specs, manual, mm.axis_size)
        buckets: List[List[int]] = []
        bucketed: frozenset = frozenset()
        if co.coalesce_buckets:
            bucket_bytes = max(int(co.bucket_size_mb * 2 ** 20), 4 * n_total)
            small = [i for i, l in enumerate(leaves)
                     if ov.padded_rows(l.size, n_total) * 4 <= bucket_bytes]
            buckets = ov.plan_buckets(small, [l.size for l in leaves],
                                      n_total, bucket_bytes)
            bucketed = frozenset(i for b in buckets for i in b)
        loco_idx: Tuple[int, ...] = ()
        if co.loco:
            # error feedback exists where quantization does: the int8
            # scatter-planned leaves under qgZ, and the psum-planned leaves
            # under the EQuARX-style quantized all-reduce (bucketed small
            # leaves reduce in exact fp32 and need no compensation)
            qgz_ = self.config.zero_config.zero_quantized_gradients
            loco_idx = tuple(
                i for i, p in enumerate(plans) if i not in bucketed
                and ((p.dim is not None and qgz_)
                     or (p.dim is None and p.psum_axes
                         and co.quantized_all_reduce)))
        self._overlap_plan_cache = (manual, n_total, plans, buckets,
                                    bucketed, loco_idx)
        return self._overlap_plan_cache

    def _layer_prefetch_shardings(self):
        """Per-layer GATHERED-layout shardings for the model's stacked
        ``layers`` subtree (leading stacked dim dropped from each spec) —
        the constraint :func:`overlap.prefetch_scan` pins each sliced layer
        to, so XLA starts the ZeRO all-gather at slice time. Models whose
        param tree has no ``layers`` dict get no constraint (the prefetch
        ordering barrier still applies)."""
        params = self.state.params
        if not (isinstance(params, dict) and "layers" in params):
            return None
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        sub = self._qw_gather_specs["layers"]
        mesh = self.mesh_mgr.mesh

        def drop_stacked(spec):
            return NamedSharding(mesh, P(*list(spec)[1:]))

        return jax.tree.map(drop_stacked, sub, is_leaf=is_p)

    def _layer_prefetch_quant(self):
        """ZeRO++ qwZ descriptors for the prefetch gathers: a pair of trees
        matching the model's ``layers`` subtree — per-leaf bool (quantize
        this leaf's gather) and the per-leaf SCALE sharding in the gathered
        layout. ``overlap.prefetch_scan`` routes flagged leaves through
        ``compressed.quantized_gather`` so each per-layer all-gather moves
        int8 + per-row fp32 scales instead of full-width bytes. None when
        qwZ is off or the param tree has no ``layers`` dict."""
        if not self.config.zero_config.zero_quantized_weights:
            return None
        params = self.state.params
        if not (isinstance(params, dict) and "layers" in params):
            return None
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        gathered = self._qw_gather_specs["layers"]
        sharded = self.param_specs["layers"]
        mesh = self.mesh_mgr.mesh

        def flag(leaf, gspec, pspec):
            # a sliced layer leaf drops the stacked dim; quantize where the
            # stacked (ZeRO-sharded) layout differs from the gathered one —
            # a real per-layer gather boundary — on float matrix leaves
            return bool(jnp.issubdtype(leaf.dtype, jnp.floating)
                        and leaf.ndim - 1 >= 2
                        and P(*list(gspec)[1:]) != P(*list(pspec)[1:]))

        def scale_shard(leaf, gspec):
            nd = leaf.ndim - 1  # stacked dim dropped
            ents = list(gspec)[1:][:nd]
            ents += [None] * (nd - len(ents))
            if ents:
                ents[-1] = None  # scales' trailing dim is size 1
            return NamedSharding(mesh, P(*ents))

        flags = jax.tree.map(flag, params["layers"], gathered, sharded)
        scales = jax.tree.map(scale_shard, params["layers"], gathered)
        return flags, scales

    def _init_loco_residuals(self) -> None:
        """Allocate the per-leaf LoCo quantization-error residuals into
        ``TrainState``: global shape [dp_world, *leaf.shape] fp32, sharded
        over the batch axes (each device owns its own error)."""
        manual, n_total, _, _, _, loco_idx = self._overlap_setup()
        if not loco_idx:
            return
        leaves = jax.tree.leaves(self.state.params)
        res = []
        for i in loco_idx:
            leaf = leaves[i]
            sharding = NamedSharding(self.mesh_mgr.mesh, P(manual))
            res.append(jax.device_put(
                jnp.zeros((n_total,) + tuple(leaf.shape), jnp.float32),
                sharding))
        self.state = self.state._replace(loco_residual=tuple(res))
        log_dist(f"comms_overlap LoCo: carrying {len(res)} error-feedback "
                 f"residual leaves (err_beta="
                 f"{self.config.comms_overlap.loco_err_beta})")

    def _accumulate_overlap(self, params, batch, loss_scale, residuals):
        """The comms_overlap replacement for :meth:`_accumulate`: gradients
        reduce with EXPLICIT collectives in a manual (shard_map) region —

        - small leaves coalesce into flat buckets → one reduce-scatter +
          all-gather per bucket instead of one collective per leaf;
        - large leaves reduce-scatter straight into the stage's sharded grad
          layout (int8-quantized when qgZ is on, with optional LoCo error
          feedback);
        - with ``deferred_gradient_reduce``, micro-batch grads accumulate in
          the local (unreduced, full-shape fp32) layout and the collectives
          fire ONCE per optimizer step instead of once per micro.

        Returns ``(grads, loss, aux, new_residuals)``."""
        from ..comm import compressed as cc
        from ..comm import overlap as ov

        co = self.config.comms_overlap
        mm = self.mesh_mgr
        gas = self.gradient_accumulation_steps()
        manual, n_total, plans, buckets, bucketed, loco_idx = \
            self._overlap_setup()
        qgz = self.config.zero_config.zero_quantized_gradients
        qar = co.quantized_all_reduce
        deferred = co.deferred_gradient_reduce and gas > 1
        err_beta = float(co.loco_err_beta)
        # collectives in a non-deferred scan body run once per micro
        reps = gas if (gas > 1 and not deferred) else 1
        res_pos = {leaf_i: k for k, leaf_i in enumerate(loco_idx)}

        compute = self._cast_gather(params)
        param_leaves = jax.tree.leaves(params)
        gdef = jax.tree_util.tree_structure(params)

        def scatter_world(plan):
            return int(np.prod([mm.axis_size(a) for a in plan.scatter]))

        def reduced_shape(leaf, i):
            plan = plans[i]
            if i in bucketed or plan.dim is None:
                return tuple(leaf.shape)
            shape = list(leaf.shape)
            shape[plan.dim] //= scatter_world(plan)
            return tuple(shape)

        out_gspecs = jax.tree_util.tree_unflatten(
            gdef,
            [P() if i in bucketed else ov.plan_out_spec(leaf.ndim, plans[i])
             for i, leaf in enumerate(param_leaves)])
        batch_specs = jax.tree.map(
            lambda x: P(None, manual) if gas > 1 else P(manual), batch)
        res_specs = tuple(P(manual) for _ in loco_idx)

        # bucket-flush spans fire at TRACE time (collectives are compile-time
        # constructs on TPU — one record describes every execution of the
        # compiled step, like the comms logger's per-trace records)
        _hub = getattr(self, "telemetry", None)
        tracer = _hub.tracer if _hub is not None else None

        def reduce_all(gleaves, res_leaves):
            """One full explicit reduction of the (local) grad leaves."""
            red: List[Any] = [None] * len(gleaves)
            new_res = list(res_leaves)
            for bucket in buckets:
                if tracer is not None and tracer.enabled:
                    tracer.instant(
                        "overlap/bucket_flush", cat="comm", trace_time=True,
                        leaves=len(bucket), deferred=deferred, repeats=reps,
                        bytes=int(sum(gleaves[i].size for i in bucket)) * 4)
                outs = ov.coalesced_reduce([gleaves[i] for i in bucket],
                                           manual, repeats=reps)
                for i, o in zip(bucket, outs):
                    red[i] = o
            for i, (g, plan) in enumerate(zip(gleaves, plans)):
                if red[i] is not None:
                    continue
                g = g.astype(jnp.float32)
                if plan.dim is not None:
                    if qgz:
                        if i in res_pos:
                            g, nr = cc.loco_quantized_reduce_scatter_dim(
                                g, plan.dim, plan.scatter,
                                new_res[res_pos[i]], err_beta=err_beta)
                            new_res[res_pos[i]] = nr
                        else:
                            g = cc.quantized_reduce_scatter_dim(
                                g, plan.dim, plan.scatter)
                    else:
                        g = ov.reduce_scatter_dim(g, plan.dim, plan.scatter,
                                                  repeats=reps)
                if plan.psum_axes:
                    if qar and plan.dim is None:
                        # EQuARX-style quantized all-reduce: the non-ZeRO DP
                        # path (replicated grad layout) — int8 RS + int8 AG
                        # instead of a full-width psum
                        if i in res_pos:
                            g, nr = cc.quantized_all_reduce_ef(
                                g, plan.psum_axes, new_res[res_pos[i]],
                                err_beta=err_beta, repeats=reps)
                            new_res[res_pos[i]] = nr
                        else:
                            g = cc.quantized_all_reduce(g, plan.psum_axes,
                                                        repeats=reps)
                    else:
                        dist.get_telemetry().record(
                            "all_reduce_grads", plan.psum_axes, g,
                            repeats=reps)
                        g = jax.lax.psum(g, plan.psum_axes)
                red[i] = g
            return red, new_res

        def local(compute_params, lbatch, res_in):
            res_leaves = [r[0] for r in res_in]  # drop the device dim

            def grads_of(mb):
                def scaled(p):
                    out = self.model.loss_fn(p, mb)
                    loss, aux = out if isinstance(out, tuple) else (out, {})
                    loss = loss.astype(jnp.float32)
                    return scale_loss(loss, loss_scale), (loss, aux)

                return jax.grad(scaled, has_aux=True)(compute_params)

            denom = float(n_total * gas)
            if gas == 1:
                grads, (loss, aux) = grads_of(lbatch)
                red, new_res = reduce_all(jax.tree.leaves(grads), res_leaves)
                losses, auxes = loss, aux
            elif deferred:
                # local-layout accumulation: full-shape fp32 partial grads
                # per device, ONE reduction at the step boundary
                def body(acc, mb):
                    grads, (loss, aux) = grads_of(mb)
                    acc = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), acc, grads)
                    return acc, (loss, aux)

                zeros = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), compute_params)
                acc, (losses, auxes) = jax.lax.scan(body, zeros, lbatch)
                red, new_res = reduce_all(jax.tree.leaves(acc), res_leaves)
            else:
                # per-micro explicit reduction (the collectives run inside
                # the scan body, but bucketed/quantized/LoCo still apply)
                def body(carry, mb):
                    acc, res = carry
                    grads, (loss, aux) = grads_of(mb)
                    red, res = reduce_all(jax.tree.leaves(grads), res)
                    acc = [a + r for a, r in zip(acc, red)]
                    return (acc, res), (loss, aux)

                zeros = [jnp.zeros(reduced_shape(leaf, i), jnp.float32)
                         for i, leaf in enumerate(param_leaves)]
                (red, new_res), (losses, auxes) = jax.lax.scan(
                    body, (zeros, res_leaves), lbatch)

            red = [g / denom for g in red]
            grads = jax.tree_util.tree_unflatten(gdef, red)
            loss = jax.lax.psum(jnp.mean(losses), manual) / n_total
            aux = jax.tree.map(
                lambda a: jax.lax.psum(
                    jnp.mean(a, axis=0).astype(jnp.float32)
                    if jnp.asarray(a).ndim and gas > 1 else
                    jnp.asarray(a).astype(jnp.float32), manual) / n_total
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                else jax.lax.psum(
                    jnp.sum(jnp.asarray(a), axis=0)
                    if jnp.asarray(a).ndim and gas > 1
                    else jnp.asarray(a), manual), auxes)
            return (grads, loss, aux,
                    tuple(r[None] for r in new_res))

        grads, loss, aux, new_residuals = dist.shard_map(
            local, mesh=mm.mesh, axis_names=set(manual),
            in_specs=(P(), batch_specs, res_specs),
            out_specs=(out_gspecs, P(), P(), res_specs),
            check_vma=False)(compute, batch, residuals)
        # place (bucketed leaves: a local slice; planned leaves: no-op) into
        # the stage's grad layout — no additional comm is implied here
        grads = jax.lax.with_sharding_constraint(grads, self._grad_shardings)
        return grads, loss, aux, new_residuals

    def _apply_update(self, state: TrainState, grads, loss, aux=None,
                      lr_override=None,
                      loco_residual=None) -> Tuple[TrainState, StepOutput]:
        cfg = self.config
        finite = grads_finite(grads)
        grads = unscale_grads(grads, state.loss_scale)

        grad_norm = _global_norm(grads)
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            clip_coef = jnp.minimum(1.0, cfg.gradient_clipping / (grad_norm + 1e-6))
            grads = jax.tree.map(lambda g: g * clip_coef, grads)

        lr_t = self.lr_schedule(state.step.astype(jnp.float32))
        if lr_override is not None:
            lr_t = jnp.where(lr_override >= 0, lr_override, lr_t)
        lr_scale = lr_t / self.base_lr

        new_params, new_opt = self.optimizer.update(state.params, grads,
                                                    state.opt_state, lr_scale=lr_scale)
        # masters keep their ZeRO-sharded layout across the update
        new_params = jax.lax.with_sharding_constraint(
            new_params, self._master_shardings)
        # overflow → skip update (reference: FP16 optimizer skip + scale cut)
        new_params = jax.tree.map(
            lambda n, o: jnp.where(finite, n, o), new_params, state.params)
        new_opt = jax.tree.map(
            lambda n, o: jnp.where(finite, n, o) if n.shape == o.shape else n,
            new_opt, state.opt_state)
        new_scale = update_loss_scale(state.loss_scale, finite)
        new_state = TrainState(
            step=state.step + jnp.where(finite, 1, 0).astype(jnp.int32),
            params=new_params,
            opt_state=new_opt,
            loss_scale=new_scale,
            skipped_steps=state.skipped_steps + jnp.where(finite, 0, 1).astype(jnp.int32),
            # LoCo residuals advance even on a skipped step: they describe
            # the quantization error of the reduce that DID happen
            loco_residual=(state.loco_residual if loco_residual is None
                           else loco_residual),
        )
        aux = {} if aux is None else aux
        icfg = cfg.reliability.integrity
        if icfg.enabled and isinstance(aux, dict):
            from ..reliability.integrity import tree_fingerprint

            # digests of replica-invariant quantities: the unscaled/clipped
            # post-reduce grads, the post-step params and optimizer moments,
            # and the loss scalar. Three scalars per leaf — the transfer to
            # host happens only on check/audit steps (IntegrityPlane)
            fp = {}
            if icfg.fingerprint_grads:
                fp["grads"] = tree_fingerprint(grads)
            if icfg.fingerprint_params:
                fp["params"] = tree_fingerprint(new_params)
            if icfg.fingerprint_opt_state:
                fp["opt_state"] = tree_fingerprint(new_opt)
            fp["loss"] = tree_fingerprint(loss)
            aux = {**aux, "integrity": fp}
        out = StepOutput(loss=loss, grad_norm=grad_norm, lr=lr_t,
                         loss_scale=new_scale.scale,
                         overflow=jnp.logical_not(finite),
                         aux=aux)
        return new_state, out

    def _make_step_fn(self):
        overlap = self._overlap_active()

        def step_fn(state: TrainState, batch, lr_override):
            if overlap:
                grads, loss, aux, new_res = self._accumulate_overlap(
                    state.params, batch, state.loss_scale,
                    state.loco_residual)
                return self._apply_update(state, grads, loss, aux,
                                          lr_override,
                                          loco_residual=new_res)
            grads, loss, aux = self._accumulate(state.params, batch, state.loss_scale)
            return self._apply_update(state, grads, loss, aux, lr_override)

        return step_fn

    def _build_train_step(self):
        # jitted entry points route through the telemetry hub's compile
        # monitor (the recompilation sentinel + per-program cost model —
        # telemetry/compile.py). Default OFF → the exact jax.jit object.
        with self.mesh_mgr.activate():
            self._train_step = self.telemetry.compile.jit(
                "train_step", self._make_step_fn(), donate_argnums=(0,))
        return self._train_step

    def _ensure_audit_step(self):
        """The shadow-recompute executable for integrity audits: the SAME
        step function as ``_train_step`` but WITHOUT input donation, so the
        auditor can re-run fwd/bwd on state buffers the live step is about
        to consume. Built lazily — never compiled unless an audit fires."""
        if getattr(self, "_audit_step", None) is None:
            with self.mesh_mgr.activate():
                self._audit_step = self.telemetry.compile.jit(
                    "audit_step", self._make_step_fn())
        return self._audit_step

    def _ensure_apply_step(self):
        """The jitted optimizer-apply phase, shared by the forward/backward/
        step API shims and the wall-clock-breakdown path."""
        if self._apply_step is None:
            with self.mesh_mgr.activate():
                self._apply_step = self.telemetry.compile.jit(
                    "apply_step",
                    lambda state, grads, loss, lro: self._apply_update(
                        state, grads, loss, lr_override=lro),
                    donate_argnums=(0,))
        return self._apply_step

    def _build_breakdown_steps(self):
        """Phase-split steps for ``wall_clock_breakdown``: a loss-only
        forward, the grad computation, and the optimizer apply as three
        separately-jitted programs so each phase can be bracketed by a
        synchronized timer."""
        gas = self.gradient_accumulation_steps()

        def fwd_fn(params, batch):
            if gas == 1:
                return self._loss(params, batch)[0]
            losses = jax.lax.map(lambda mb: self._loss(params, mb)[0], batch)
            return jnp.mean(losses)

        def bwd_fn(params, batch, loss_scale):
            return self._accumulate(params, batch, loss_scale)

        with self.mesh_mgr.activate():
            self._fwd_step = self.telemetry.compile.jit("fwd_step", fwd_fn)
            self._bwd_step = self.telemetry.compile.jit("bwd_step", bwd_fn)
        self._ensure_apply_step()

    def _train_batch_breakdown(self, batch) -> StepOutput:
        """Instrumented optimizer step (``wall_clock_breakdown: true``):
        three jitted phases bracketed by device-synchronized timers and
        profiler spans. XLA fuses forward into the grad program, so ``fwd``
        is measured from a dedicated loss-only pass and ``bwd`` is the full
        grad phase (it includes the fused forward, as with rematerialized
        activations). This is a diagnostic mode: it costs roughly one extra
        forward per step and defeats the fused-step overlap — production
        throughput numbers come from the un-instrumented path."""
        if self._bwd_step is None:
            self._build_breakdown_steps()
        t = self.timers
        tracer = self.telemetry.tracer
        with _annotate("fwd"), tracer.span("train/fwd", cat="train"):
            t(FORWARD_GLOBAL_TIMER).start(sync=True)
            self._fwd_step(self.state.params, batch)
            t(FORWARD_GLOBAL_TIMER).stop(sync=True)
        with _annotate("bwd"), tracer.span("train/bwd", cat="train"):
            t(BACKWARD_GLOBAL_TIMER).start()
            grads, loss, aux = self._bwd_step(self.state.params, batch,
                                              self.state.loss_scale)
            t(BACKWARD_GLOBAL_TIMER).stop(sync=True)
        with _annotate("step"), tracer.span("train/step", cat="train"):
            t(STEP_GLOBAL_TIMER).start()
            self.state, out = self._apply_step(self.state, grads, loss,
                                               self._lr_override)
            t(STEP_GLOBAL_TIMER).stop(sync=True)
        return out

    def _estimate_step_flops(self, batch) -> None:
        """One-shot per-step flops estimate from XLA's cost analysis of the
        fused train step → feeds ThroughputTimer TFLOPS reporting. Gated on
        the flops profiler being enabled (the lowering is not free)."""
        self._flops_estimated = True
        try:
            if self._train_step is None:
                self._build_train_step()
            lowered = self._train_step.lower(self.state, batch,
                                             self._lr_override)
            cost = lowered.compile().cost_analysis() or {}
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0))
            if flops > 0:
                self.tput_timer.set_flops_per_step(flops)
                log_dist(f"flops/step estimate: {flops:.3e} "
                         f"(XLA cost analysis)")
        except Exception as e:
            logger.debug(f"step flops estimate unavailable: {e}")

    # ------------------------------------------------------------------ #
    # public API — train_batch (PipelineEngine.train_batch parity)
    # ------------------------------------------------------------------ #
    def _shard_batch(self, batch, with_gas_dim: bool):
        """Reshape global batch [B, ...] → [gas, micro, ...] and place with
        batch sharding over (data, expert) [+ seq on dim 2 when SP active]."""
        gas = self.gradient_accumulation_steps()
        sp = self.mesh_mgr.sp_world_size

        def reshape(x):
            x = jnp.asarray(x)
            if with_gas_dim and gas > 1:
                b = x.shape[0]
                if b % gas != 0:
                    raise ValueError(f"batch dim {b} not divisible by gas={gas}")
                x = x.reshape((gas, b // gas) + x.shape[1:])
            return x

        batch = jax.tree.map(reshape, batch)

        def spec_for(x):
            batch_dim_index = 1 if (with_gas_dim and gas > 1) else 0
            entries = [None] * x.ndim
            if x.ndim > batch_dim_index:
                entries[batch_dim_index] = BATCH_AXES
            seq_dim = batch_dim_index + 1
            # shard the sequence dim for Ulysses SP only when it divides evenly
            # (token arrays often carry a +1 label column)
            if sp > 1 and x.ndim > seq_dim and x.shape[seq_dim] % sp == 0:
                entries[seq_dim] = "seq"
            return NamedSharding(self.mesh_mgr.mesh, P(*entries))

        return jax.tree.map(lambda x: jax.device_put(x, spec_for(x)), batch)

    @staticmethod
    def _count_batch_tokens(batch) -> int:
        """Host-side token estimate for one global batch: the size of the
        ``tokens`` leaf when the batch carries one, the leading (sample) dim
        of the first leaf otherwise. Shape math only — never touches device
        data."""
        try:
            if isinstance(batch, dict) and "tokens" in batch:
                return int(np.prod(np.shape(batch["tokens"])))
            leaves = jax.tree.leaves(batch)
            if leaves:
                shape = np.shape(leaves[0])
                return int(shape[0]) if shape else 1
        except Exception:
            pass
        return 0

    def train_batch(self, batch) -> StepOutput:
        """One full optimizer step from one global batch (all GAS micro-batches
        stacked in the leading dim)."""
        self.global_tokens += self._count_batch_tokens(batch)
        if self._nvme_opt is not None:
            return self._train_batch_nvme(batch)
        if self._tiered_opt:
            return self._train_batch_tiered(batch)
        breakdown = self.wall_clock_breakdown()
        if self._train_step is None and not breakdown:
            self._build_train_step()
        self.tput_timer.start()
        self.telemetry.step_begin(self.global_steps + 1)
        if self.watchdog is not None:
            self.watchdog.step_started()
        if self.curriculum_scheduler is not None:
            # difficulty = seq length; each bucket is its own cached jit
            batch = self.curriculum_scheduler.truncate(batch, self.global_steps)
        batch = self._shard_batch(batch, with_gas_dim=True)
        if not self._flops_estimated and self.config.flops_profiler.enabled:
            self._estimate_step_flops(batch)
        if breakdown:
            self.timers(TRAIN_BATCH_TIMER).start()
            with self.telemetry.tracer.span("train/train_batch", cat="train",
                                            step=self.global_steps + 1):
                out = self._train_batch_breakdown(batch)
            self.timers(TRAIN_BATCH_TIMER).stop(sync=False)
        else:
            # shadow recompute audit (rotating auditor): must run BEFORE
            # the live step donates the state buffers it reads
            if self.integrity is not None:
                self.integrity.pre_step(self, batch)
            # the fused step is ONE XLA program — a single span (the phase
            # split only exists under wall_clock_breakdown)
            with self.telemetry.tracer.span("train/train_batch", cat="train",
                                            step=self.global_steps + 1):
                self.state, out = self._train_step(self.state, batch,
                                                   self._lr_override)
        self.global_steps += 1
        self._last_grad_norm = out.grad_norm
        self.lr_scheduler.last_step = self.global_steps
        self.tput_timer.stop()
        self._write_monitor_events(out)
        self.telemetry.step_end(self.global_steps,
                                step_time_s=self.tput_timer.avg_step_time()
                                or None)
        if self.tuning is not None:
            # optimizer-step seam: the only point a training knob may flip
            # (an apply invalidates the cached step — next batch rebuilds).
            # last_step_time, not the running average: each trial arm must
            # be scored on its own steps
            self.tuning.on_train_step(
                self.global_steps,
                step_time_s=self.tput_timer.last_step_time or None)
        if self.config.steps_per_print and \
                self.global_steps % self.config.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(out.loss):.4f} "
                     f"lr={float(out.lr):.3e} gnorm={float(out.grad_norm):.3f} "
                     f"scale={float(out.loss_scale):.0f}")
        if self.watchdog is not None:
            self.watchdog.observe(self, out)
        if self.integrity is not None:
            self.integrity.on_step(self, out)
        return out

    # ------------------------------------------------------------------ #
    # forward/backward/step shims (DeepSpeedEngine API parity)
    # ------------------------------------------------------------------ #
    def forward(self, batch):
        """Compute loss for one micro-batch (staging it for backward)."""
        if self._grad_step is None:
            def one_micro(params, b, ls):
                grads, loss, aux = self._grads_one_micro(params, b, ls)
                # staged grads live in the stage's (possibly sharded) layout —
                # the API-parity path must not hold replicated fp32 grads
                return self._constrain_grads(
                    jax.tree.map(lambda g: g.astype(jnp.float32), grads)), loss, aux

            with self.mesh_mgr.activate():
                self._grad_step = self.telemetry.compile.jit(
                    "grad_step", one_micro)
        if self.watchdog is not None and not self._staged_batches:
            # first micro-batch of a GAS window: start the stall clock that
            # the boundary step()'s observe() reads
            self.watchdog.step_started()
        self._staged_batches.append(self._shard_batch(batch, with_gas_dim=False))
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).start(sync=True)
        with self.telemetry.tracer.span("train/fwd_micro", cat="train"):
            grads, loss, aux = self._grad_step(self.state.params,
                                               self._staged_batches[-1],
                                               self.state.loss_scale)
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).stop(sync=True)
        self._last_micro = (grads, loss)
        return loss

    def backward(self, loss=None):
        """Accumulate the staged micro-batch's grads (already computed in
        forward — JAX computes loss+grads together)."""
        grads, loss_val = self._last_micro
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).start()
        if getattr(self, "_pending_grads", None) is None:
            self._pending_grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            self._pending_loss = loss_val
            self._pending_count = 1
        else:
            self._pending_grads = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), self._pending_grads, grads)
            self._pending_loss = self._pending_loss + loss_val
            self._pending_count += 1
        self.micro_steps += 1
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).stop(sync=True)
        return loss_val

    def is_gradient_accumulation_boundary(self) -> bool:
        return getattr(self, "_pending_count", 0) >= self.gradient_accumulation_steps()

    def step(self):
        """Apply the optimizer step at the GAS boundary (no-op otherwise,
        matching reference semantics)."""
        if not self.is_gradient_accumulation_boundary():
            return None
        self._ensure_apply_step()
        breakdown = self.wall_clock_breakdown()
        if breakdown:
            self.timers(STEP_MICRO_TIMER).start()
            self.timers(STEP_GLOBAL_TIMER).start()
        n = self._pending_count
        grads = jax.tree.map(lambda g: g / n, self._pending_grads)
        loss = self._pending_loss / n
        with self.telemetry.tracer.span("train/step", cat="train",
                                        step=self.global_steps + 1):
            self.state, out = self._apply_step(self.state, grads, loss,
                                               self._lr_override)
        self._pending_grads = None
        self._pending_loss = None
        self._pending_count = 0
        self._staged_batches.clear()
        self.global_steps += 1
        self._last_grad_norm = out.grad_norm
        if breakdown:
            self.timers(STEP_MICRO_TIMER).stop(sync=True)
            self.timers(STEP_GLOBAL_TIMER).stop(sync=False)
        # commit any in-flight async checkpoint at the boundary (reference
        # decoupled-engine commit, runtime/engine.py:2797)
        ce = getattr(self, "checkpoint_engine", None)
        if ce is not None and getattr(ce, "_pending", None):
            ce.wait_all()
        self._write_monitor_events(out)
        self.telemetry.step_end(self.global_steps)
        if self.watchdog is not None:
            self.watchdog.observe(self, out)
        return out

    def _write_monitor_events(self, out) -> None:
        """Train/Samples/* scalars per step (reference engine.py:2825-2847)."""
        mon = getattr(self, "monitor", None)
        if mon is None or not mon.enabled:
            return
        events = [("Train/Samples/train_loss", float(out.loss),
                   self.global_steps),
                  ("Train/Samples/lr", float(out.lr), self.global_steps)]
        if self.config.fp16.enabled:
            events.append(("Train/Samples/loss_scale", float(out.loss_scale),
                           self.global_steps))
        if out.grad_norm is not None:
            events.append(("Train/Samples/grad_norm", float(out.grad_norm),
                           self.global_steps))
        mon.write_events(events)

    # ------------------------------------------------------------------ #
    # eval / inference forward
    # ------------------------------------------------------------------ #
    def eval_batch(self, batch):
        if not hasattr(self, "_eval_step") or self._eval_step is None:
            with self.mesh_mgr.activate():
                self._eval_step = self.telemetry.compile.jit(
                    "eval_step", lambda p, b: self._loss(p, b)[0])
        batch = self._shard_batch(batch, with_gas_dim=False)
        breakdown = self.wall_clock_breakdown()
        with _annotate("eval_batch"):
            if breakdown:
                self.timers("eval_batch").start(sync=True)
            loss = self._eval_step(self.state.params, batch)
            if breakdown:
                self.timers("eval_batch").stop(sync=True)
        return loss

    def __call__(self, batch):
        return self.forward(batch)

    # ------------------------------------------------------------------ #
    # compile / no_sync (reference engine.compile :4444, no_sync :2518)
    # ------------------------------------------------------------------ #
    def compile(self, example_batch=None, backend: Optional[str] = None,
                **kw) -> "DeepSpeedTPUEngine":
        """Reference ``engine.compile()`` enables torch.compile + DeepCompile
        graph passes; here the train step is ALREADY one compiled XLA program,
        so compile() AOT-lowers it for the example batch shape (warms the
        cache so the first train_batch doesn't pay compile latency) and logs
        the compiler's cost analysis."""
        if self._train_step is None:
            self._build_train_step()
        if example_batch is not None:
            if self.curriculum_scheduler is not None:
                # warm the shape train_batch will actually run first
                example_batch = self.curriculum_scheduler.truncate(
                    example_batch, self.global_steps)
            batch = self._shard_batch(example_batch, with_gas_dim=True)
            lowered = self._train_step.lower(self.state, batch,
                                             self._lr_override)
            compiled = lowered.compile()
            cost = compiled.cost_analysis() or {}
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            log_dist(f"engine.compile: AOT-compiled train step "
                     f"(flops={cost.get('flops', 0):.3e}, "
                     f"bytes={cost.get('bytes accessed', 0):.3e})")
            flops = float(cost.get("flops", 0.0))
            if flops > 0:  # free TFLOPS baseline — the analysis is in hand
                self.tput_timer.set_flops_per_step(flops)
                self._flops_estimated = True
        self._is_compiled = True
        return self

    @property
    def is_compiled(self) -> bool:
        return getattr(self, "_is_compiled", False) or self._train_step is not None

    @contextlib.contextmanager
    def no_sync(self):
        """Reference ``no_sync`` (:2518) disables grad allreduce between
        accumulation steps. Here accumulation is already local —
        forward/backward stage grads without collectives, which only fire in
        the fused step at the boundary — so this is a semantic no-op provided
        for API parity."""
        yield

    # ------------------------------------------------------------------ #
    # dataloader (deepspeed_io parity, runtime/engine.py:2147)
    # ------------------------------------------------------------------ #
    def deepspeed_io(self, dataset, batch_size: Optional[int] = None):
        from .dataloader import DeepSpeedTPUDataLoader

        return DeepSpeedTPUDataLoader(
            dataset,
            batch_size=batch_size or self.train_batch_size(),
            mesh_mgr=self.mesh_mgr)

    # ------------------------------------------------------------------ #
    # checkpointing (full impl in runtime/checkpoint/)
    # ------------------------------------------------------------------ #
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None, **kw):
        from .checkpoint.saver import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state or {})

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None, **kw):
        from .checkpoint.saver import load_checkpoint as _load

        return _load(self, load_dir, tag=tag)

    # --- universal checkpoint v2: elastic, topology-free save/load
    # (runtime/checkpoint/universal.py; docs/reliability.md "Elastic
    # training & universal checkpoint") ---
    def save_universal_checkpoint(self, save_dir: str,
                                  tag: Optional[str] = None,
                                  client_state: Optional[dict] = None,
                                  reason: Optional[str] = None) -> str:
        from .checkpoint.universal import save_universal_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state,
                     reason=reason)

    def load_universal_checkpoint(self, load_dir: str,
                                  tag: Optional[str] = None):
        from .checkpoint.universal import load_universal_checkpoint as _load

        return _load(self, load_dir, tag=tag)

    # ------------------------------------------------------------------ #
    # state offload (reference runtime/engine.py:4533 offload_states)
    # ------------------------------------------------------------------ #
    def offload_states(self, include=None, device: str = "cpu",
                       pin_memory: bool = True, non_blocking: bool = False):
        from .offload_states import offload_engine_states

        offload_engine_states(self, include=include, device=device,
                              pin_memory=pin_memory, non_blocking=non_blocking)

    def reload_states(self, non_blocking: bool = False):
        from .offload_states import reload_engine_states

        reload_engine_states(self, non_blocking=non_blocking)

    # ------------------------------------------------------------------ #
    # shutdown (reference engine.destroy :390)
    # ------------------------------------------------------------------ #
    def destroy(self) -> None:
        """Release observability resources: drain pending async checkpoint
        writers (process exit must never truncate an in-flight save), stop
        any live profiler trace, flush + close monitor backends (so partial
        CSV/JSONL rows land on disk). Safe to call more than once; atexit
        backstops it."""
        ce = getattr(self, "checkpoint_engine", None)
        if ce is not None and hasattr(ce, "wait_all"):
            try:
                ce.wait_all()
            except Exception as e:
                # a failed background save must not mask the shutdown path —
                # log it (the checkpoint was never published, so 'latest'
                # still points at the previous good tag)
                logger.error(f"async checkpoint write failed during "
                             f"shutdown: {e}")
        tel = getattr(self, "telemetry", None)
        if tel is not None:
            tel.close()
        store = getattr(self, "tiered_store", None)
        if store is not None:
            store.close()
        mon = getattr(self, "monitor", None)
        if mon is not None:
            mon.close()


# --------------------------------------------------------------------------- #
# initialize() — reference deepspeed/__init__.py:80
# --------------------------------------------------------------------------- #
def initialize(args=None, model: Optional[ModelSpec] = None, optimizer=None,
               model_parameters=None, training_data=None, lr_scheduler=None,
               config=None, config_params=None, mesh_mgr: Optional[MeshManager] = None,
               rng: Optional[jax.Array] = None, dist_init_required: bool = True,
               devices=None, **kwargs):
    """Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` —
    the reference's 4-tuple.

    ``devices``: build the mesh over this device subset instead of every
    visible device — the elastic runtime (``elasticity/run_elastic``) uses
    it to bring an engine up at a REDUCED chip count after capacity loss."""
    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if model is None:
        raise ValueError("model (ModelSpec) is required")
    hf_model = None
    if not isinstance(model, ModelSpec):
        # reference UX: deepspeed.initialize(model=<HF transformers model>)
        # — import the weights and route to the family's ModelSpec
        # (conversion is deferred until the config is parsed so the family
        # closures compute in the configured precision, not a default)
        from ..models.hf_import import is_hf_model

        if is_hf_model(model):
            hf_model = model
        else:
            raise TypeError(f"model must be a ModelSpec or a transformers "
                            f"model, got {type(model)}")

    if dist_init_required:
        dist.init_distributed()

    devices = list(devices) if devices is not None else None
    n_devices = len(devices) if devices is not None else \
        (mesh_mgr.world_size if mesh_mgr is not None else len(jax.devices()))
    # resolve mesh first so batch math can use the true dp size
    pre = parse_config(config, world_size=n_devices, resolve_batch=False)
    if hf_model is not None:
        from ..models.hf_import import spec_from_hf

        compute_dtype = (jnp.bfloat16 if pre.bf16.enabled else
                         jnp.float16 if pre.fp16.enabled else jnp.float32)
        model = spec_from_hf(hf_model, compute_dtype=compute_dtype)
    axis_sizes = pre.mesh.axis_sizes(n_devices) if pre.raw.get("mesh") else None
    if axis_sizes is None:
        sizes = {"tensor": pre.tensor_parallel.autotp_size or 1,
                 "pipe": pre.pipeline.stages or 1,
                 "seq": pre.sequence_parallel_size or 1,
                 "expert": pre.moe.expert_parallel_size or 1}
        fixed = int(np.prod(list(sizes.values())))
        if n_devices % fixed != 0:
            raise ValueError(f"device count {n_devices} not divisible by {sizes}")
        sizes["data"] = n_devices // fixed
        axis_sizes = sizes
    # MiCS / ZeRO++ hpZ: carve the shard group out of the data axis — ZeRO
    # shards over 'zero_shard' (size G) and replicates over the remaining
    # 'data' groups (reference runtime/zero/mics.py:63, zero_hpz_partition_size)
    mics = max(int(pre.zero_config.mics_shard_size),
               int(pre.zero_config.zero_hpz_partition_size), 1)
    if mics > 1 and int(axis_sizes.get("zero_shard", 1)) == 1:
        data = int(axis_sizes.get("data", 1))
        if data % mics != 0:
            raise ValueError(f"mics/hpz shard size {mics} does not divide "
                             f"data-parallel size {data}")
        axis_sizes["zero_shard"] = mics
        axis_sizes["data"] = data // mics
    if mesh_mgr is None:
        mesh_mgr = init_mesh(axis_sizes, devices)
        if mics > 1 and int(axis_sizes.get("data", 1)) > 1 \
                and not mesh_mgr.dcn_axes:
            # the zero_shard carve models a 2-level topology: 'zero_shard'
            # is the intra-island (ICI) tier, 'data' the cross-island tier —
            # tag it so CommsTelemetry's link-class split can prove which
            # collectives stay inside the island (real multi-slice meshes
            # auto-detect this in MeshManager.create)
            mesh_mgr.set_dcn_axes(("data",))
    dp = int(axis_sizes.get("data", 1)) * int(axis_sizes.get("zero_shard", 1)) \
        * int(axis_sizes.get("expert", 1))
    cfg = parse_config(config, world_size=n_devices, dp_world_size=dp)

    engine = DeepSpeedTPUEngine(model=model, config=cfg, mesh_mgr=mesh_mgr,
                                optimizer=optimizer, lr_schedule=lr_scheduler,
                                training_data=training_data, rng=rng)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler
