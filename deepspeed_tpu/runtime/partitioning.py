"""Logical-axis → mesh-sharding rules: ZeRO stages and TP as sharding specs.

This module is the heart of the TPU-native ZeRO design. The reference
implements ZeRO with runtime machinery — gradient-hook bucketing and
reduce-scatter streams (``runtime/zero/stage_1_and_2.py``), parameter
partitioning/allgather hooks (``stage3.py``, ``partition_parameters.py``,
``partitioned_param_coordinator.py``). On TPU all of that becomes *placement*:

- **stage 1**: params+grads replicated over the ZeRO axes; optimizer state
  sharded. (XLA emits the same reduce-then-shard-update traffic the
  reference's partitioned optimizer does.)
- **stage 2**: + gradients reduce-scattered — expressed by giving grads the
  sharded spec so XLA lowers the grad psum into reduce-scatter.
- **stage 3**: + params sharded; XLA SPMD inserts all-gathers at use sites and
  its latency-hiding scheduler overlaps them with compute (replacing the
  prefetch coordinator).

Tensor parallelism: logical names (heads/mlp/vocab/...) map to the 'tensor'
mesh axis — the same rule table serves training TP and inference AutoTP.

MiCS (``runtime/zero/mics.py``): sharding over a *subset* of the ZeRO axes —
pass ``zero_axes=("expert","seq")`` or reshape the mesh so 'data' spans only a
replication subgroup.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comm.mesh import ZERO_AXES, MeshManager
from ..utils.logging import logger

# default logical-axis → mesh-axis rules (t5x-style)
DEFAULT_RULES: Dict[str, Optional[Any]] = {
    "heads": "tensor",
    "kv_heads": "tensor",
    "mlp": "tensor",
    "vocab": "tensor",
    "tp": "tensor",       # generic AutoTP-inferred dim (module_inject/auto_tp)
    "expert": "expert",   # MoE expert dim
    "embed": None,
    "layers": None,       # stays unsharded for scan; 'pipe' when PP is active
    "kv": None,
}


def logical_to_spec(axes: Tuple[Optional[str], ...],
                    rules: Dict[str, Optional[Any]]) -> P:
    return P(*[rules.get(a) if a is not None else None for a in axes])


def _add_zero_axes(spec: P, axes: Tuple[Optional[str], ...], shape: Tuple[int, ...],
                   axis_sizes: Dict[str, int], zero_axes: Sequence[str]) -> P:
    """Shard one currently-unsharded dim over the ZeRO axes. Prefers the
    largest divisible non-'layers' dim (keeps lax.scan over layers clean);
    falls back to 'layers' if it is the only divisible dim.

    Mesh axes already used by the spec (e.g. 'expert' on expert-bank params)
    are excluded — the ZeRO group of an expert param is the data axes only,
    mirroring the reference's expert-data-parallel groups
    (``utils/groups.py:240-495``). Divisibility is checked against the product
    of the *remaining* axes."""
    entries = list(spec)
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a is not None:
                used.add(a)
    zero_axes = tuple(a for a in zero_axes if a not in used)
    zero_size = int(np.prod([axis_sizes[a] for a in zero_axes])) if zero_axes else 1
    if zero_size <= 1:
        return spec
    candidates = []
    for i, (rule, logical) in enumerate(zip(entries, axes)):
        if rule is not None or i >= len(shape):
            continue
        if shape[i] % zero_size == 0:
            candidates.append((logical != "layers", shape[i], -i))
    if not candidates:
        return spec  # replicated — too small to shard (persistence threshold analog)
    candidates.sort(reverse=True)
    idx = -candidates[0][2]
    entries[idx] = tuple(zero_axes)
    return P(*entries)


class Partitioner:
    """Derives param / grad / optimizer-state shardings for a model.

    ``logical_axes``: pytree (matching params) of per-dim logical names.
    """

    def __init__(self, mesh_mgr: MeshManager, zero_stage: int = 0,
                 rules: Optional[Dict[str, Any]] = None,
                 zero_axes: Sequence[str] = ZERO_AXES,
                 tensor_parallel: bool = True,
                 pipeline_layers: bool = True,
                 secondary_axes: Optional[Sequence[str]] = None):
        self.mm = mesh_mgr
        self.zero_stage = zero_stage
        self.zero_axes = tuple(a for a in zero_axes if mesh_mgr.axis_size(a) > 1)
        self.axis_sizes = {a: mesh_mgr.axis_size(a) for a in self.zero_axes}
        # ZeRO++ hpZ (zero_hpz_partition_size, arXiv:2306.10209): a SECONDARY
        # parameter partition over the intra-island axes only. Masters/opt
        # state/grads keep the full (primary) ZeRO sharding; the stage-3
        # compute-param layout shards over these axes instead, so fwd/bwd
        # gathers resolve inside the island and only the once-per-step
        # primary gather (master -> secondary) crosses the 'data' tier.
        self.secondary_axes: Optional[Tuple[str, ...]] = None
        if secondary_axes is not None:
            self.secondary_axes = tuple(
                a for a in secondary_axes if mesh_mgr.axis_size(a) > 1)
        self.zero_size = int(np.prod([mesh_mgr.axis_size(a) for a in self.zero_axes])) \
            if self.zero_axes else 1
        self.rules = dict(DEFAULT_RULES)
        if mesh_mgr.pp_world_size > 1 and pipeline_layers:
            # stacked layer dim lives on the pipe axis (stage-local params);
            # only when the model actually executes via pipeline_apply
            self.rules["layers"] = "pipe"
        if rules:
            self.rules.update(rules)
        if not tensor_parallel or mesh_mgr.tp_world_size == 1:
            for k, v in list(self.rules.items()):
                if v == "tensor":
                    self.rules[k] = None

    # --- spec derivation ---
    def _base_specs(self, logical_axes, shapes, shard_extra: bool,
                    zero_axes: Optional[Tuple[str, ...]] = None):
        axes_set = self.zero_axes if zero_axes is None else zero_axes
        sizes = (self.axis_sizes if zero_axes is None
                 else {a: self.mm.axis_size(a) for a in axes_set})

        def one(axes, shape):
            spec = logical_to_spec(tuple(axes), self.rules)
            if shard_extra:
                spec = _add_zero_axes(spec, tuple(axes), tuple(shape),
                                      sizes, axes_set)
            return spec

        return jax.tree.map(one, logical_axes, shapes,
                            is_leaf=lambda x: isinstance(x, tuple))

    def param_specs(self, logical_axes, shapes):
        """Parameter shardings: TP always; + ZeRO axes at stage 3. With an
        hpZ ``secondary_axes`` set, the stage-3 compute layout shards over
        the secondary (intra-island) axes only — masters keep the full
        primary sharding (``opt_state_specs``)."""
        if self.zero_stage >= 3 and self.secondary_axes is not None:
            return self._base_specs(logical_axes, shapes,
                                    shard_extra=bool(self.secondary_axes),
                                    zero_axes=self.secondary_axes)
        return self._base_specs(logical_axes, shapes, shard_extra=self.zero_stage >= 3)

    def gathered_param_specs(self, logical_axes, shapes):
        """The compute (TP-only) layout a ZeRO-sharded param leaf has AFTER
        its all-gather — the target layout for qwZ's int8 gather."""
        return self._base_specs(logical_axes, shapes, shard_extra=False)

    def grad_specs(self, logical_axes, shapes):
        """Gradient shardings: match params at stage<=1; reduce-scattered
        (sharded) at stage >= 2."""
        return self._base_specs(logical_axes, shapes, shard_extra=self.zero_stage >= 2)

    def opt_state_specs(self, logical_axes, shapes):
        """Optimizer-state (and fp32 master weight) shardings: sharded from
        stage 1 up."""
        return self._base_specs(logical_axes, shapes, shard_extra=self.zero_stage >= 1)

    # --- sharding constructors ---
    def shardings(self, specs):
        return jax.tree.map(lambda s: NamedSharding(self.mm.mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))


def shapes_of(tree):
    return jax.tree.map(lambda x: tuple(x.shape), tree)


def abstract_shapes_of(tree):
    """Shapes from a ``jax.eval_shape`` result — the zero.Init-equivalent path
    (materialize nothing, derive shardings from abstract values)."""
    return jax.tree.map(lambda x: tuple(x.shape), tree)
