"""Domino: tensor-parallel linear layers with communication/compute overlap.

Reference parity: ``runtime/domino/`` — ``DominoAsyncColumnParallelLinear``
(``async_linear.py``) and the tensor-slicing transformer block
(``transformer.py``) that launches TP all-reduces on side streams and
overlaps them with the other half-batch's compute.

TPU-first: XLA's latency-hiding scheduler performs exactly this overlap for
collectives it can move, so the *mechanism* (streams, async handles) has no
analog to port — what this module provides is the reference's *API surface*
and its batch-splitting schedule: ``domino_block`` splits the tokens into two
half-batches inside one jit so the all-reduce of half 0 overlaps the matmuls
of half 1 in the compiled schedule. Use inside ``shard_map`` over the
'tensor' axis; outside shard_map, pjit sharding constraints give the same
effect with zero code.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def column_parallel_linear(x: jnp.ndarray, w_shard: jnp.ndarray,
                           bias_shard: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Column-parallel: weight sharded on the OUTPUT dim; no collective on
    the forward (reference ColumnParallelLinear). x: [..., in] replicated;
    w_shard: [in, out/tp] local shard → [..., out/tp]."""
    y = x @ w_shard
    if bias_shard is not None:
        y = y + bias_shard
    return y


def row_parallel_linear(x_shard: jnp.ndarray, w_shard: jnp.ndarray,
                        axis: str = "tensor",
                        bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Row-parallel: weight sharded on the INPUT dim; partial products are
    all-reduced over the TP axis (reference LinearAllreduce /
    RowParallelLinear). Call inside shard_map."""
    y = lax.psum(x_shard @ w_shard, axis)
    if bias is not None:
        y = y + bias
    return y


def domino_block(block_fn: Callable[[jnp.ndarray], jnp.ndarray],
                 x: jnp.ndarray, num_chunks: int = 2) -> jnp.ndarray:
    """Run ``block_fn`` over ``num_chunks`` micro-slices of the batch in one
    jit: XLA interleaves chunk i's TP collectives with chunk i+1's compute —
    the reference's Domino row/column pipelining without stream plumbing.
    x: [batch, ...]; batch must divide by num_chunks."""
    b = x.shape[0]
    if b % num_chunks:
        raise ValueError(f"batch {b} not divisible by {num_chunks} chunks")
    chunks = x.reshape(num_chunks, b // num_chunks, *x.shape[1:])
    out = lax.map(block_fn, chunks)
    return out.reshape(b, *out.shape[2:])
