"""Profiler range annotations — reference ``deepspeed/utils/nvtx.py``.

The reference wraps hot functions in NVTX ranges
(``get_accelerator().range_push/pop``) so they show up named in nsight
traces. The TPU equivalents are ``jax.named_scope`` (names HLO ops, visible
in xprof/tensorboard traces) and ``jax.profiler.TraceAnnotation`` (names
host-side spans). ``instrument_w_nvtx`` keeps the reference decorator name.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax


def instrument_w_nvtx(func: Callable) -> Callable:
    """Decorator: record the function under its qualified name in both the
    compiled trace (named_scope) and the host profiler timeline."""
    name = getattr(func, "__qualname__", getattr(func, "__name__", "fn"))

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            return func(*args, **kwargs)

    return wrapped


def range_push(name: str):
    """Imperative range begin (reference accelerator.range_push)."""
    ctx = jax.profiler.TraceAnnotation(name)
    ctx.__enter__()
    _stack.append(ctx)
    return ctx


def range_pop():
    if _stack:
        _stack.pop().__exit__(None, None, None)


_stack: list = []
