"""Rank-filtered logging.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (``log_dist``,
``logger``): rank-0-by-default logging that works in multi-host JAX jobs, where
"rank" is ``jax.process_index()`` rather than a torch.distributed rank.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

_LOGGER_NAME = "deepspeed_tpu"

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = _LOGGER_NAME, level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        # stderr by default: stdout is a machine-readable contract for the
        # bench/CLI tools (ONE JSON line) and log lines must never pollute it
        stream = (sys.stdout if os.environ.get("DSTPU_LOG_STREAM") == "stdout"
                  else sys.stderr)
        handler = logging.StreamHandler(stream=stream)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                              datefmt="%Y-%m-%d %H:%M:%S"))
        lg.addHandler(handler)
    env_level = os.environ.get("DSTPU_LOG_LEVEL")
    if env_level:
        lg.setLevel(log_levels.get(env_level.lower(), logging.INFO))
    return lg


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # jax not initialised yet / single process
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process indices (default: rank 0).

    ``ranks=[-1]`` logs on every process.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
