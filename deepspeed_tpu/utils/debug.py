"""Debug helpers (reference ``deepspeed/utils/debug.py`` — module/param
naming + ``deepspeed.runtime.utils`` NaN checks, recast for pytrees).

The reference walks live ``nn.Module`` trees; here the model IS a pytree, so
the debug surface is: stable path-names for every leaf, a NaN/Inf sweep that
reports names instead of crashing deep inside a jit, and a compiled-memory
dump for "where did my HBM go" questions."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .logging import logger
from .tree import path_to_str


def param_names(tree: Any) -> Dict[str, Any]:
    """{'layers/wq': leaf, ...} — stable slash-joined path per leaf
    (reference ``debug_extract_module_and_param_names``)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {path_to_str(path, sep="/") or "<root>": leaf
            for path, leaf in flat}


def find_nonfinite(tree: Any) -> List[Tuple[str, int]]:
    """[(leaf_name, count_of_nonfinite)] over every float leaf — host-side,
    call OUTSIDE jit on materialized values (reference ``check_grad_overflow``
    per-tensor variant)."""
    bad = []
    for name, leaf in param_names(tree).items():
        if not (hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            continue
        n = int(np.sum(~np.isfinite(np.asarray(leaf))))
        if n:
            bad.append((name, n))
    return bad


def assert_all_finite(tree: Any, what: str = "tree") -> None:
    bad = find_nonfinite(tree)
    if bad:
        detail = ", ".join(f"{n} ({c} values)" for n, c in bad[:8])
        raise FloatingPointError(f"non-finite values in {what}: {detail}")


def tree_summary(tree: Any, top: int = 10) -> str:
    """Human-readable largest-leaves table (bytes, shape, dtype) — the
    'where did my HBM go' companion to ``see_memory_usage``."""
    rows = []
    for name, leaf in param_names(tree).items():
        if hasattr(leaf, "nbytes"):
            rows.append((int(leaf.nbytes), name, tuple(leaf.shape),
                         str(leaf.dtype)))
    rows.sort(reverse=True)
    total = sum(r[0] for r in rows)
    lines = [f"total {total / 1e6:.1f} MB over {len(rows)} leaves"]
    for nbytes, name, shape, dtype in rows[:top]:
        lines.append(f"  {nbytes / 1e6:9.1f} MB  {name}  {shape} {dtype}")
    return "\n".join(lines)


def log_tree_summary(tree: Any, what: str = "tree", top: int = 10) -> None:
    logger.info("%s:\n%s", what, tree_summary(tree, top))


def compiled_memory_report(compiled) -> Dict[str, int]:
    """Byte breakdown of a ``jit(...).lower(...).compile()`` artifact
    (argument/output/temp/generated code sizes) — XLA's answer to the
    reference's ``see_memory_usage`` at the program level."""
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    return {k: int(getattr(ma, k, 0) or 0)
            for k in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "generated_code_size_in_bytes",
                      "alias_size_in_bytes")}
