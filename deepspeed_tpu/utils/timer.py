"""Wall-clock and throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` at :44, ``ThroughputTimer`` at :199). On TPU,
"synchronized" means blocking on the async JAX dispatch queue
(``jax.block_until_ready`` / ``device.synchronize_all_activity``) instead of CUDA
events.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .logging import log_dist, logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _sync_device() -> None:
    """Drain the async dispatch queue so host wall-clock brackets device work."""
    try:
        import jax

        # effective and cheap: blocks until all in-flight computations finish
        for d in jax.local_devices():
            try:
                d.synchronize_all_activity()
            except Exception:
                pass
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0  # seconds, accumulated since last reset
        self._records: List[float] = []

    def start(self, sync: bool = False) -> None:
        if self.started:
            return
        if sync:
            _sync_device()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = True, record: bool = True) -> None:
        if not self.started:
            return
        if sync:
            _sync_device()
        dt = time.perf_counter() - self._start
        self._elapsed += dt
        if record:
            self._records.append(dt)
        self.started = False

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._records.clear()

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed seconds since last reset (stops nothing)."""
        value = self._elapsed
        if self.started:
            value += time.perf_counter() - self._start
        if reset:
            self._elapsed = 0.0
            self._records.clear()
        return value

    def mean(self) -> float:
        return sum(self._records) / len(self._records) if self._records else 0.0


class SynchronizedWallClockTimer:
    """Named timers synchronized against device completion.

    Mirrors the reference API: ``timers(name).start()/stop()``, ``timers.log(names)``.
    """

    def __init__(self):
        self.timers: "OrderedDict[str, _Timer]" = OrderedDict()

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: Optional[List[str]] = None, normalizer: float = 1.0,
            reset: bool = True, memory_breakdown: bool = False, ranks=None) -> None:
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        return {n: self.timers[n].mean() * 1000.0 / normalizer for n in names if n in self.timers}


@dataclass
class ThroughputTimer:
    """Samples/sec + TFLOPS tracking (reference ``utils/timer.py:199``)."""

    batch_size: int = 1
    start_step: int = 2  # skip compile/warmup steps
    steps_per_output: int = 0
    monitor_memory: bool = False
    logging_fn: Optional[object] = None
    # model flops for ONE optimizer step (all micro-batches); set via
    # set_flops_per_step (typically from FlopsProfiler / XLA cost analysis)
    # to make the periodic log line and avg_tflops_per_sec report TFLOPS
    flops_per_step: Optional[float] = None

    total_elapsed: float = 0.0
    step_count: int = 0
    # wall time of the most recent MEASURED step (post-warmup), seconds —
    # unlike avg_step_time this doesn't smear across a config change, so
    # the online tuner scores each trial arm on its own steps
    last_step_time: float = 0.0
    _start: float = field(default=0.0, repr=False)
    _started: bool = field(default=False, repr=False)

    def start(self) -> None:
        self._start = time.perf_counter()
        self._started = True

    def stop(self, global_step: bool = True, report_speed: bool = True) -> None:
        if not self._started:
            return
        self._started = False
        self.step_count += 1
        if self.step_count > self.start_step:
            _sync_device()
            self.last_step_time = time.perf_counter() - self._start
            self.total_elapsed += self.last_step_time
            if (report_speed and self.steps_per_output
                    and self.step_count % self.steps_per_output == 0):
                msg = (f"step={self.step_count}, "
                       f"samples/sec={self.avg_samples_per_sec():.2f}")
                if self.flops_per_step:
                    msg += f", TFLOPS={self.avg_tflops_per_sec():.2f}"
                logger.info(msg)

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        self.flops_per_step = float(flops) if flops else None

    def avg_samples_per_sec(self) -> float:
        counted = self.step_count - self.start_step
        if counted <= 0 or self.total_elapsed == 0:
            return 0.0
        return counted * self.batch_size / self.total_elapsed

    def avg_step_time(self) -> float:
        counted = self.step_count - self.start_step
        if counted <= 0:
            return 0.0
        return self.total_elapsed / counted

    def avg_tflops_per_sec(self) -> float:
        """Achieved model TFLOPS (needs flops_per_step + measured steps)."""
        st = self.avg_step_time()
        if not st or not self.flops_per_step:
            return 0.0
        return self.flops_per_step / st / 1e12
