"""Memory introspection — reference ``see_memory_usage``
(``runtime/utils.py``) and the ``memory_breakdown`` config."""

from __future__ import annotations

from typing import Dict, Optional

import jax

from .logging import log_dist


def memory_stats(device: Optional[jax.Device] = None) -> Dict[str, float]:
    """Device memory stats in GB (empty dict on backends without stats)."""
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return {}
    gb = 1 << 30
    return {
        "in_use_GB": stats.get("bytes_in_use", 0) / gb,
        "peak_GB": stats.get("peak_bytes_in_use", 0) / gb,
        "limit_GB": stats.get("bytes_limit", 0) / gb,
        "reserved_GB": stats.get("bytes_reserved", 0) / gb,
    }


def see_memory_usage(message: str, force: bool = False) -> Dict[str, float]:
    """Log current/peak device memory (reference ``see_memory_usage``):
    silent unless ``force=True`` (or DSTPU_MEMORY_BREAKDOWN=1), matching the
    reference's default-off behavior so per-step call sites don't spam."""
    import os

    s = memory_stats()
    if not (force or os.environ.get("DSTPU_MEMORY_BREAKDOWN")):
        return s
    if s:
        log_dist(f"{message} | MA {s['in_use_GB']:.2f} GB  "
                 f"Max_MA {s['peak_GB']:.2f} GB  "
                 f"limit {s['limit_GB']:.2f} GB")
    else:
        log_dist(f"{message} | (no device memory stats on "
                 f"{jax.default_backend()})")
    return s
