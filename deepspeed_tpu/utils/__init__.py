from .logging import log_dist, logger
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["log_dist", "logger", "SynchronizedWallClockTimer", "ThroughputTimer"]
