"""Pytree helpers shared across subsystems."""

from __future__ import annotations


def path_to_str(path, sep: str = ".") -> str:
    """jax KeyPath → joined string ('layers.wq', 'opt.0.mu.embed', ...)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return sep.join(parts)
