"""Pytree helpers shared across subsystems."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cast_floating(tree, dtype):
    """astype(dtype) on floating leaves; everything else untouched."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x, tree)


def path_to_str(path, sep: str = ".") -> str:
    """jax KeyPath → joined string ('layers.wq', 'opt.0.mu.embed', ...)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return sep.join(parts)
