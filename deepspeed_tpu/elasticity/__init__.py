from .elastic_agent import elastic_train_config, run_elastic  # noqa: F401
from .elasticity import (compute_elastic_config, ElasticityError,  # noqa: F401
                         get_compatible_chip_counts)
