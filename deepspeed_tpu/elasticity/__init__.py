from .elastic_agent import (PreemptionGuard, elastic_train_config,  # noqa: F401
                            run_elastic)
from .elasticity import (compute_elastic_config, ElasticityError,  # noqa: F401
                         get_compatible_chip_counts)
