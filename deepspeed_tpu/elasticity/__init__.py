from .elasticity import (compute_elastic_config, ElasticityError,  # noqa: F401
                         get_compatible_chip_counts)
