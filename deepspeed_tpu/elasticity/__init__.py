from .elastic_agent import (PreemptionGuard, elastic_train_config,  # noqa: F401
                            read_reshard_hint, run_elastic,
                            write_reshard_hint)
from .elasticity import (best_chips_at_most, compute_elastic_config,  # noqa: F401
                         ElasticityError, ElasticityIncompatibleWorldSize,
                         get_compatible_chip_counts)
