"""Elastic training agent: resume-at-different-scale orchestration.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(torch-elastic rendezvous; worker failure → re-rendezvous → restart from
checkpoint). On TPU there is no in-job rendezvous to subclass — scale changes
arrive as a NEW set of hosts/chips (the resource manager restarts the job),
so the agent's work is the RESUME protocol:

1. at startup, read the elastic config and the current chip count;
2. pick the (micro_batch, gas) the elastic math assigns to this scale —
   the GLOBAL batch is invariant across restarts (``compute_elastic_config``);
3. load the latest (universal) checkpoint onto the new topology.

``run_elastic`` packages those steps around ``deepspeed_tpu.initialize``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist
from .elasticity import compute_elastic_config


def elastic_train_config(base_config: Dict[str, Any],
                         n_chips: Optional[int] = None) -> Dict[str, Any]:
    """Resolve a config's ``elasticity`` block against the CURRENT chip
    count → concrete micro-batch/GAS entries (invariant global batch)."""
    ec = base_config.get("elasticity", {})
    if not ec.get("enabled"):
        return dict(base_config)
    n_chips = n_chips if n_chips is not None else len(jax.devices())
    batch, mb, cfg = compute_elastic_config(ec, target_chips=n_chips,
                                            return_microbatch=True)
    out = dict(base_config)
    out.pop("train_batch_size", None)
    out["train_micro_batch_size_per_gpu"] = mb
    out["gradient_accumulation_steps"] = cfg.gradient_accumulation_steps
    log_dist(f"elastic: {n_chips} chips → global batch {batch} "
             f"(micro {mb} × gas {cfg.gradient_accumulation_steps} × "
             f"dp {n_chips})")
    return out


def run_elastic(model_spec, base_config: Dict[str, Any],
                checkpoint_dir: Optional[str] = None,
                n_chips: Optional[int] = None, **init_kw) -> Tuple[Any, ...]:
    """Bring up an engine at the current scale and resume state if a
    checkpoint exists (reference: elastic agent restart path)."""
    import deepspeed_tpu as dst

    config = elastic_train_config(base_config, n_chips)
    engine, opt, loader, sched = dst.initialize(model=model_spec,
                                                config=config, **init_kw)
    if checkpoint_dir is not None:
        try:
            path, _ = engine.load_checkpoint(checkpoint_dir)
            if path:
                log_dist(f"elastic resume from {path} at step "
                         f"{engine.global_steps}")
        except FileNotFoundError:
            log_dist("elastic: no checkpoint yet — fresh start")
    return engine, opt, loader, sched


# --------------------------------------------------------------------------- #
# in-job failure / preemption hook
# --------------------------------------------------------------------------- #
def _process_count() -> int:
    return jax.process_count()


class PreemptionGuard:
    """In-job failure hook (reference ``DSElasticAgent._invoke_run:127`` —
    monitor workers, on UNHEALTHY/FAILED checkpoint-and-restart at a new
    scale). On TPU the failure signal is a PREEMPTION: the resource manager
    sends SIGTERM with a grace window before reclaiming the slice. The guard
    installs signal handlers that flip a flag; the training loop calls
    :meth:`step_boundary` between steps — when the flag is up it saves a
    checkpoint and returns True so the loop exits cleanly, and the next
    incarnation resumes at its (possibly different) scale via
    :func:`run_elastic`.

    Usage::

        guard = PreemptionGuard(save_dir="ckpts")
        engine, *_ = run_elastic(spec, config, checkpoint_dir="ckpts")
        for batch in loader:
            engine.train_batch(batch)
            if guard.step_boundary(engine):
                break          # checkpointed; exit for the restart
    """

    def __init__(self, save_dir: str, *, signals: Tuple[int, ...] = None,
                 tag: Optional[str] = None, coordinate_interval: int = 1,
                 watchdog=None):
        import signal as _signal

        self.save_dir = save_dir
        self.tag = tag
        # multi-host flag agreement runs every Nth boundary (all ranks share
        # the same counter so they agree on WHICH boundaries coordinate);
        # raise it to amortize the per-step allgather on big pods — the
        # trade is up to N-1 extra steps of the SIGTERM grace window
        self.coordinate_interval = max(1, int(coordinate_interval))
        self._boundary_count = 0
        self._triggered = False
        self._signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        # a bound TrainingWatchdog (runtime/watchdog.py) with
        # on_violation="exit" requests checkpoint-and-exit through the SAME
        # boundary protocol a preemption signal uses
        self.watchdog = watchdog
        if watchdog is not None and hasattr(watchdog, "bind_guard"):
            watchdog.bind_guard(self)
        if signals is None:
            signals = (_signal.SIGTERM,)
        for s in signals:
            self._prev[s] = _signal.signal(s, self._on_signal)

    @staticmethod
    def _dump_traces(reason: str) -> None:
        """Preemption may be the last thing this process does — land every
        live flight recorder NOW (telemetry/trace.py), not at the step
        boundary the grace window might not reach. Best-effort."""
        try:
            from ..telemetry.trace import dump_all

            dump_all(reason)
        except Exception:
            pass

    def _on_signal(self, signum, frame):
        self._triggered = True
        self._signum = signum
        log_dist(f"PreemptionGuard: received signal {signum} — will "
                 f"checkpoint at the next step boundary")
        self._dump_traces("preemption_signal")
        prev = self._prev.get(signum)
        if callable(prev):  # chain whatever handler was there before
            prev(signum, frame)

    def trigger(self, signum: Optional[int] = None) -> None:
        """Deliver a SYNTHETIC preemption (no OS signal, no handler
        chaining) — the entry point `deepspeed_tpu.testing.faults.preempt`
        uses to exercise the checkpoint-on-SIGTERM path deterministically."""
        self._triggered = True
        self._signum = signum
        log_dist(f"PreemptionGuard: synthetic preemption"
                 f"{f' (signal {signum})' if signum is not None else ''} — "
                 f"will checkpoint at the next step boundary")
        self._dump_traces("preemption_synthetic")

    @property
    def triggered(self) -> bool:
        return self._triggered

    def step_boundary(self, engine) -> bool:
        """Checkpoint-and-signal-exit when a preemption arrived. Returns
        True exactly once per trigger; safe to call every step (no-op when
        no signal is pending).

        Multi-host: SIGTERM can land on different hosts at different times,
        but ``engine.save_checkpoint`` is COLLECTIVE (orbax over sharded
        arrays) — entering it at mismatched steps hangs or corrupts the
        checkpoint (the reference coordinates restarts through torch-elastic
        rendezvous, ``elastic_agent.py:32``). So the local flag is agreed on
        globally at every boundary: an allgather-OR, synchronous with the
        step's collectives, guarantees every process sees the trigger at the
        SAME boundary and checkpoints the same step."""
        wd_exit = bool(self.watchdog is not None and
                       getattr(self.watchdog, "restart_requested", False))
        local = self._triggered or wd_exit
        trig = local
        self._boundary_count += 1
        if _process_count() > 1 and \
                self._boundary_count % self.coordinate_interval == 0:
            import numpy as _np
            from jax.experimental import multihost_utils

            trig = bool(multihost_utils.process_allgather(
                _np.asarray(local)).any())
        elif _process_count() > 1:
            # off-cadence boundaries never act on the LOCAL flag alone —
            # acting would desynchronize the collective save
            trig = False
        if not trig:
            return False
        self._triggered = False  # once per trigger — never re-save the
        # checkpoint on later calls inside the preemption grace window
        if wd_exit:
            self.watchdog.restart_requested = False
        self._reliability(engine, "preemption_signal")
        path = engine.save_checkpoint(self.save_dir, tag=self.tag)
        self._reliability(engine, "preemption_checkpoint")
        cause = "watchdog exit request" if wd_exit else \
            f"signal {self._signum or 'on a peer host'}"
        log_dist(f"PreemptionGuard: checkpoint saved to {path} after "
                 f"{cause}; exit for elastic restart")
        return True

    @staticmethod
    def _reliability(engine, name: str) -> None:
        tel = getattr(engine, "telemetry", None)
        if tel is not None and hasattr(tel, "reliability_event"):
            tel.reliability_event(name, 1.0,
                                  int(getattr(engine, "global_steps", 0)))

    def uninstall(self) -> None:
        import signal as _signal

        for s, prev in self._prev.items():
            _signal.signal(s, prev)
        self._prev.clear()
