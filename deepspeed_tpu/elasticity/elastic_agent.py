"""Elastic training agent: resume-at-different-scale orchestration.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(torch-elastic rendezvous; worker failure → re-rendezvous → restart from
checkpoint). On TPU there is no in-job rendezvous to subclass — scale changes
arrive as a NEW set of hosts/chips (the resource manager restarts the job),
so the agent's work is the RESUME protocol:

1. at startup, read the elastic config and the current chip count;
2. pick the (micro_batch, gas) the elastic math assigns to this scale —
   the GLOBAL batch is invariant across restarts (``compute_elastic_config``);
3. load the latest (universal) checkpoint onto the new topology.

``run_elastic`` packages those steps around ``deepspeed_tpu.initialize``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist
from .elasticity import compute_elastic_config


def elastic_train_config(base_config: Dict[str, Any],
                         n_chips: Optional[int] = None) -> Dict[str, Any]:
    """Resolve a config's ``elasticity`` block against the CURRENT chip
    count → concrete micro-batch/GAS entries (invariant global batch)."""
    ec = base_config.get("elasticity", {})
    if not ec.get("enabled"):
        return dict(base_config)
    n_chips = n_chips if n_chips is not None else len(jax.devices())
    batch, mb, cfg = compute_elastic_config(ec, target_chips=n_chips,
                                            return_microbatch=True)
    out = dict(base_config)
    out.pop("train_batch_size", None)
    out["train_micro_batch_size_per_gpu"] = mb
    out["gradient_accumulation_steps"] = cfg.gradient_accumulation_steps
    log_dist(f"elastic: {n_chips} chips → global batch {batch} "
             f"(micro {mb} × gas {cfg.gradient_accumulation_steps} × "
             f"dp {n_chips})")
    return out


def run_elastic(model_spec, base_config: Dict[str, Any],
                checkpoint_dir: Optional[str] = None,
                n_chips: Optional[int] = None, **init_kw) -> Tuple[Any, ...]:
    """Bring up an engine at the current scale and resume state if a
    checkpoint exists (reference: elastic agent restart path)."""
    import deepspeed_tpu as dst

    config = elastic_train_config(base_config, n_chips)
    engine, opt, loader, sched = dst.initialize(model=model_spec,
                                                config=config, **init_kw)
    if checkpoint_dir is not None:
        try:
            path, _ = engine.load_checkpoint(checkpoint_dir)
            if path:
                log_dist(f"elastic resume from {path} at step "
                         f"{engine.global_steps}")
        except FileNotFoundError:
            log_dist("elastic: no checkpoint yet — fresh start")
    return engine, opt, loader, sched
