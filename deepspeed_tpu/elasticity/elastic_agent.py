"""Elastic training agent: resume-at-different-scale orchestration.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(torch-elastic rendezvous; worker failure → re-rendezvous → restart from
checkpoint). On TPU there is no in-job rendezvous to subclass — scale changes
arrive as a NEW set of hosts/chips (the resource manager restarts the job),
so the agent's work is the RESUME protocol:

1. at startup, read the elastic config, the current chip count, and — when
   the previous incarnation left one — the machine-readable **reshard hint**
   (``reshard_hint.json``: why the job exited, at what step, and the batch
   invariants to preserve);
2. pick the (chips, micro_batch, gas) triple the elastic math assigns to the
   available capacity — the GLOBAL batch is invariant across restarts
   (``compute_elastic_config`` / ``best_chips_at_most``);
3. rebuild the engine at the new topology (a device SUBSET when capacity
   shrank) and restore from the latest **universal** checkpoint
   (``runtime/checkpoint/universal.py`` — fragments reshard onto any mesh /
   ZeRO stage / optimizer tier), falling back to a regular checkpoint when
   the tag predates the elastic runtime.

``run_elastic`` packages those steps around ``deepspeed_tpu.initialize``.
The in-job half — preemption signals, watchdog host-loss detection — is
:class:`PreemptionGuard`, which with ``universal=True`` answers every exit
cause with a durable universal save plus the reshard hint the next
incarnation consumes. See docs/reliability.md "Elastic training & universal
checkpoint"; the whole cycle is drilled by
``deepspeed_tpu.testing.drill.elastic_drill``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist
from .elasticity import best_chips_at_most, compute_elastic_config

RESHARD_HINT_NAME = "reshard_hint.json"


# --------------------------------------------------------------------------- #
# reshard hint — the machine-readable handoff between incarnations
# --------------------------------------------------------------------------- #
def write_reshard_hint(save_dir: str, hint: Dict[str, Any]) -> str:
    """Durably publish ``reshard_hint.json`` next to the checkpoint tags
    (write-tmp + fsync + atomic rename, like the ``latest`` pointer)."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, RESHARD_HINT_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(hint, f, indent=2, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_reshard_hint(save_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(save_dir, RESHARD_HINT_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _hint_from_engine(engine, reason: str, tag: Optional[str],
                      signum: Optional[int] = None) -> Dict[str, Any]:
    hint = {
        "reason": reason,
        "signum": signum,
        "step": int(engine.global_steps),
        "tag": tag,
        "global_batch": int(engine.train_batch_size()),
        "micro_batch": int(engine.train_micro_batch_size_per_gpu()),
        "gas": int(engine.gradient_accumulation_steps()),
        "chips": int(engine.mesh_mgr.world_size),
        "mesh": {k: int(v) for k, v in engine.mesh_mgr.mesh.shape.items()},
        "zero_stage": int(engine.config.zero_config.stage),
        "elasticity": dict(engine.config.elasticity or {}),
        "time": time.time(),
    }
    # numerics-integrity verdicts ride the hint (reliability/integrity.py):
    # quarantined hosts are excluded from the next incarnation's device
    # pool, and audit-confirmed corruption pins resume to the newest tag at
    # or before the last verified step (walk-back — never resume poisoned
    # weights)
    ip = getattr(engine, "integrity", None)
    hint["excluded_hosts"] = sorted(
        int(h) for h in getattr(ip, "excluded_hosts", []) or [])
    if ip is not None and getattr(ip, "walkback_requested", False):
        hint["walkback_to_verified"] = True
        hint["last_verified_step"] = int(
            getattr(ip, "last_verified_step", -1))
    return hint


def elastic_train_config(base_config: Dict[str, Any],
                         n_chips: Optional[int] = None) -> Dict[str, Any]:
    """Resolve a config's ``elasticity`` block against the CURRENT chip
    count → concrete micro-batch/GAS entries (invariant global batch)."""
    ec = base_config.get("elasticity", {})
    if not ec.get("enabled"):
        return dict(base_config)
    n_chips = n_chips if n_chips is not None else len(jax.devices())
    batch, mb, cfg = compute_elastic_config(ec, target_chips=n_chips,
                                            return_microbatch=True)
    out = dict(base_config)
    out.pop("train_batch_size", None)
    out["train_micro_batch_size_per_gpu"] = mb
    out["gradient_accumulation_steps"] = cfg.gradient_accumulation_steps
    log_dist(f"elastic: {n_chips} chips → global batch {batch} "
             f"(micro {mb} × gas {cfg.gradient_accumulation_steps} × "
             f"dp {n_chips})")
    return out


def run_elastic(model_spec, base_config: Dict[str, Any],
                checkpoint_dir: Optional[str] = None,
                n_chips: Optional[int] = None, devices=None,
                excluded_hosts=None, device_host_fn=None,
                **init_kw) -> Tuple[Any, ...]:
    """Bring up an engine at the current scale and resume state if a
    checkpoint exists (reference: elastic agent restart path).

    With an ``elasticity`` block in ``base_config``, the (chips, micro, gas)
    triple comes from the elastic math for the AVAILABLE capacity — and when
    the previous incarnation left a reshard hint under ``checkpoint_dir``,
    the chosen scale is validated against it (the global batch must be the
    one the trajectory was trained at). Universal checkpoint tags restore
    through ``engine.load_universal_checkpoint`` (reshard onto the new
    topology, dataloader/RNG fast-forward); legacy tags through the regular
    loader.

    A ``reshard_hint.json`` carrying ``excluded_hosts`` (an integrity
    quarantine — docs/reliability.md "Numerics integrity & SDC") removes
    those hosts' devices from the pool before the scale is chosen;
    ``excluded_hosts`` merges extra exclusions in. ``device_host_fn`` maps a
    device to its host id (default: ``device.process_index``) — drills
    simulating an N-host fleet on one process override it."""
    import deepspeed_tpu as dst

    devices = list(devices) if devices is not None else list(jax.devices())
    hint = read_reshard_hint(checkpoint_dir) if checkpoint_dir else None
    excluded = set(int(h) for h in (excluded_hosts or []))
    excluded.update(int(h) for h in (hint or {}).get("excluded_hosts") or [])
    if excluded:
        host_of = device_host_fn or \
            (lambda d: int(getattr(d, "process_index", 0)))
        keep = [d for d in devices if int(host_of(d)) not in excluded]
        if keep:
            log_dist(f"elastic: excluding quarantined host(s) "
                     f"{sorted(excluded)} — {len(devices) - len(keep)} "
                     f"device(s) removed from the pool")
            devices = keep
        else:
            log_dist(f"elastic: exclusion of host(s) {sorted(excluded)} "
                     f"would leave no devices — ignoring the quarantine "
                     f"(single-host pool)")
    available = len(devices) if n_chips is None \
        else min(int(n_chips), len(devices))
    ec = base_config.get("elasticity", {})
    chips = available
    if ec.get("enabled"):
        # the available capacity may not be a compatible scale — come back
        # at the largest compatible chip count that fits (reference
        # _invoke_run restart-at-new-world-size semantics)
        chips = best_chips_at_most(ec, available)
        if chips != available:
            log_dist(f"elastic: {available} chip(s) available but {chips} is "
                     f"the largest compatible scale — running at {chips}")
    config = elastic_train_config(base_config, chips)
    if hint is not None and ec.get("enabled"):
        gb = int(hint.get("global_batch", 0) or 0)
        mb = int(config.get("train_micro_batch_size_per_gpu", 0) or 0)
        gas = int(config.get("gradient_accumulation_steps", 1) or 1)
        if gb and mb * gas * chips != gb:
            raise RuntimeError(
                f"elastic resume would change the global batch: hint says "
                f"{gb}, the new topology gives {mb}*{gas}*{chips}="
                f"{mb * gas * chips} — the elasticity block no longer "
                f"matches the checkpointed run")
    sub = devices[:chips]
    engine, opt, loader, sched = dst.initialize(
        model=model_spec, config=config,
        devices=None if sub == list(jax.devices()) else sub, **init_kw)
    if checkpoint_dir is not None:
        resumed = _resume(engine, checkpoint_dir, hint=hint)
        if resumed and hint is not None:
            old_mesh = hint.get("mesh") or {}
            new_mesh = {k: int(v) for k, v in engine.mesh_mgr.mesh.shape.items()}
            if old_mesh != new_mesh or \
                    int(hint.get("zero_stage", -1)) != \
                    int(engine.config.zero_config.stage):
                tel = getattr(engine, "telemetry", None)
                if tel is not None and hasattr(tel, "reliability_event"):
                    tel.reliability_event("elastic/reshards", 1.0,
                                          int(engine.global_steps))
                log_dist(f"elastic: resharded {old_mesh} (stage "
                         f"{hint.get('zero_stage')}) → {new_mesh} (stage "
                         f"{engine.config.zero_config.stage}) at step "
                         f"{engine.global_steps}")
    return engine, opt, loader, sched


def _walkback_tag(checkpoint_dir: str, max_step: int) -> Optional[str]:
    """Newest VERIFIED tag whose step is <= ``max_step`` (PR 3 machinery:
    meta.json steps via ``tag_candidates``, SHA-256 manifests via
    ``verify_manifest``). None when every retained tag postdates the last
    verified step or fails verification."""
    import json

    from ..runtime.checkpoint.manifest import tag_candidates, verify_manifest

    for name in tag_candidates(checkpoint_dir):
        full = os.path.join(checkpoint_dir, name)
        try:
            with open(os.path.join(full, "meta.json")) as f:
                steps = int(json.load(f).get("global_steps", -1))
        except (OSError, ValueError, TypeError):
            continue
        if steps < 0 or steps > int(max_step):
            continue
        status, detail = verify_manifest(full)
        if status == "corrupt":
            log_dist(f"elastic: walk-back skipping corrupt tag {name} "
                     f"({detail})")
            continue
        return name
    return None


def _resume(engine, checkpoint_dir: str,
            hint: Optional[Dict[str, Any]] = None) -> bool:
    """Restore from the newest tag under ``checkpoint_dir`` — universal
    (fragment) tags via the elastic loader, legacy tags via the regular
    one. Returns True when a checkpoint was loaded.

    When the reshard hint says ``walkback_to_verified`` (an integrity audit
    confirmed corruption after ``last_verified_step``), resume is pinned to
    the newest verified tag at or before that step — the newer, suspect
    tags stay on disk for the post-mortem but are never resumed."""
    from ..runtime.checkpoint.saver import resolve_tag
    from ..runtime.checkpoint.universal import is_universal_tag

    tag = None
    walkback = bool(hint and hint.get("walkback_to_verified"))
    if walkback:
        max_step = int(hint.get("last_verified_step", -1))
        tag = _walkback_tag(checkpoint_dir, max_step)
        if tag is None:
            log_dist(f"elastic: walk-back found no verified tag at or "
                     f"before step {max_step} — fresh start")
            return False
        log_dist(f"elastic: integrity walk-back — resuming from verified "
                 f"tag {tag} (<= step {max_step}), ignoring newer suspect "
                 f"tags")
    else:
        try:
            tag = resolve_tag(checkpoint_dir, None)
        except FileNotFoundError:
            log_dist("elastic: no checkpoint yet — fresh start")
            return False
    if is_universal_tag(os.path.join(checkpoint_dir, tag)):
        path, _ = engine.load_universal_checkpoint(checkpoint_dir, tag=tag)
    else:
        path, _ = engine.load_checkpoint(checkpoint_dir, tag=tag)
    if path:
        if walkback:
            tel = getattr(engine, "telemetry", None)
            if tel is not None and hasattr(tel, "reliability_event"):
                tel.reliability_event("integrity/walkbacks", 1.0,
                                      int(engine.global_steps))
        log_dist(f"elastic resume from {path} at step {engine.global_steps}")
        return True
    return False


# --------------------------------------------------------------------------- #
# in-job failure / preemption hook
# --------------------------------------------------------------------------- #
def _process_count() -> int:
    return jax.process_count()


class PreemptionGuard:
    """In-job failure hook (reference ``DSElasticAgent._invoke_run:127`` —
    monitor workers, on UNHEALTHY/FAILED checkpoint-and-restart at a new
    scale). On TPU the failure signal is a PREEMPTION: the resource manager
    sends SIGTERM with a grace window before reclaiming the slice. The guard
    installs signal handlers that flip a flag; the training loop calls
    :meth:`step_boundary` between steps — when the flag is up it saves a
    checkpoint and returns True so the loop exits cleanly, and the next
    incarnation resumes at its (possibly different) scale via
    :func:`run_elastic`.

    ``universal=True`` makes the exit ELASTIC: the boundary save is a
    topology-free universal checkpoint (``engine.save_universal_checkpoint``)
    and a machine-readable ``reshard_hint.json`` lands beside it — the
    restart can come back at ANY compatible chip count. The same protocol
    answers watchdog ``on_violation: exit`` requests and heartbeat host-loss
    detection (``runtime/watchdog.py HostHeartbeat``).

    Usage::

        guard = PreemptionGuard(save_dir="ckpts", universal=True)
        engine, *_ = run_elastic(spec, config, checkpoint_dir="ckpts")
        for batch in loader:
            engine.train_batch(batch)
            if guard.step_boundary(engine):
                break          # checkpointed; exit for the restart
    """

    def __init__(self, save_dir: str, *, signals: Tuple[int, ...] = None,
                 tag: Optional[str] = None, coordinate_interval: int = 1,
                 watchdog=None, universal: bool = False):
        import signal as _signal

        self.save_dir = save_dir
        self.tag = tag
        self.universal = bool(universal)
        # multi-host flag agreement runs every Nth boundary (all ranks share
        # the same counter so they agree on WHICH boundaries coordinate);
        # raise it to amortize the per-step allgather on big pods — the
        # trade is up to N-1 extra steps of the SIGTERM grace window
        self.coordinate_interval = max(1, int(coordinate_interval))
        self._boundary_count = 0
        self._triggered = False
        self._signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        # a bound TrainingWatchdog (runtime/watchdog.py) with
        # on_violation="exit" requests checkpoint-and-exit through the SAME
        # boundary protocol a preemption signal uses
        self.watchdog = watchdog
        if watchdog is not None and hasattr(watchdog, "bind_guard"):
            watchdog.bind_guard(self)
        if signals is None:
            signals = (_signal.SIGTERM,)
        for s in signals:
            self._prev[s] = _signal.signal(s, self._on_signal)

    @staticmethod
    def _dump_traces(reason: str) -> None:
        """Preemption may be the last thing this process does — land every
        live flight recorder NOW (telemetry/trace.py), not at the step
        boundary the grace window might not reach. Best-effort."""
        try:
            from ..telemetry.trace import dump_all

            dump_all(reason)
        except Exception:
            pass

    def _on_signal(self, signum, frame):
        self._triggered = True
        self._signum = signum
        log_dist(f"PreemptionGuard: received signal {signum} — will "
                 f"checkpoint at the next step boundary")
        self._dump_traces("preemption_signal")
        prev = self._prev.get(signum)
        if callable(prev):  # chain whatever handler was there before
            prev(signum, frame)

    def trigger(self, signum: Optional[int] = None) -> None:
        """Deliver a SYNTHETIC preemption (no OS signal, no handler
        chaining) — the entry point `deepspeed_tpu.testing.faults.preempt`
        uses to exercise the checkpoint-on-SIGTERM path deterministically;
        the watchdog's host-loss handler calls it too."""
        self._triggered = True
        self._signum = signum
        log_dist(f"PreemptionGuard: synthetic preemption"
                 f"{f' (signal {signum})' if signum is not None else ''} — "
                 f"will checkpoint at the next step boundary")
        self._dump_traces("preemption_synthetic")

    @property
    def triggered(self) -> bool:
        return self._triggered

    def step_boundary(self, engine) -> bool:
        """Checkpoint-and-signal-exit when a preemption arrived. Returns
        True exactly once per trigger; safe to call every step (no-op when
        no signal is pending).

        Multi-host: SIGTERM can land on different hosts at different times,
        but ``engine.save_checkpoint`` is COLLECTIVE (orbax over sharded
        arrays) — entering it at mismatched steps hangs or corrupts the
        checkpoint (the reference coordinates restarts through torch-elastic
        rendezvous, ``elastic_agent.py:32``). So the local flag is agreed on
        globally at every boundary: an allgather-OR, synchronous with the
        step's collectives, guarantees every process sees the trigger at the
        SAME boundary and checkpoints the same step."""
        wd_exit = bool(self.watchdog is not None and
                       getattr(self.watchdog, "restart_requested", False))
        # the integrity plane requests the SAME elastic exit on quarantine /
        # audit-confirmed corruption (reliability/integrity.py _escalate)
        ip = getattr(engine, "integrity", None)
        ip_exit = bool(ip is not None and
                       getattr(ip, "restart_requested", False))
        local = self._triggered or wd_exit or ip_exit
        trig = local
        self._boundary_count += 1
        if _process_count() > 1 and \
                self._boundary_count % self.coordinate_interval == 0:
            import numpy as _np
            from jax.experimental import multihost_utils

            trig = bool(multihost_utils.process_allgather(
                _np.asarray(local)).any())
        elif _process_count() > 1:
            # off-cadence boundaries never act on the LOCAL flag alone —
            # acting would desynchronize the collective save
            trig = False
        if not trig:
            return False
        self._triggered = False  # once per trigger — never re-save the
        # checkpoint on later calls inside the preemption grace window
        wd_reason = getattr(self.watchdog, "restart_reason", None) \
            if wd_exit else None
        if wd_exit:
            self.watchdog.restart_requested = False
        ip_reason = getattr(ip, "restart_reason", None) if ip_exit else None
        if ip_exit:
            ip.restart_requested = False
        self._reliability(engine, "preemption_signal")
        reason = wd_reason or ip_reason or \
            ("watchdog exit request" if wd_exit else "preemption")
        if self.universal:
            path = engine.save_universal_checkpoint(self.save_dir,
                                                    tag=self.tag,
                                                    reason=reason)
            write_reshard_hint(self.save_dir, _hint_from_engine(
                engine, reason, tag=os.path.basename(path),
                signum=self._signum))
        else:
            path = engine.save_checkpoint(self.save_dir, tag=self.tag)
        self._reliability(engine, "preemption_checkpoint")
        cause = reason if wd_exit else \
            f"signal {self._signum or 'on a peer host'}"
        log_dist(f"PreemptionGuard: checkpoint saved to {path} after "
                 f"{cause}; exit for elastic restart")
        return True

    @staticmethod
    def _reliability(engine, name: str) -> None:
        tel = getattr(engine, "telemetry", None)
        if tel is not None and hasattr(tel, "reliability_event"):
            tel.reliability_event(name, 1.0,
                                  int(getattr(engine, "global_steps", 0)))

    def uninstall(self) -> None:
        import signal as _signal

        for s, prev in self._prev.items():
            _signal.signal(s, prev)
        self._prev.clear()
