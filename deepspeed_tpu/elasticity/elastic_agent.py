"""Elastic training agent: resume-at-different-scale orchestration.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(torch-elastic rendezvous; worker failure → re-rendezvous → restart from
checkpoint). On TPU there is no in-job rendezvous to subclass — scale changes
arrive as a NEW set of hosts/chips (the resource manager restarts the job),
so the agent's work is the RESUME protocol:

1. at startup, read the elastic config and the current chip count;
2. pick the (micro_batch, gas) the elastic math assigns to this scale —
   the GLOBAL batch is invariant across restarts (``compute_elastic_config``);
3. load the latest (universal) checkpoint onto the new topology.

``run_elastic`` packages those steps around ``deepspeed_tpu.initialize``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist
from .elasticity import compute_elastic_config


def elastic_train_config(base_config: Dict[str, Any],
                         n_chips: Optional[int] = None) -> Dict[str, Any]:
    """Resolve a config's ``elasticity`` block against the CURRENT chip
    count → concrete micro-batch/GAS entries (invariant global batch)."""
    ec = base_config.get("elasticity", {})
    if not ec.get("enabled"):
        return dict(base_config)
    n_chips = n_chips if n_chips is not None else len(jax.devices())
    batch, mb, cfg = compute_elastic_config(ec, target_chips=n_chips,
                                            return_microbatch=True)
    out = dict(base_config)
    out.pop("train_batch_size", None)
    out["train_micro_batch_size_per_gpu"] = mb
    out["gradient_accumulation_steps"] = cfg.gradient_accumulation_steps
    log_dist(f"elastic: {n_chips} chips → global batch {batch} "
             f"(micro {mb} × gas {cfg.gradient_accumulation_steps} × "
             f"dp {n_chips})")
    return out


def run_elastic(model_spec, base_config: Dict[str, Any],
                checkpoint_dir: Optional[str] = None,
                n_chips: Optional[int] = None, **init_kw) -> Tuple[Any, ...]:
    """Bring up an engine at the current scale and resume state if a
    checkpoint exists (reference: elastic agent restart path)."""
    import deepspeed_tpu as dst

    config = elastic_train_config(base_config, n_chips)
    engine, opt, loader, sched = dst.initialize(model=model_spec,
                                                config=config, **init_kw)
    if checkpoint_dir is not None:
        try:
            path, _ = engine.load_checkpoint(checkpoint_dir)
            if path:
                log_dist(f"elastic resume from {path} at step "
                         f"{engine.global_steps}")
        except FileNotFoundError:
            log_dist("elastic: no checkpoint yet — fresh start")
    return engine, opt, loader, sched


# --------------------------------------------------------------------------- #
# in-job failure / preemption hook
# --------------------------------------------------------------------------- #
class PreemptionGuard:
    """In-job failure hook (reference ``DSElasticAgent._invoke_run:127`` —
    monitor workers, on UNHEALTHY/FAILED checkpoint-and-restart at a new
    scale). On TPU the failure signal is a PREEMPTION: the resource manager
    sends SIGTERM with a grace window before reclaiming the slice. The guard
    installs signal handlers that flip a flag; the training loop calls
    :meth:`step_boundary` between steps — when the flag is up it saves a
    checkpoint and returns True so the loop exits cleanly, and the next
    incarnation resumes at its (possibly different) scale via
    :func:`run_elastic`.

    Usage::

        guard = PreemptionGuard(save_dir="ckpts")
        engine, *_ = run_elastic(spec, config, checkpoint_dir="ckpts")
        for batch in loader:
            engine.train_batch(batch)
            if guard.step_boundary(engine):
                break          # checkpointed; exit for the restart
    """

    def __init__(self, save_dir: str, *, signals: Tuple[int, ...] = None,
                 tag: Optional[str] = None):
        import signal as _signal

        self.save_dir = save_dir
        self.tag = tag
        self._triggered = False
        self._signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        if signals is None:
            signals = (_signal.SIGTERM,)
        for s in signals:
            self._prev[s] = _signal.signal(s, self._on_signal)

    def _on_signal(self, signum, frame):
        self._triggered = True
        self._signum = signum
        log_dist(f"PreemptionGuard: received signal {signum} — will "
                 f"checkpoint at the next step boundary")
        prev = self._prev.get(signum)
        if callable(prev):  # chain whatever handler was there before
            prev(signum, frame)

    @property
    def triggered(self) -> bool:
        return self._triggered

    def step_boundary(self, engine) -> bool:
        """Checkpoint-and-signal-exit when a preemption arrived. Returns
        True exactly once per trigger; safe to call every step (no-op when
        no signal is pending)."""
        if not self._triggered:
            return False
        self._triggered = False  # once per trigger — never re-save the
        # checkpoint on later calls inside the preemption grace window
        path = engine.save_checkpoint(self.save_dir, tag=self.tag)
        log_dist(f"PreemptionGuard: checkpoint saved to {path} after "
                 f"signal {self._signum}; exit for elastic restart")
        return True

    def uninstall(self) -> None:
        import signal as _signal

        for s, prev in self._prev.items():
            _signal.signal(s, prev)
        self._prev.clear()
