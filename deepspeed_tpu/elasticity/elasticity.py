"""Elastic batch configuration.

Reference parity: ``deepspeed/elasticity/elasticity.py:233
compute_elastic_config`` (+ candidate-batch algorithms v0.1 :83 / v0.2 :126)
— given a maximum acceptable global batch size and a set of micro-batch
candidates, enumerate the chip counts at which the job can run with an
IDENTICAL effective batch, so a restarted job at a different scale keeps its
training schedule. The reference's torch-elastic agent becomes: resume from a
(universal) checkpoint on the new mesh; this module supplies the math, the
checkpoint layer supplies the state portability.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current chip count is not in the chosen batch's compatible list
    (reference ``elasticity/config.py`` exception of the same name)."""


def _candidate_batch_sizes(base_list: Sequence[int], max_batch: int) -> List[int]:
    """All attainable global batch sizes: multiples of each micro-batch
    candidate up to max (reference v0.1 ``get_candidate_batch_sizes``)."""
    out = set()
    for mb in base_list:
        b = mb
        while b <= max_batch:
            out.add(b)
            b += mb
    return sorted(out)


def _valid_chip_counts(batch: int, micro_batches: Sequence[int],
                      min_chips: int, max_chips: int,
                      prefer_larger: bool) -> List[Tuple[int, int, int]]:
    """(chips, micro_batch, gas) triples with chips*mb*gas == batch."""
    out = []
    for mb in micro_batches:
        if batch % mb:
            continue
        total_steps = batch // mb  # chips × gas
        for chips in range(min_chips, max_chips + 1):
            if total_steps % chips == 0:
                out.append((chips, mb, total_steps // chips))
    out.sort(key=lambda t: (t[0], t[1] if not prefer_larger else -t[1]))
    return out


def get_compatible_chip_counts(micro_batches: Sequence[int], max_batch: int,
                               min_chips: int = 1, max_chips: int = 1024,
                               prefer_larger: bool = True) -> Dict[int, List[Tuple[int, int, int]]]:
    """batch size → feasible (chips, micro_batch, gas) list.

    Raises :class:`ElasticityError` naming the infeasible inputs instead of
    returning an empty dict (``max_batch`` below the smallest micro-batch —
    or chip bounds that admit no split — previously produced ``{}`` with no
    diagnostic and the caller crashed later on an empty table)."""
    candidates = _candidate_batch_sizes(micro_batches, max_batch)
    if not candidates:
        raise ElasticityError(
            f"no attainable global batch size: max_train_batch_size="
            f"{max_batch} is below the smallest micro-batch candidate "
            f"{min(micro_batches) if micro_batches else '<empty>'} "
            f"(micro_batch_sizes={list(micro_batches)})")
    result = {}
    for b in candidates:
        triples = _valid_chip_counts(b, micro_batches, min_chips, max_chips,
                                     prefer_larger)
        if triples:
            result[b] = triples
    if not result:
        raise ElasticityError(
            f"no feasible (chips, micro_batch, gas) split: "
            f"micro_batch_sizes={list(micro_batches)}, "
            f"max_train_batch_size={max_batch}, chip bounds "
            f"[{min_chips}, {max_chips}]")
    return result


def best_chips_at_most(elastic_config: Dict, available: int) -> int:
    """Largest compatible chip count not exceeding ``available`` — the scale
    an elastic restart should come back at after capacity loss (global batch
    invariant; reshard-hint consumption in ``elastic_agent.run_elastic``)."""
    _, cfg = compute_elastic_config(elastic_config)
    usable = [c for c in cfg.compatible_chip_counts if c <= int(available)]
    if not usable:
        raise ElasticityIncompatibleWorldSize(
            f"no compatible chip count fits the {available} available "
            f"chip(s); compatible counts: {cfg.compatible_chip_counts}")
    return max(usable)


@dataclasses.dataclass
class ElasticConfig:
    global_batch_size: int
    micro_batch_size: int
    gradient_accumulation_steps: int
    chips: int
    compatible_chip_counts: List[int]


def compute_elastic_config(elastic_config: Dict, target_chips: Optional[int] = None,
                           return_microbatch: bool = False):
    """Reference ``compute_elastic_config`` (``elasticity.py:233``): pick the
    best (global batch, micro batch, gas) for ``target_chips`` under the
    user's elastic constraints dict:

        {"enabled": true, "max_train_batch_size": N,
         "micro_batch_sizes": [...], "min_gpus": a, "max_gpus": b,
         "prefer_larger_batch": true, "version": 0.2}
    """
    if not elastic_config.get("enabled", False):
        raise ElasticityError("elasticity not enabled in config")
    version = float(elastic_config.get("version", LATEST_ELASTICITY_VERSION))
    if version > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(f"unsupported elasticity version {version}")
    max_batch = int(elastic_config["max_train_batch_size"])
    micro_batches = [int(m) for m in elastic_config["micro_batch_sizes"]]
    if not micro_batches or any(m <= 0 for m in micro_batches):
        raise ElasticityError(f"bad micro_batch_sizes {micro_batches}")
    min_chips = int(elastic_config.get("min_gpus",
                                       elastic_config.get("min_chips", 1)))
    max_chips = int(elastic_config.get("max_gpus",
                                       elastic_config.get("max_chips", 1024)))
    prefer_larger = bool(elastic_config.get("prefer_larger_batch", True))

    table = get_compatible_chip_counts(micro_batches, max_batch, min_chips,
                                       max_chips, prefer_larger)
    if not table:
        raise ElasticityError("no feasible elastic configuration")

    # Choose the batch size ONCE, independent of the current scale — that is
    # the elasticity promise (restart anywhere on the compatible list with an
    # identical effective batch; reference get_best_candidates). TPU twist on
    # the score: slices come in power-of-two chip counts, so we rank by how
    # many power-of-two scales a batch supports (the reference ranks by raw
    # count, which favours highly-composite batches full of odd GPU counts
    # that no TPU slice will ever have). Ties break to the larger batch.
    def score(b):
        chips = {t[0] for t in table[b]}
        pow2 = sum(1 for c in chips if c & (c - 1) == 0)
        return (pow2, len(chips), b if prefer_larger else -b)

    best_batch = max(table, key=score)
    if target_chips is not None and \
            not any(t[0] == target_chips for t in table[best_batch]):
        compatible = sorted({t[0] for t in table[best_batch]})
        raise ElasticityIncompatibleWorldSize(
            f"{target_chips} chips incompatible with elastic batch "
            f"{best_batch}; compatible counts: {compatible}")
    triples = table[best_batch]
    compatible = sorted({t[0] for t in triples})
    if target_chips is None:
        target_chips = compatible[-1]  # default to the largest feasible scale
    match = [t for t in triples if t[0] == target_chips]
    # triples are sorted so match[0] respects prefer_larger_batch
    chips, mb, gas = match[0]
    cfg = ElasticConfig(global_batch_size=best_batch, micro_batch_size=mb,
                        gradient_accumulation_steps=gas, chips=chips,
                        compatible_chip_counts=compatible)
    if return_microbatch:
        return cfg.global_batch_size, cfg.micro_batch_size, cfg
    return cfg.global_batch_size, cfg


def main(argv=None) -> int:
    """``dstpu_elastic`` CLI (reference ``bin/ds_elastic`` →
    ``elasticity/elastic_agent`` info tool): read a config JSON, print the
    resolved elastic batch and the chip counts it admits."""
    import argparse
    import json

    p = argparse.ArgumentParser(prog="dstpu_elastic")
    p.add_argument("-c", "--config", required=True,
                   help="DeepSpeed-style config JSON with an 'elasticity' block")
    p.add_argument("-w", "--world-size", type=int, default=None,
                   help="validate this chip count against the config")
    args = p.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    elastic = cfg.get("elasticity")
    if not elastic:
        print("no 'elasticity' block in config")
        return 1
    try:
        final_batch, micro, ecfg = compute_elastic_config(
            elastic, target_chips=args.world_size, return_microbatch=True)
    except ElasticityError as e:
        print(f"error: {e}")
        return 1
    print(f"final batch size ........ {final_batch}")
    print(f"micro batch per chip .... {micro}")
    print(f"grad accumulation ....... {ecfg.gradient_accumulation_steps}")
    print(f"compatible chip counts .. {ecfg.compatible_chip_counts}")
    return 0
