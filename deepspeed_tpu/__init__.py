"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Capability parity with DeepSpeed (reference: ``deepspeed/__init__.py``), designed
TPU-first: named device meshes + XLA collectives instead of NCCL process groups,
sharding specs instead of runtime partitioning hooks, jit-compiled train steps
instead of engine-orchestrated streams, Pallas kernels instead of CUDA.

Public entry points (reference parity):
- :func:`initialize` — config + model → (engine, optimizer, dataloader, scheduler)
  (reference ``deepspeed/__init__.py:80``)
- :func:`init_inference` — inference engine (reference :313)
- ``comm`` — collectives API (reference ``deepspeed/comm``)
"""

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .runtime.config import DeepSpeedTPUConfig, parse_config  # noqa: F401


def initialize(*args, **kwargs):
    from .runtime.engine import initialize as _init

    return _init(*args, **kwargs)


def init_inference(*args, **kwargs):
    from .inference.engine import init_inference as _init

    return _init(*args, **kwargs)
