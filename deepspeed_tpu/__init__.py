"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Capability parity with DeepSpeed (reference: ``deepspeed/__init__.py``), designed
TPU-first: named device meshes + XLA collectives instead of NCCL process groups,
sharding specs instead of runtime partitioning hooks, jit-compiled train steps
instead of engine-orchestrated streams, Pallas kernels instead of CUDA.

Public entry points (reference parity):
- :func:`initialize` — config + model → (engine, optimizer, dataloader, scheduler)
  (reference ``deepspeed/__init__.py:80``)
- :func:`init_inference` — inference engine (reference :313)
- ``comm`` — collectives API (reference ``deepspeed/comm``)
"""

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .runtime.config import DeepSpeedTPUConfig, parse_config  # noqa: F401


def initialize(*args, **kwargs):
    from .runtime.engine import initialize as _init

    return _init(*args, **kwargs)


def init_inference(*args, **kwargs):
    from .inference.engine import init_inference as _init

    return _init(*args, **kwargs)


def tp_model_init(*args, **kwargs):
    from .runtime.zero_init import tp_model_init as _init

    return _init(*args, **kwargs)


class _ZeroNamespace:
    """``deepspeed_tpu.zero`` — reference ``deepspeed.zero`` namespace."""

    @property
    def Init(self):
        from .runtime.zero_init import Init

        return Init

    @property
    def GatheredParameters(self):
        from .runtime.zero_init import GatheredParameters

        return GatheredParameters

    @property
    def materialize_sharded(self):
        from .runtime.zero_init import materialize_sharded

        return materialize_sharded


zero = _ZeroNamespace()
