"""Minimal latent-diffusion (Stable-Diffusion-style) inference tier.

Reference parity: ``model_implementations/diffusers/unet.py`` /``vae.py``
(DSUNet/DSVAE — CUDA-graph captures around the denoiser and VAE) and
``csrc/spatial/csrc/opt_bias_add.cu`` (fused NHWC bias-add for the conv
stacks). The reference wraps user-supplied ``diffusers`` modules; this
module is self-contained (a compact UNet + VAE decoder + DDIM sampler)
because the TPU path has no torch modules to wrap.

TPU-first redesign:
- The CUDA-graph capture IS ``jax.jit``: the ENTIRE denoise loop (all
  sampler steps, ``lax.scan``) compiles into one XLA program — the same
  "record once, replay every call" property, plus cross-step fusion the
  graph capture cannot do.
- ``opt_bias_add``'s fusions (bias+add, bias+residual) are XLA fusions:
  convs run NHWC (the TPU-native conv layout), and GroupNorm→SiLU→conv
  chains fuse automatically — no hand kernel tier.
- Cross-attention rides the shared attention op stack (``ops/attention``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = Dict[str, Any]
_DN = ("NHWC", "HWIO", "NHWC")  # TPU-native conv layout


# --------------------------------------------------------------------------- #
# config
# --------------------------------------------------------------------------- #
@dataclass
class DiffusionConfig:
    in_channels: int = 4            # latent channels
    model_channels: int = 64
    channel_mults: Tuple[int, ...] = (1, 2)
    num_res_blocks: int = 1
    num_groups: int = 8             # GroupNorm groups
    num_heads: int = 4
    context_dim: int = 64           # text-conditioning width
    vae_channels: int = 32
    image_channels: int = 3
    num_train_timesteps: int = 1000

    @classmethod
    def tiny(cls, **kw) -> "DiffusionConfig":
        base = dict(in_channels=4, model_channels=16, channel_mults=(1, 2),
                    num_res_blocks=1, num_groups=4, num_heads=2,
                    context_dim=16, vae_channels=8)
        base.update(kw)
        return cls(**base)


# --------------------------------------------------------------------------- #
# primitives
# --------------------------------------------------------------------------- #
def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               groups: int, eps: float = 1e-5) -> jnp.ndarray:
    """GroupNorm over NHWC (reference spatial tier normalization; XLA fuses
    the normalize→SiLU→conv chain that opt_bias_add.cu hand-fuses)."""
    B, H, W, C = x.shape
    g = x.reshape(B, H, W, groups, C // groups).astype(jnp.float32)
    mean = g.mean(axis=(1, 2, 4), keepdims=True)
    var = g.var(axis=(1, 2, 4), keepdims=True)
    g = (g - mean) * jax.lax.rsqrt(var + eps)
    return (g.reshape(B, H, W, C) * scale + bias).astype(x.dtype)


def _conv(x, w, b=None, stride=1):
    out = lax.conv_general_dilated(x, w.astype(x.dtype),
                                   (stride, stride), "SAME",
                                   dimension_numbers=_DN)
    if b is not None:
        out = out + b.astype(x.dtype)   # the opt_bias_add fusion, via XLA
    return out


def timestep_embedding(t: jnp.ndarray, dim: int,
                       max_period: float = 10000.0) -> jnp.ndarray:
    """Sinusoidal timestep embedding [B, dim] (standard DDPM encoding)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(max_period) * jnp.arange(half) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _init_conv(rng, kh, kw, cin, cout, scale=1.0):
    w = jax.random.normal(rng, (kh, kw, cin, cout)) * \
        (scale / np.sqrt(kh * kw * cin))
    return {"w": w.astype(jnp.float32), "b": jnp.zeros((cout,))}


def _init_dense(rng, cin, cout, scale=1.0):
    w = jax.random.normal(rng, (cin, cout)) * (scale / np.sqrt(cin))
    return {"w": w.astype(jnp.float32), "b": jnp.zeros((cout,))}


def _dense(p, x):
    return x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)


# --------------------------------------------------------------------------- #
# UNet blocks
# --------------------------------------------------------------------------- #
def _init_resblock(rng, cin, cout, temb_dim):
    ks = jax.random.split(rng, 4)
    p = {"norm1": {"s": jnp.ones((cin,)), "b": jnp.zeros((cin,))},
         "conv1": _init_conv(ks[0], 3, 3, cin, cout),
         "temb": _init_dense(ks[1], temb_dim, cout),
         "norm2": {"s": jnp.ones((cout,)), "b": jnp.zeros((cout,))},
         "conv2": _init_conv(ks[2], 3, 3, cout, cout, scale=1e-5)}
    if cin != cout:
        p["skip"] = _init_conv(ks[3], 1, 1, cin, cout)
    return p


def _resblock(cfg, p, x, temb):
    h = jax.nn.silu(group_norm(x, p["norm1"]["s"], p["norm1"]["b"],
                               cfg.num_groups))
    h = _conv(h, p["conv1"]["w"], p["conv1"]["b"])
    h = h + _dense(p["temb"], jax.nn.silu(temb))[:, None, None, :]
    h = jax.nn.silu(group_norm(h, p["norm2"]["s"], p["norm2"]["b"],
                               cfg.num_groups))
    h = _conv(h, p["conv2"]["w"], p["conv2"]["b"])
    skip = _conv(x, p["skip"]["w"], p["skip"]["b"]) if "skip" in p else x
    return h + skip


def _init_attn(rng, c, context_dim, heads):
    ks = jax.random.split(rng, 5)
    return {"norm": {"s": jnp.ones((c,)), "b": jnp.zeros((c,))},
            "q": _init_dense(ks[0], c, c),
            "k": _init_dense(ks[1], context_dim, c),
            "v": _init_dense(ks[2], context_dim, c),
            "o": _init_dense(ks[3], c, c, scale=1e-5)}


def _cross_attn(cfg, p, x, context):
    """Spatial tokens attend to the conditioning sequence (self-attention
    when ``context`` is the flattened feature map itself)."""
    from ..ops.attention import attention_xla

    B, H, W, C = x.shape
    hd = C // cfg.num_heads
    h = group_norm(x, p["norm"]["s"], p["norm"]["b"], cfg.num_groups)
    q = _dense(p["q"], h.reshape(B, H * W, C))
    k = _dense(p["k"], context)
    v = _dense(p["v"], context)
    q = q.reshape(B, H * W, cfg.num_heads, hd)
    k = k.reshape(B, -1, cfg.num_heads, hd)
    v = v.reshape(B, -1, cfg.num_heads, hd)
    out = attention_xla(q, k, v, causal=False)
    out = _dense(p["o"], out.reshape(B, H * W, C)).reshape(B, H, W, C)
    return x + out


def _key_stream(rng):
    i = 0
    while True:
        yield jax.random.fold_in(rng, i)
        i += 1


def init_unet(cfg: DiffusionConfig, rng: jax.Array) -> Params:
    temb_dim = cfg.model_channels * 4
    ks = _key_stream(rng)
    chans = [cfg.model_channels * m for m in cfg.channel_mults]
    p: Params = {
        "temb1": _init_dense(next(ks), cfg.model_channels, temb_dim),
        "temb2": _init_dense(next(ks), temb_dim, temb_dim),
        "conv_in": _init_conv(next(ks), 3, 3, cfg.in_channels, chans[0]),
        "down": [], "up": [],
    }
    cin = chans[0]
    for c in chans:
        blocks = [_init_resblock(next(ks), cin if i == 0 else c, c, temb_dim)
                  for i in range(cfg.num_res_blocks)]
        p["down"].append({"blocks": blocks,
                          "downsample": _init_conv(next(ks), 3, 3, c, c)})
        cin = c
    p["mid"] = {"res1": _init_resblock(next(ks), cin, cin, temb_dim),
                "attn": _init_attn(next(ks), cin, cfg.context_dim,
                                   cfg.num_heads),
                "res2": _init_resblock(next(ks), cin, cin, temb_dim)}
    for c in reversed(chans):
        blocks = [_init_resblock(next(ks), cin + c if i == 0 else c, c,
                                 temb_dim)
                  for i in range(cfg.num_res_blocks)]
        # the upsample conv sees the PREVIOUS level's channel count
        p["up"].append({"blocks": blocks,
                        "upsample": _init_conv(next(ks), 3, 3, cin, cin)})
        cin = c
    p["norm_out"] = {"s": jnp.ones((cin,)), "b": jnp.zeros((cin,))}
    p["conv_out"] = _init_conv(next(ks), 3, 3, cin, cfg.in_channels,
                               scale=1e-5)
    return p


def apply_unet(cfg: DiffusionConfig, p: Params, latents: jnp.ndarray,
               t: jnp.ndarray, context: jnp.ndarray) -> jnp.ndarray:
    """Predict noise ``eps`` for NHWC latents at timesteps ``t`` [B]."""
    temb = timestep_embedding(t, cfg.model_channels)
    temb = _dense(p["temb2"], jax.nn.silu(_dense(p["temb1"], temb)))
    h = _conv(latents, p["conv_in"]["w"], p["conv_in"]["b"])
    skips = []
    for lvl in p["down"]:
        for blk in lvl["blocks"]:
            h = _resblock(cfg, blk, h, temb)
        skips.append(h)
        h = _conv(h, lvl["downsample"]["w"], lvl["downsample"]["b"], stride=2)
    h = _resblock(cfg, p["mid"]["res1"], h, temb)
    h = _cross_attn(cfg, p["mid"]["attn"], h, context)
    h = _resblock(cfg, p["mid"]["res2"], h, temb)
    for lvl in p["up"]:
        B, H, W, C = h.shape
        h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
        h = _conv(h, lvl["upsample"]["w"], lvl["upsample"]["b"])
        h = jnp.concatenate([h, skips.pop()], axis=-1)
        for blk in lvl["blocks"]:
            h = _resblock(cfg, blk, h, temb)
    h = jax.nn.silu(group_norm(h, p["norm_out"]["s"], p["norm_out"]["b"],
                               cfg.num_groups))
    return _conv(h, p["conv_out"]["w"], p["conv_out"]["b"])


# --------------------------------------------------------------------------- #
# VAE decoder (DSVAE.decode analog — latents → image)
# --------------------------------------------------------------------------- #
def init_vae_decoder(cfg: DiffusionConfig, rng: jax.Array) -> Params:
    ks = jax.random.split(rng, 4)
    c = cfg.vae_channels
    return {"conv_in": _init_conv(ks[0], 3, 3, cfg.in_channels, c),
            "norm1": {"s": jnp.ones((c,)), "b": jnp.zeros((c,))},
            "conv_mid": _init_conv(ks[1], 3, 3, c, c),
            "norm2": {"s": jnp.ones((c,)), "b": jnp.zeros((c,))},
            "conv_out": _init_conv(ks[2], 3, 3, c, cfg.image_channels)}


def apply_vae_decoder(cfg: DiffusionConfig, p: Params,
                      latents: jnp.ndarray, upscale: int = 2) -> jnp.ndarray:
    h = _conv(latents, p["conv_in"]["w"], p["conv_in"]["b"])
    h = jax.nn.silu(group_norm(h, p["norm1"]["s"], p["norm1"]["b"],
                               cfg.num_groups))
    for _ in range(int(np.log2(upscale))):
        B, H, W, C = h.shape
        h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
        h = _conv(h, p["conv_mid"]["w"], p["conv_mid"]["b"])
        h = jax.nn.silu(group_norm(h, p["norm2"]["s"], p["norm2"]["b"],
                                   cfg.num_groups))
    return jnp.tanh(_conv(h, p["conv_out"]["w"], p["conv_out"]["b"]))


# --------------------------------------------------------------------------- #
# DDIM sampler
# --------------------------------------------------------------------------- #
def ddim_alphas(num_train_timesteps: int, beta_start: float = 0.00085,
                beta_end: float = 0.012) -> jnp.ndarray:
    """Scaled-linear schedule (SD default): cumulative alpha products."""
    betas = jnp.linspace(beta_start ** 0.5, beta_end ** 0.5,
                         num_train_timesteps) ** 2
    return jnp.cumprod(1.0 - betas)


def ddim_step(x_t: jnp.ndarray, eps: jnp.ndarray, alpha_t: jnp.ndarray,
              alpha_prev: jnp.ndarray) -> jnp.ndarray:
    """Deterministic (eta=0) DDIM update x_t → x_{t_prev}."""
    x0 = (x_t - jnp.sqrt(1 - alpha_t) * eps) / jnp.sqrt(alpha_t)
    return jnp.sqrt(alpha_prev) * x0 + jnp.sqrt(1 - alpha_prev) * eps


# --------------------------------------------------------------------------- #
# the engine: one compiled program per (shape, steps) — the CUDA-graph analog
# --------------------------------------------------------------------------- #
class DiffusionEngine:
    """DSUNet/DSVAE analog: the whole classifier-free-guided DDIM loop +
    VAE decode compiles into ONE XLA program (record once, replay every
    ``generate`` call — with cross-step fusion the CUDA graph can't do)."""

    def __init__(self, cfg: DiffusionConfig, unet_params: Params,
                 vae_params: Optional[Params] = None,
                 compute_dtype=jnp.bfloat16):
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        cast = lambda t: jax.tree.map(  # noqa: E731
            lambda x: x.astype(compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, t)
        self.unet_params = cast(unet_params)
        self.vae_params = cast(vae_params) if vae_params is not None else None
        self.alphas = ddim_alphas(cfg.num_train_timesteps)

        @partial(jax.jit, static_argnames=("steps", "guidance"))
        def _generate(unet_p, vae_p, latents, context, uncond_context, *,
                      steps: int, guidance: float):
            ts = jnp.linspace(cfg.num_train_timesteps - 1, 0, steps) \
                .astype(jnp.int32)
            a = self.alphas[ts]
            a_prev = jnp.concatenate([self.alphas[ts[1:]],
                                      jnp.ones((1,))])

            def body(x, sched):
                t, alpha_t, alpha_p = sched
                B = x.shape[0]
                if guidance != 1.0:
                    # classifier-free guidance: ONE UNet call at 2B (cond
                    # and uncond batched on the leading axis), then split —
                    # keeps the MXU fed instead of two sequential passes
                    both = apply_unet(
                        cfg, unet_p, jnp.concatenate([x, x]),
                        jnp.full((2 * B,), t),
                        jnp.concatenate([context, uncond_context]))
                    eps_c, eps_u = both[:B], both[B:]
                    eps = eps_u + guidance * (eps_c - eps_u)
                else:
                    eps = apply_unet(cfg, unet_p, x, jnp.full((B,), t),
                                     context)
                return ddim_step(x, eps.astype(jnp.float32), alpha_t,
                                 alpha_p).astype(x.dtype), None

            x, _ = lax.scan(body, latents, (ts, a, a_prev))
            if vae_p is not None:
                return apply_vae_decoder(cfg, vae_p, x)
            return x

        self._generate = _generate

    def generate(self, latents: jnp.ndarray, context: jnp.ndarray, *,
                 uncond_context: Optional[jnp.ndarray] = None,
                 steps: int = 20, guidance: float = 1.0) -> jnp.ndarray:
        """latents: [B, H, W, C_latent] noise; context: [B, T, context_dim]
        conditioning. Returns decoded images (or final latents without a
        VAE)."""
        if uncond_context is None:
            uncond_context = jnp.zeros_like(context)
        return self._generate(self.unet_params, self.vae_params,
                              latents.astype(self.compute_dtype),
                              context.astype(self.compute_dtype),
                              uncond_context.astype(self.compute_dtype),
                              steps=steps, guidance=guidance)


def build_diffusion_engine(cfg: DiffusionConfig, rng: jax.Array,
                           with_vae: bool = True,
                           compute_dtype=jnp.bfloat16) -> DiffusionEngine:
    k1, k2 = jax.random.split(rng)
    return DiffusionEngine(cfg, init_unet(cfg, k1),
                           init_vae_decoder(cfg, k2) if with_vae else None,
                           compute_dtype=compute_dtype)
