"""Shared paged-KV attention step — the one copy of the v2 block-table
protocol every family's ``apply_paged`` builds on.

Contract (see ``models/llama.py`` for the layout): the KV pool is
``[num_blocks, kv_heads, block_size, hd]`` per layer (last two dims are the
decode kernel's per-block tile — TPU tiling legal), block tables are
fixed-width ``[b, max_blocks]`` indices into the pool, block 0 is the trash
block that absorbs writes for padded tokens, and ``positions`` are absolute
token positions (``context_lens + arange(t)``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from ..ops.attention import attention


def paged_attention_step(q, k, v, k_cache, v_cache, block_tables,
                         context_lens, positions, valid, *,
                         window=None) -> Tuple:
    """Scatter this step's K/V into the block pool, then attend over it.

    q [b, t, nh, hd]; k/v [b, t, nkv, hd]. ``window``: optional per-layer
    sliding-window length (int or traced scalar — exaone4 scans per-layer
    windows). Single-token decode dispatches the paged flash-decode kernel
    (windowed or plain-causal); multi-token prefill takes the gathered-view
    mask path. Returns (attn_out [b, t, nh, hd], k_cache, v_cache)."""
    b, t = q.shape[0], q.shape[1]
    nkv, hd = k.shape[-2], k.shape[-1]
    bs = k_cache.shape[2]
    max_blocks = block_tables.shape[1]

    blk_idx = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    blk_idx = jnp.where(valid, blk_idx, 0)
    off = positions % bs
    # advanced indices (blk_idx, off) straddle the kv-head slice, so the
    # result dims land in front: [b, t, nkv, hd] — exactly k's layout
    k_cache = k_cache.at[blk_idx, :, off].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[blk_idx, :, off].set(v.astype(v_cache.dtype))

    if t == 1:
        from ..ops import pallas as _pallas_ops  # noqa: F401 (registers)
        from ..ops.registry import get_op

        out = get_op("paged_decode_attention")(
            q[:, 0], k_cache, v_cache, block_tables, context_lens,
            window=window)[:, None]
    else:
        S = max_blocks * bs
        kg = k_cache[block_tables].swapaxes(2, 3).reshape(b, S, nkv, hd)
        vg = v_cache[block_tables].swapaxes(2, 3).reshape(b, S, nkv, hd)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = positions[:, None, :, None]
        mask = kv_pos <= q_abs
        if window is not None:
            mask = mask & (q_abs - kv_pos < window)
        out = attention(q, kg, vg, causal=False, mask=mask)
    return out, k_cache, v_cache
