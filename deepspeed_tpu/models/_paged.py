"""Shared paged-KV attention step — the one copy of the v2 block-table
protocol every family's ``apply_paged`` builds on.

Contract (see ``models/llama.py`` for the layout): the KV pool is
``[num_blocks, kv_heads, block_size, hd]`` per layer (last two dims are the
decode kernel's per-block tile — TPU tiling legal), block tables are
fixed-width ``[b, max_blocks]`` indices into the pool, block 0 is the trash
block that absorbs writes for padded tokens, and ``positions`` are absolute
token positions (``context_lens + arange(t)``).

Quantized KV mode (``inference.kv_quant``, docs/serving.md "Quantized KV
cache"): the cache dict additionally carries ``k_scale``/``v_scale`` pools
``[num_blocks, kv_heads, block_size, ngroups]`` fp32, K/V pools hold int8
codes, and :func:`paged_attention_step` receives each pool as a
``(codes, scales)`` tuple (:func:`split_kv`). Fill-time quantization is
fused into the cache-update scatter (per-token groupwise scales — a token's
write never touches another position's scale), and dequant is fused into
the attention reads: in-register inside the Pallas paged-decode kernel, and
into the gather consumer on the multi-token prefill path. There is NO
standalone int8→bf16 convert pass over the pool — QUANT_TPU_LIVE.json shows
that path losing to bf16 outright.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional, Tuple

import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.quantization import kv_dequantize_int8, kv_quantize_int8

# --------------------------------------------------------------------------- #
# fused speculative verification (inference.speculative.fused_verify;
# docs/serving.md "Fused verification"). Trace-time gate: the engine's
# verify program wraps its apply_paged call in :func:`fused_verify_scope`,
# so ONLY that program's multi-token attention dispatches the
# block-table-walking spec-verify kernel — prefill keeps the gathered-view
# path, and with the gate off every program is byte-identical to before.
# --------------------------------------------------------------------------- #
_FUSED_VERIFY = {"on": False}


def fused_verify_active() -> bool:
    return _FUSED_VERIFY["on"]


@contextmanager
def fused_verify_scope():
    """Arm the fused-verify dispatch for the duration of one trace (the
    flag is consulted at trace time only — compiled programs keep whatever
    path they were traced with)."""
    prev = _FUSED_VERIFY["on"]
    _FUSED_VERIFY["on"] = True
    try:
        yield
    finally:
        _FUSED_VERIFY["on"] = prev


def init_paged_pools(num_layers: int, num_blocks: int, num_kv_heads: int,
                     block_size: int, head_size: int, dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None):
    """The one cache-pool constructor every family's ``init_paged_cache``
    delegates to. Plain mode returns the historical ``{"k", "v"}`` dict;
    with ``kv_quant_group`` set (``inference.kv_quant.group_size``, clamped
    to ``head_size``) the pools hold int8 codes plus fp32
    ``[L, num_blocks, nkv, bs, ngroups]`` scale pools beside them — the
    per-block scale table that every block-lifecycle op (COW copy, fork,
    spill, truncate) carries automatically because it is part of the cache
    pytree. Scales init to ZERO so unwritten positions and the trash block
    dequantize to exactly the bf16 pool's zeros."""
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_size)
    if kv_quant_group is None:
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    gs = min(int(kv_quant_group), head_size)
    if gs < 1 or head_size % gs:
        raise ValueError(
            f"kv_quant.group_size {kv_quant_group} does not divide "
            f"head_size {head_size}")
    sshape = shape[:-1] + (head_size // gs,)
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def split_kv(cache):
    """The per-family adapter from the cache dict to
    :func:`paged_attention_step`'s K/V entries: plain pools stay arrays;
    quantized pools (``k_scale`` present) become ``(codes, scales)`` tuples
    so ``lax.scan`` threads codes AND scales per layer with no per-family
    plumbing. Returns ``(k_entry, v_entry)``."""
    if "k_scale" in cache:
        return ((cache["k"], cache["k_scale"]),
                (cache["v"], cache["v_scale"]))
    return cache["k"], cache["v"]


def join_kv(k_entry, v_entry):
    """Inverse of :func:`split_kv`: rebuild the cache dict from the scan's
    stacked per-layer outputs."""
    if isinstance(k_entry, tuple):
        return {"k": k_entry[0], "k_scale": k_entry[1],
                "v": v_entry[0], "v_scale": v_entry[1]}
    return {"k": k_entry, "v": v_entry}


def _gathered_view(pool, block_tables):
    """Dense [b, S, nkv, *] view of the pool rows the tables reference —
    the multi-token (prefill) read path's gather."""
    b, max_blocks = block_tables.shape
    g = pool[block_tables]                     # [b, mb, nkv, bs, *]
    g = g.swapaxes(2, 3)                       # [b, mb, bs, nkv, *]
    return g.reshape((b, max_blocks * g.shape[2]) + g.shape[3:])


def paged_attention_step(q, k, v, k_cache, v_cache, block_tables,
                         context_lens, positions, valid, *,
                         window=None) -> Tuple:
    """Scatter this step's K/V into the block pool, then attend over it.

    q [b, t, nh, hd]; k/v [b, t, nkv, hd]. ``k_cache``/``v_cache`` are
    either plain pools or ``(codes, scales)`` tuples (:func:`split_kv` —
    quantized KV mode). ``window``: optional per-layer sliding-window length
    (int or traced scalar — exaone4 scans per-layer windows). Single-token
    decode dispatches the paged flash-decode kernel (windowed, plain-causal,
    or the fused-dequant quantized variant); multi-token prefill takes the
    gathered-view mask path (dequant fusing into the gather consumer).
    Returns (attn_out [b, t, nh, hd], k_cache, v_cache) with the cache
    entries in the same representation they arrived in."""
    b, t = q.shape[0], q.shape[1]
    nkv, hd = k.shape[-2], k.shape[-1]
    quant = isinstance(k_cache, tuple)
    if quant:
        k_codes, k_scales = k_cache
        v_codes, v_scales = v_cache
        bs = k_codes.shape[2]
        group_size = hd // k_scales.shape[-1]
    else:
        bs = k_cache.shape[2]
    max_blocks = block_tables.shape[1]

    blk_idx = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    blk_idx = jnp.where(valid, blk_idx, 0)
    off = positions % bs
    # advanced indices (blk_idx, off) straddle the kv-head slice, so the
    # result dims land in front: [b, t, nkv, hd] — exactly k's layout
    if quant:
        # fill-time quantization fused into the cache-update: codes and the
        # per-(token, head, group) scales scatter in the same program
        qk, sk = kv_quantize_int8(k, group_size)
        qv, sv = kv_quantize_int8(v, group_size)
        k_codes = k_codes.at[blk_idx, :, off].set(qk)
        v_codes = v_codes.at[blk_idx, :, off].set(qv)
        k_scales = k_scales.at[blk_idx, :, off].set(sk)
        v_scales = v_scales.at[blk_idx, :, off].set(sv)
    else:
        k_cache = k_cache.at[blk_idx, :, off].set(k.astype(k_cache.dtype))
        v_cache = v_cache.at[blk_idx, :, off].set(v.astype(v_cache.dtype))

    if t == 1:
        from ..ops import pallas as _pallas_ops  # noqa: F401 (registers)
        from ..ops.registry import get_op

        if quant:
            out = get_op("paged_decode_attention")(
                q[:, 0], k_codes, v_codes, block_tables, context_lens,
                window=window, k_scale=k_scales, v_scale=v_scales)[:, None]
        else:
            out = get_op("paged_decode_attention")(
                q[:, 0], k_cache, v_cache, block_tables, context_lens,
                window=window)[:, None]
    elif fused_verify_active():
        # speculative verification rides the paged-decode kernel family:
        # t = 1 + max_draft_tokens rows per sequence score against the
        # block-table-indexed pools (dequant-in-register in quant mode) —
        # never the dense [B, max_blocks*bs, ...] gather below
        from ..ops import pallas as _pallas_ops  # noqa: F401 (registers)
        from ..ops.registry import get_op

        if quant:
            out = get_op("paged_spec_verify_attention")(
                q, k_codes, v_codes, block_tables, context_lens,
                window=window, k_scale=k_scales, v_scale=v_scales)
        else:
            out = get_op("paged_spec_verify_attention")(
                q, k_cache, v_cache, block_tables, context_lens,
                window=window)
    else:
        if quant:
            # dequant fuses into the gather consumer — the gathered view is
            # materialized either way, so the convert rides the same pass
            kg = kv_dequantize_int8(_gathered_view(k_codes, block_tables),
                                    _gathered_view(k_scales, block_tables),
                                    q.dtype)
            vg = kv_dequantize_int8(_gathered_view(v_codes, block_tables),
                                    _gathered_view(v_scales, block_tables),
                                    q.dtype)
        else:
            kg = _gathered_view(k_cache, block_tables)
            vg = _gathered_view(v_cache, block_tables)
        S = max_blocks * bs
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = positions[:, None, :, None]
        mask = kv_pos <= q_abs
        if window is not None:
            mask = mask & (q_abs - kv_pos < window)
        out = attention(q, kg, vg, causal=False, mask=mask)
    if quant:
        return out, (k_codes, k_scales), (v_codes, v_scales)
    return out, k_cache, v_cache
