"""Mixtral-family model: Llama backbone with MoE FFN (expert parallel).

Reference parity: the reference serves mixtral via
``inference/v2/model_implementations/mixtral`` and trains MoE via
``deepspeed/moe`` — this is the training+inference model family for MoE here.
Stacked-layer ``lax.scan`` like ``models/llama.py``; each block's FFN is the
expert bank with top-k routing; the load-balancing aux loss accumulates
through the scan and is added to the LM loss.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..moe.layer import MoELayer, init_moe_ffn, moe_ffn_logical_axes
from ..ops.attention import attention
from ._paged import join_kv, paged_attention_step, split_kv
from ._paged import init_paged_pools as _init_paged_pools
from ..ops.embedding import embedding_lookup
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rotary, rope_frequencies
from . import llama as llama_mod

Params = Dict[str, Any]

# checkpoint names this family's TRAINING block attaches (the selective-
# remat saveables; the MoE expert matmuls stay unnamed — their dispatch
# layout is the compact/einsum implementation's concern)
CHECKPOINT_NAMES_EMITTED = ("qkv_proj", "attn_mix", "attn_out", "mlp_out")


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_coef: float = 0.01
    max_seq_len: int = 4096
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    remat: bool = False
    remat_policy: str = "none"  # none | full | dots | any registry policy
    # Qwen2-MoE extensions (reference .../qwen_v2_moe): QKV biases, raw
    # (unnormalized) top-k gates, and a sigmoid-gated shared dense expert
    attention_bias: bool = False
    norm_topk_prob: bool = True
    shared_expert_intermediate_size: int = 0
    # MoE dispatch implementation: 'einsum' (dense one-hot, MXU) or
    # 'compact' (index-table gather/scatter) — see moe/layer.py
    moe_dispatch: str = "einsum"

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, num_experts=4,
                    top_k=2, max_seq_len=128, rope_theta=10000.0)
        base.update(kw)
        return cls(**base)


def init(cfg: MixtralConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, nkv, v = cfg.num_layers, cfg.num_heads, cfg.num_kv_heads, cfg.vocab_size
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5).astype(dtype)

    def one_moe(k):
        p = init_moe_ffn(k, cfg.num_experts, h, cfg.intermediate_size, dtype)
        si = cfg.shared_expert_intermediate_size
        if si:
            ks = jax.random.split(jax.random.fold_in(k, 7), 4)
            scale_h = jnp.float32(h) ** -0.5
            p["shared_w_gate"] = (jax.random.normal(ks[0], (h, si)) * scale_h).astype(dtype)
            p["shared_w_up"] = (jax.random.normal(ks[1], (h, si)) * scale_h).astype(dtype)
            p["shared_w_down"] = (jax.random.normal(ks[2], (si, h)) *
                                  jnp.float32(si) ** -0.5).astype(dtype)
            p["shared_gate"] = (jax.random.normal(ks[3], (h, 1)) * scale_h).astype(dtype)
        return p

    moe = jax.vmap(one_moe)(jax.random.split(keys[5], L))
    out = {
        "embed": normal(keys[0], (v, h), h),
        "layers": {
            "attn_norm": jnp.ones((L, h), dtype),
            "wq": normal(keys[1], (L, h, nh * hd), h),
            "wk": normal(keys[2], (L, h, nkv * hd), h),
            "wv": normal(keys[3], (L, h, nkv * hd), h),
            "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
            "mlp_norm": jnp.ones((L, h), dtype),
            "moe": moe,   # leaves: [L, E, ...] / router [L, H, E]
        },
        "final_norm": jnp.ones((h,), dtype),
        "lm_head": normal(keys[6], (h, v), h),
    }
    if cfg.attention_bias:
        out["layers"]["bq"] = jnp.zeros((L, nh * hd), dtype)
        out["layers"]["bk"] = jnp.zeros((L, nkv * hd), dtype)
        out["layers"]["bv"] = jnp.zeros((L, nkv * hd), dtype)
    return out


def param_logical_axes(cfg: MixtralConfig) -> Params:
    moe_axes = {k: ("layers",) + tuple(v) for k, v in moe_ffn_logical_axes().items()}
    if cfg.shared_expert_intermediate_size:
        moe_axes.update({"shared_w_gate": ("layers", "embed", "mlp"),
                         "shared_w_up": ("layers", "embed", "mlp"),
                         "shared_w_down": ("layers", "mlp", "embed"),
                         "shared_gate": ("layers", "embed", None)})
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
            "moe": moe_axes,
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }
    if cfg.attention_bias:
        axes["layers"]["bq"] = ("layers", "heads")
        axes["layers"]["bk"] = ("layers", "kv_heads")
        axes["layers"]["bv"] = ("layers", "kv_heads")
    return axes


def _head_split(cfg, params, x, compute_dtype):
    """Final norm + unembed matrix minus the logits matmul — consumed by
    the tiled fused logits+loss head (``tiled_loss_fn``)."""
    x = rms_norm(x, params["final_norm"].astype(compute_dtype),
                 cfg.rms_norm_eps)
    return x, params["lm_head"].astype(compute_dtype)


def _head(cfg, params, x, compute_dtype):
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def apply(cfg: MixtralConfig, params: Params, tokens: jnp.ndarray, *,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    """Forward → (logits [b, s, vocab] fp32, total_aux_loss); with
    ``return_hidden`` → (normed hidden, unembed matrix, total_aux_loss)."""
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    moe_layer = MoELayer(cfg.num_experts, cfg.top_k, cfg.capacity_factor,
                         cfg.min_capacity, cfg.drop_tokens,
                         norm_topk=cfg.norm_topk_prob,
                         dispatch=cfg.moe_dispatch)

    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    def block(x, layer):
        b, s, h = x.shape
        nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
        y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = y @ layer["wq"], y @ layer["wk"], y @ layer["wv"]
        if "bq" in layer:
            q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
        # selective-remat saveables (identity outside a targeting policy);
        # see POLICY_SAVED_NAMES in activation_checkpointing/checkpointing
        q = checkpoint_name(q, "qkv_proj")
        k = checkpoint_name(k, "qkv_proj")
        v = checkpoint_name(v, "qkv_proj")
        q = apply_rotary(q.reshape(b, s, nh, hd), cos, sin)
        k = apply_rotary(k.reshape(b, s, nkv, hd), cos, sin)
        v = v.reshape(b, s, nkv, hd)
        # K/V pass NARROW (nkv heads) into the attention op: widening —
        # when the gqa_native kernels are off — happens inside the op,
        # never here (the gqa-native lint traces this apply)
        x = x + checkpoint_name(
            checkpoint_name(attention(q, k, v, causal=True), "attn_mix")
            .reshape(b, s, nh * hd) @ layer["wo"], "attn_out")
        y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        ffn_out, aux = moe_layer(layer["moe"], y)
        return x + checkpoint_name(ffn_out, "mlp_out"), aux

    if cfg.remat:
        # shared remat-policy registry (same name map as models/llama.py)
        from ..runtime.activation_checkpointing import checkpointing as ac

        name = {"none": "full", "full": "full",
                "dots": "dots_saveable"}.get(cfg.remat_policy,
                                             cfg.remat_policy)
        block = jax.checkpoint(block, policy=ac.get_policy(name))

    from ..comm import overlap as ov

    def scan_body(x, layer):
        x, aux = block(x, ov.constrain_scan_slice(layer))
        return x, aux

    if ov.layer_prefetch_active():
        x, aux_losses = ov.prefetch_scan(scan_body, x, layers)
    else:
        x, aux_losses = lax.scan(scan_body, x, layers)
    if return_hidden:
        hidden, head = _head_split(cfg, params, x, compute_dtype)
        return hidden, head, jnp.sum(aux_losses)
    return _head(cfg, params, x, compute_dtype), jnp.sum(aux_losses)


# --- KV-cached inference path (MoE decode; reference
# ``inference/v2/model_implementations/mixtral``) ------------------------- #
def init_cache(cfg: MixtralConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads,
             cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: MixtralConfig) -> Params:
    spec = ("layers", None, None, "kv_heads", None)
    return {"k": spec, "v": spec}


def apply_cached(cfg: MixtralConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Prefill/decode with KV cache; MoE routing runs per new token (aux loss
    is discarded at inference)."""
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    b, t = tokens.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(t)[None, :]
    # inference never drops tokens: a dropped decode token would silently
    # corrupt the completion (reference v2 mixtral routes without capacity)
    moe_layer = MoELayer(cfg.num_experts, cfg.top_k, cfg.capacity_factor,
                         cfg.min_capacity, drop_tokens=False,
                         norm_topk=cfg.norm_topk_prob,
                         dispatch=cfg.moe_dispatch)
    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = y @ layer["wq"], y @ layer["wk"], y @ layer["wv"]
        if "bq" in layer:
            q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
        q = apply_rotary(q.reshape(b, t, nh, hd), cos, sin, positions)
        k = apply_rotary(k.reshape(b, t, nkv, hd), cos, sin, positions)
        v = v.reshape(b, t, nkv, hd)
        k_c = llama_mod._write_cache(k_c, k, cache_len)
        v_c = llama_mod._write_cache(v_c, v, cache_len)
        S = k_c.shape[1]
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = positions[:, None, :, None]
        attn = attention(q, k_c, v_c, causal=False, mask=kv_pos <= q_abs)
        x = x + attn.reshape(b, t, nh * hd) @ layer["wo"]
        y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        ffn_out, _aux = moe_layer(layer["moe"], y)
        return x + ffn_out, (k_c, v_c)

    x, (nk, nv) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"].astype(compute_dtype), cfg.rms_norm_eps)
    logits = x @ params["lm_head"].astype(compute_dtype)
    return logits.astype(jnp.float32), {"k": nk, "v": nv}


def loss_fn(cfg: MixtralConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits, aux = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    logp = jax.nn.log_softmax(logits, axis=-1)
    lm_loss = -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))
    loss = lm_loss + cfg.aux_loss_coef * aux
    return loss, {"loss": loss, "lm_loss": lm_loss, "aux_loss": aux}


def tiled_loss_fn(cfg: MixtralConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``).
    The MoE aux loss is added exactly as in ``loss_fn``."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head, aux = apply(cfg, params, inputs,
                              compute_dtype=compute_dtype,
                              return_hidden=True)
    lm_loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    loss = lm_loss + cfg.aux_loss_coef * aux
    return loss, {"loss": loss, "lm_loss": lm_loss, "aux_loss": aux}


def model_spec(cfg: MixtralConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="mixtral",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(cfg, params, tokens,
                                                    compute_dtype=compute_dtype)[0],
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,   # MoE model runs plain scan (no pipeline path yet)
    )


# --------------------------------------------------------------------------- #
# Paged (blocked) KV-cache path — the v2 continuous-batching protocol
# (reference serves Mixtral through inference/v2; block-table layout as in
# models/llama.py: fixed-width tables, block 0 is the trash block)
# --------------------------------------------------------------------------- #
def init_paged_cache(cfg: MixtralConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None) -> Params:
    return _init_paged_pools(cfg.num_layers, num_blocks, cfg.num_kv_heads,
                             block_size, cfg.head_size, dtype,
                             kv_quant_group)


def apply_paged(cfg: MixtralConfig, params: Params, tokens: jnp.ndarray,
                cache: Params, block_tables: jnp.ndarray,
                context_lens: jnp.ndarray, *,
                valid: Optional[jnp.ndarray] = None,
                compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Ragged forward over the paged cache (see llama.apply_paged for the
    contract); the FFN is the no-drop MoE routing of apply_cached."""
    b, t = tokens.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    if valid is None:
        valid = jnp.ones((b, t), bool)
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = context_lens[:, None] + jnp.arange(t)[None, :]
    moe_layer = MoELayer(cfg.num_experts, cfg.top_k, cfg.capacity_factor,
                         cfg.min_capacity, drop_tokens=False,
                         norm_topk=cfg.norm_topk_prob,
                         dispatch=cfg.moe_dispatch)
    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = y @ layer["wq"], y @ layer["wk"], y @ layer["wv"]
        if "bq" in layer:
            q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
        q = apply_rotary(q.reshape(b, t, nh, hd), cos, sin, positions)
        k = apply_rotary(k.reshape(b, t, nkv, hd), cos, sin, positions)
        v = v.reshape(b, t, nkv, hd)
        attn, k_c, v_c = paged_attention_step(
            q, k, v, k_c, v_c, block_tables, context_lens, positions, valid)
        x = x + attn.reshape(b, t, nh * hd) @ layer["wo"]
        y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        ffn_out, _aux = moe_layer(layer["moe"], y)
        return x + ffn_out, (k_c, v_c)

    x, (nk, nv) = lax.scan(scan_body, x, (layers,) + split_kv(cache))
    x = rms_norm(x, params["final_norm"].astype(compute_dtype),
                 cfg.rms_norm_eps)
    logits = x @ params["lm_head"].astype(compute_dtype)
    return logits.astype(jnp.float32), join_kv(nk, nv)
