"""BLOOM family, written TPU-first.

Reference parity: v1 injection policy ``module_inject/containers/bloom.py``
(+ ``model_implementations/ds_bloom.py``). BLOOM deltas vs the GPT/Llama
families, all handled here:

- **ALiBi** position encoding: a per-head additive logits slope instead of
  rotary. Softmax rows are shift-invariant, so ``slope · key_pos`` is
  equivalent to ``slope · (key_pos − query_pos)`` under the causal mask —
  that one-sided form works unchanged for the KV-cached decode path.
- A LayerNorm over the embedding output (``word_embeddings_layernorm``).
- Sequential (non-parallel) blocks, LayerNorm with bias, biases on every
  linear, tied lm_head.

Same TPU shape as the sibling models: stacked layers under ``lax.scan``,
logical axis names per param for the sharding-rule engine. The fused HF
``query_key_value`` projection ships head-interleaved [q|k|v]; the importer
(``models/hf_import.py``) de-interleaves into separate wq/wk/wv so the TP
rules shard heads cleanly.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 4096
    num_layers: int = 30
    num_heads: int = 32
    max_seq_len: int = 2048
    layer_norm_eps: float = 1e-5

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def tiny(cls, **kw) -> "BloomConfig":
        base = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=128)
        base.update(kw)
        return cls(**base)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (HF ``build_alibi_tensor`` formula: geometric
    series from the closest power of two, odd-step fill for the remainder)."""
    closest = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** i for i in range(1, closest + 1)]
    if closest != num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        n_rem = min(closest, num_heads - closest)
        slopes += [extra_base ** i for i in range(1, 2 * n_rem, 2)]
    return jnp.asarray(slopes, jnp.float32)


def _alibi_bias(num_heads: int, kv_len: int) -> jnp.ndarray:
    """[heads, 1, kv_len] additive logits bias (one-sided form)."""
    slopes = alibi_slopes(num_heads)
    return (slopes[:, None, None] *
            jnp.arange(kv_len, dtype=jnp.float32)[None, None, :])


def init(cfg: BloomConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, v, i = cfg.num_layers, cfg.num_heads, cfg.vocab_size, cfg.intermediate_size
    keys = jax.random.split(rng, 7)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5).astype(dtype)

    return {
        "embed": normal(keys[0], (v, h), h),
        "embed_ln_scale": jnp.ones((h,), dtype),
        "embed_ln_bias": jnp.zeros((h,), dtype),
        "layers": {
            "ln1_scale": jnp.ones((L, h), dtype),
            "ln1_bias": jnp.zeros((L, h), dtype),
            "wq": normal(keys[1], (L, h, nh * hd), h),
            "wk": normal(keys[2], (L, h, nh * hd), h),
            "wv": normal(keys[3], (L, h, nh * hd), h),
            "bq": jnp.zeros((L, nh * hd), dtype),
            "bk": jnp.zeros((L, nh * hd), dtype),
            "bv": jnp.zeros((L, nh * hd), dtype),
            "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
            "bo": jnp.zeros((L, h), dtype),
            "ln2_scale": jnp.ones((L, h), dtype),
            "ln2_bias": jnp.zeros((L, h), dtype),
            "w_up": normal(keys[5], (L, h, i), h),
            "b_up": jnp.zeros((L, i), dtype),
            "w_down": normal(keys[6], (L, i, h), i),
            "b_down": jnp.zeros((L, h), dtype),
        },
        "final_ln_scale": jnp.ones((h,), dtype),
        "final_ln_bias": jnp.zeros((h,), dtype),
    }


def param_logical_axes(cfg: BloomConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "embed_ln_scale": ("embed",),
        "embed_ln_bias": ("embed",),
        "layers": {
            "ln1_scale": ("layers", "embed"),
            "ln1_bias": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "heads"),
            "wv": ("layers", "embed", "heads"),
            "bq": ("layers", "heads"),
            "bk": ("layers", "heads"),
            "bv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"),
            "bo": ("layers", "embed"),
            "ln2_scale": ("layers", "embed"),
            "ln2_bias": ("layers", "embed"),
            "w_up": ("layers", "embed", "mlp"),
            "b_up": ("layers", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "b_down": ("layers", "embed"),
        },
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }


def _block(cfg: BloomConfig, x: jnp.ndarray, layer: Params,
           bias: jnp.ndarray, mask=None) -> jnp.ndarray:
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    y = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"],
                   cfg.layer_norm_eps)
    q = (y @ layer["wq"] + layer["bq"]).reshape(b, s, nh, hd)
    k = (y @ layer["wk"] + layer["bk"]).reshape(b, s, nh, hd)
    v = (y @ layer["wv"] + layer["bv"]).reshape(b, s, nh, hd)
    attn_out = attention(q, k, v, causal=mask is None, bias=bias, mask=mask)
    x = x + attn_out.reshape(b, s, nh * hd) @ layer["wo"] + layer["bo"]

    y = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"],
                   cfg.layer_norm_eps)
    u = jax.nn.gelu(y @ layer["w_up"] + layer["b_up"], approximate=True)
    return x + u @ layer["w_down"] + layer["b_down"]


def _embed(cfg: BloomConfig, params: Params, tokens, compute_dtype):
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    return layer_norm(x, params["embed_ln_scale"].astype(compute_dtype),
                      params["embed_ln_bias"].astype(compute_dtype),
                      cfg.layer_norm_eps)


def _head_split(cfg: BloomConfig, params: Params, x: jnp.ndarray,
                compute_dtype):
    """Final norm + unembed matrix minus the logits matmul — consumed by
    the tiled fused logits+loss head (``tiled_loss_fn``)."""
    x = layer_norm(x, params["final_ln_scale"].astype(compute_dtype),
                   params["final_ln_bias"].astype(compute_dtype),
                   cfg.layer_norm_eps)
    return x, params["embed"].T.astype(compute_dtype)


def _head(cfg: BloomConfig, params: Params, x: jnp.ndarray,
          compute_dtype) -> jnp.ndarray:
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def _cast_layers(params: Params, compute_dtype):
    return jax.tree.map(lambda p: p.astype(compute_dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p,
                        params["layers"])


def apply(cfg: BloomConfig, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    del positions  # ALiBi: position information lives in the logits bias
    x = _embed(cfg, params, tokens, compute_dtype)
    bias = _alibi_bias(cfg.num_heads, tokens.shape[1])
    layers = _cast_layers(params, compute_dtype)

    from ..comm import overlap as ov

    def scan_body(x, layer):
        return _block(cfg, x, ov.constrain_scan_slice(layer), bias), None

    x, _ = lax.scan(scan_body, x, layers)
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# ---- KV-cached decode (v1-engine path) ---- #
def init_cache(cfg: BloomConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    L, nh, hd = cfg.num_layers, cfg.num_heads, cfg.head_size
    shape = (L, batch_size, max_len, nh, hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: BloomConfig) -> Params:
    spec = ("layers", None, None, "heads", None)
    return {"k": spec, "v": spec}


def _write_cache(cache, new, starts):
    def one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

    return jax.vmap(one)(cache, new, starts)


def apply_cached(cfg: BloomConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    b, t = tokens.shape
    nh, hd = cfg.num_heads, cfg.head_size
    x = _embed(cfg, params, tokens, compute_dtype)
    layers = _cast_layers(params, compute_dtype)

    S = cache["k"].shape[2]
    bias = _alibi_bias(nh, S)  # layer-invariant: hoisted out of the scan

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        y = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"],
                       cfg.layer_norm_eps)
        q = (y @ layer["wq"] + layer["bq"]).reshape(b, t, nh, hd)
        k = (y @ layer["wk"] + layer["bk"]).reshape(b, t, nh, hd)
        v = (y @ layer["wv"] + layer["bv"]).reshape(b, t, nh, hd)
        k_c = _write_cache(k_c, k, cache_len)
        v_c = _write_cache(v_c, v, cache_len)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = cache_len[:, None, None, None] + jnp.arange(t)[None, None, :, None]
        mask = kv_pos <= q_abs
        attn_out = attention(q, k_c, v_c, causal=False, bias=bias, mask=mask)
        x = x + attn_out.reshape(b, t, nh * hd) @ layer["wo"] + layer["bo"]
        y = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"],
                       cfg.layer_norm_eps)
        u = jax.nn.gelu(y @ layer["w_up"] + layer["b_up"], approximate=True)
        x = x + u @ layer["w_down"] + layer["b_down"]
        return x, (k_c, v_c)

    x, (new_k, new_v) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    return _head(cfg, params, x, compute_dtype), {"k": new_k, "v": new_v}


def loss_fn(cfg: BloomConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tl = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(valid, tl, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return loss, {"loss": loss, "ntokens": valid.sum()}


def tiled_loss_fn(cfg: BloomConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``)."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head = apply(cfg, params, inputs, compute_dtype=compute_dtype,
                         return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    return loss, {"loss": loss, "ntokens": (labels != -100).sum()}


def model_spec(cfg: BloomConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="bloom",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(
            cfg, params, tokens, compute_dtype=compute_dtype, **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )
