"""Llama-family model, written TPU-first.

Role in the framework: the flagship training/inference model family (the
reference ships llama support via ``module_inject/containers/llama*.py`` and
``inference/v2/model_implementations/llama_v2``; training-side the reference
wraps the HF implementation). Here the model is a *pure function over a param
pytree*:

- layers are **stacked** (leading ``L`` dim) and executed with ``lax.scan`` —
  one trace/compile of a single block regardless of depth, the idiomatic XLA
  form (and the unit pipeline parallelism later splits);
- every param carries **logical axis names** (t5x-style), so tensor/ZeRO/expert
  sharding are rule lookups, not per-model surgery — this is the TPU-native
  replacement for AutoTP's module-graph parsing (``module_inject/auto_tp.py``);
- attention/norm/rotary go through the op registry (Pallas kernel or XLA
  fallback).

Supports GQA, RoPE, SwiGLU, RMSNorm, optional tied embeddings — i.e. Llama 2/3,
Mistral, Qwen dense configs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import attention
from ._paged import join_kv, paged_attention_step, split_kv
from ._paged import init_paged_pools as _init_paged_pools
from ..ops.embedding import embedding_lookup
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rotary, rope_frequencies

Params = Dict[str, Any]

# checkpoint names this family's TRAINING block attaches (the selective-
# remat saveables) — the tier-1 lint test verifies each appears in the
# traced jaxpr, so a refactor can't silently drop one
CHECKPOINT_NAMES_EMITTED = ("qkv_proj", "attn_mix", "attn_out",
                            "mlp_gate", "mlp_up", "mlp_out")


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    attention_bias: bool = False  # QKV biases (Qwen2; HF attention_bias flag)
    qk_norm: bool = False         # per-head RMSNorm on q/k pre-rotary (Qwen3)
    remat: bool = False          # jax.checkpoint each block
    remat_policy: str = "none"   # none | full | dots
    attention_impl: str = "auto"  # auto | xla | ulysses | ring | fpdt | ulysses_fpdt
    fpdt_chunks: int = 4         # query/KV chunk count for the fpdt impls
    fpdt_offload_kv: bool = False  # park K/V in host memory between chunks
    use_pipeline: bool = True    # use the pipe mesh axis when present

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        h, i, v, L = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        hd = self.head_size
        attn = h * self.num_heads * hd + 2 * h * self.num_kv_heads * hd + self.num_heads * hd * h
        mlp = 3 * h * i
        norms = 2 * h
        embed = v * h * (1 if self.tie_embeddings else 2)
        return L * (attn + mlp + norms) + embed + h

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
                    rope_theta=10000.0)
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                   num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192)

    @classmethod
    def mistral_7b(cls) -> "LlamaConfig":
        """Mistral-7B dense config (the reference serves mistral via
        ``inference/v2/model_implementations/mistral``)."""
        return cls(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                   num_layers=32, num_heads=32, num_kv_heads=8,
                   max_seq_len=8192, rope_theta=10000.0)

    @classmethod
    def qwen2_7b(cls) -> "LlamaConfig":
        """Qwen2-7B dense config (reference ``.../qwen_v2``)."""
        return cls(vocab_size=152064, hidden_size=3584, intermediate_size=18944,
                   num_layers=28, num_heads=28, num_kv_heads=4,
                   max_seq_len=32768, rope_theta=1000000.0)

    @classmethod
    def phi3_mini(cls) -> "LlamaConfig":
        """Phi-3-mini dense config (reference ``.../phi3``)."""
        return cls(vocab_size=32064, hidden_size=3072, intermediate_size=8192,
                   num_layers=32, num_heads=32, num_kv_heads=32,
                   max_seq_len=4096, rope_theta=10000.0)


def init(cfg: LlamaConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    """Initialize the stacked param pytree."""
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, nkv, i, v = (cfg.num_layers, cfg.num_heads, cfg.num_kv_heads,
                        cfg.intermediate_size, cfg.vocab_size)
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    params: Params = {
        "embed": normal(keys[0], (v, h), h),
        "layers": {
            "attn_norm": jnp.ones((L, h), dtype),
            "wq": normal(keys[1], (L, h, nh * hd), h),
            "wk": normal(keys[2], (L, h, nkv * hd), h),
            "wv": normal(keys[3], (L, h, nkv * hd), h),
            "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
            "mlp_norm": jnp.ones((L, h), dtype),
            "w_gate": normal(keys[5], (L, h, i), h),
            "w_up": normal(keys[6], (L, h, i), h),
            "w_down": normal(keys[7], (L, i, h), i),
        },
        "final_norm": jnp.ones((h,), dtype),
    }
    if cfg.attention_bias:
        params["layers"]["bq"] = jnp.zeros((L, nh * hd), dtype)
        params["layers"]["bk"] = jnp.zeros((L, nkv * hd), dtype)
        params["layers"]["bv"] = jnp.zeros((L, nkv * hd), dtype)
    if cfg.qk_norm:
        params["layers"]["q_norm"] = jnp.ones((L, hd), dtype)
        params["layers"]["k_norm"] = jnp.ones((L, hd), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(jax.random.fold_in(rng, 99), (h, v), h)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Logical axis names per param — consumed by the partitioner
    (``runtime/partitioning.py``) to derive mesh shardings. ``None`` marks an
    unsharded dim."""
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if cfg.attention_bias:
        axes["layers"]["bq"] = ("layers", "heads")
        axes["layers"]["bk"] = ("layers", "kv_heads")
        axes["layers"]["bv"] = ("layers", "kv_heads")
    if cfg.qk_norm:
        axes["layers"]["q_norm"] = ("layers", None)
        axes["layers"]["k_norm"] = ("layers", None)
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _resolve_attention(cfg: LlamaConfig, in_pipeline: bool = False):
    """Pick the attention path: explicit config wins; 'auto' uses Ulysses when
    the mesh has a seq axis. Ring/Ulysses cannot nest inside the pipeline's
    manual 'pipe' region (nested shard_map / sharding constraints over other
    axes), so that combination is rejected explicitly."""
    impl = cfg.attention_impl
    if in_pipeline and impl in ("ring", "ulysses", "ulysses_fpdt"):
        raise ValueError(
            f"attention_impl='{impl}' cannot run inside pipeline parallelism; "
            "use attention_impl='auto'/'xla' with the pipe axis, or drop the "
            "pipe axis to use sequence parallelism")
    if impl == "ring":
        from ..sequence.ring import ring_attention_spmd

        return ring_attention_spmd
    if impl in ("fpdt", "ulysses_fpdt"):
        # the reference's FPDT composition (fpdt_layer.py:972): chunked
        # flash attention (optionally KV-host-offloaded) as the LOCAL
        # attention, under the Ulysses a2a when a seq axis is present
        from ..sequence.fpdt import fpdt_attention

        chunked = partial(fpdt_attention, chunks=cfg.fpdt_chunks,
                          offload_kv=cfg.fpdt_offload_kv)

        if impl == "fpdt":
            def chunked_plain(q, k, v, causal=True, **kw):
                return chunked(q, k, v, causal=causal)

            return chunked_plain
        from jax.sharding import PartitionSpec as P

        from ..comm.mesh import BATCH_AXES, get_mesh
        from ..sequence.layer import head_shard_axes, ulysses_attention

        def chunked_inner(q, k, v, causal=True, **kw):
            # post-a2a the head dim is sharded per head_shard_axes (the ONE
            # policy, shared with ulysses' to_heads). Run the chunked
            # attention under shard_map over those axes: heads are
            # independent, so each device runs fpdt locally on its head
            # group — and the Pallas kernels never meet the SPMD partitioner
            # (a pallas_call under plain jit with sharded operands forces an
            # involuntary full remat, b/433785288)
            mm = get_mesh()
            sp, tp = mm.axis_size("seq"), mm.axis_size("tensor")
            n = q.shape[-2]
            axes = head_shard_axes(n, sp=sp, tp=tp)
            group = tp * sp if "tensor" in axes else sp
            if n % group != 0:  # uneven heads: ulysses gathered the sequence
                return chunked(q, k, v, causal=causal)
            nkv = k.shape[-2]
            if nkv % group != 0:
                # GQA-narrow KV can't shard over the head group — widen by
                # the SMALLEST factor that aligns (lcm(nkv, group) — the
                # ONE alignment policy, ops.attention.kv_alignment_heads),
                # keeping the host-offload stream as narrow as possible
                # (fpdt fetches narrow; under attention.gqa_native it runs
                # the native kernel on the aligned-narrow K/V directly).
                from ..ops.attention import (gqa_native_active,
                                             kv_alignment_heads, widen_kv)

                target = kv_alignment_heads(nkv, n, group)
                if target == n and gqa_native_active():
                    # misaligned lcm would force FULL q-width — with the
                    # native kernel that widening is pure waste; gather the
                    # sequence instead and keep K/V narrow
                    return chunked(q, k, v, causal=causal)
                k, v = widen_kv(k, v, target)
            spec = P(BATCH_AXES, None, axes, None)
            from ..comm import comm as dist
            return dist.shard_map(
                lambda ql, kl, vl: chunked(ql, kl, vl, causal=causal),
                mesh=mm.mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False)(q, k, v)

        def ulysses_fpdt(q, k, v, **kw):
            return ulysses_attention(q, k, v, inner=chunked_inner, **kw)

        return ulysses_fpdt
    if impl == "ulysses" or (impl == "auto" and not in_pipeline):
        from ..comm.mesh import get_mesh

        if get_mesh().sp_world_size > 1:
            from ..sequence.layer import ulysses_attention

            return ulysses_attention
    return attention


def _qkv_proj(cfg: LlamaConfig, y: jnp.ndarray, layer: Params):
    """QKV projections with optional biases (Qwen2 — the reference's qwen_v2
    container maps q/k/v biases explicitly)."""
    b, s, _ = y.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    q, k, v = y @ layer["wq"], y @ layer["wk"], y @ layer["wv"]
    if "bq" in layer:
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    # "qkv_proj": the three projection dot results — selective-remat
    # saveables (identity outside a targeting policy)
    q = checkpoint_name(q, "qkv_proj")
    k = checkpoint_name(k, "qkv_proj")
    v = checkpoint_name(v, "qkv_proj")
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nkv, hd)
    if "q_norm" in layer:
        # Qwen3: per-head RMSNorm on q/k before rotary
        q = rms_norm(q, layer["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, layer["k_norm"], cfg.rms_norm_eps)
    return q, k, v.reshape(b, s, nkv, hd)


def _residual_sharding():
    """NamedSharding pinning the [batch, seq, hidden] residual stream to its
    canonical layout — batch over the data axes, seq over ('seq', 'tensor'),
    hidden replicated — or None when no TP/SP axis is active.

    This is the Megatron sequence-parallel pattern (Korthikanti et al. 2022):
    with the residual's seq dim sharded over the TENSOR axis, the TP
    row-parallel projections' partial sums REDUCE-SCATTER into seq shards
    (and the column projections all-gather on entry) instead of all-reducing
    into a tensor-replicated residual. Same wire bytes, but the residual,
    norms, and their activations shrink by tp_size, and SPMD never lands the
    residual hidden-sharded (the involuntary full-rematerialization boundary
    observed in the r1 8-device dryrun).  Without the pin, propagation from
    the next layer's ZeRO-sharded weights can reshard the residual
    mid-stream."""
    try:
        from ..comm.mesh import BATCH_AXES, get_mesh

        mm = get_mesh()
        seq_axes = tuple(
            a for a, on in (("seq", mm.sp_world_size > 1),
                            ("tensor", mm.tp_world_size > 1)) if on)
        if seq_axes:
            return mm.sharding(BATCH_AXES, seq_axes)
    except Exception:
        pass
    return None


def _block(cfg: LlamaConfig, x: jnp.ndarray, layer: Params,
           cos: jnp.ndarray, sin: jnp.ndarray,
           positions: Optional[jnp.ndarray],
           attn_fn=attention, res_sharding=None) -> jnp.ndarray:
    """One transformer block. x: [batch, seq, hidden] (compute dtype)."""
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size

    def pin(t):
        if res_sharding is None:
            return t
        return lax.with_sharding_constraint(t, res_sharding)

    y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv_proj(cfg, y, layer)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    # checkpoint names mark the selective-remat saveables (identity outside
    # a jax.checkpoint policy that targets them — see POLICY_SAVED_NAMES in
    # runtime/activation_checkpointing/checkpointing.py): "attn_mix" = the
    # pre-projection attention output (what the wo backward consumes),
    # "attn_out"/"mlp_out" = the residual-branch projections
    attn_out = checkpoint_name(attn_fn(q, k, v, causal=True), "attn_mix")
    x = x + pin(checkpoint_name(
        attn_out.reshape(b, s, nh * hd) @ layer["wo"], "attn_out"))

    y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(checkpoint_name(y @ layer["w_gate"], "mlp_gate"))
    up = checkpoint_name(y @ layer["w_up"], "mlp_up")
    x = x + pin(checkpoint_name((gate * up) @ layer["w_down"], "mlp_out"))
    return x


def _head_split(cfg: LlamaConfig, params: Params, x: jnp.ndarray,
                compute_dtype):
    """Final norm + unembed matrix WITHOUT the logits matmul — the
    factorization the tiled fused logits+loss head consumes so [B, S, V]
    is never materialized. ``_head`` composes it back for the dense path."""
    x = rms_norm(x, params["final_norm"].astype(compute_dtype),
                 cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return x, head.astype(compute_dtype)


def _head(cfg: LlamaConfig, params: Params, x: jnp.ndarray, compute_dtype):
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def apply(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    """Forward pass → logits [batch, seq, vocab] (fp32); with
    ``return_hidden`` → the ``_head_split`` pair (normed hidden, unembed)
    for the tiled loss head instead.

    Layers run under ``lax.scan`` over the stacked leading dim; with
    ``cfg.remat`` each block is wrapped in ``jax.checkpoint`` so the backward
    pass rematerializes activations (the reference's
    ``runtime/activation_checkpointing``)."""
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)

    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    pipe_stages = 1
    if cfg.use_pipeline:
        try:
            from ..comm.mesh import get_mesh

            pipe_stages = get_mesh().pp_world_size
        except Exception:
            pipe_stages = 1

    attn_fn = _resolve_attention(cfg, in_pipeline=pipe_stages > 1)
    # no residual pin inside the pipeline's manual shard_map region (the
    # full-mesh NamedSharding is not addressable from there)
    res_sharding = _residual_sharding() if pipe_stages == 1 else None
    if res_sharding is not None:
        # enter the blocks already in the residual layout so layer 0 doesn't
        # pay a reshard inside the scan
        x = lax.with_sharding_constraint(x, res_sharding)
    block = partial(_block, cfg, attn_fn=attn_fn, res_sharding=res_sharding)
    if cfg.remat:
        # route through the shared remat-policy registry
        # (runtime/activation_checkpointing) so the config knob and the model
        # agree on policy names
        from ..runtime.activation_checkpointing import checkpointing as ac

        name = {"none": "full", "full": "full",
                "dots": "dots_saveable"}.get(cfg.remat_policy, cfg.remat_policy)
        block = jax.checkpoint(block, policy=ac.get_policy(name))

    if pipe_stages > 1:
        from ..runtime.pipe import pipeline_apply

        x = pipeline_apply(lambda layer, h: block(h, layer, cos, sin, positions),
                           layers, x)
    else:
        from ..comm import overlap as ov

        def scan_body(x, layer):
            # ZeRO-3: pin the slice to the gathered compute layout
            # (engine-published; identity otherwise) so SPMD can't
            # repartition the fwd+bwd scan into wrong numerics
            return block(x, ov.constrain_scan_slice(layer),
                         cos, sin, positions), None

        if ov.layer_prefetch_active():
            # ZeRO-3 per-layer all-gather prefetch: layer i+1's param shards
            # gather while layer i's matmuls run (engine-configured; same
            # slices in the same order → bit-identical to the plain scan)
            x, _ = ov.prefetch_scan(scan_body, x, layers)
        else:
            x, _ = lax.scan(scan_body, x, layers)
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# --------------------------------------------------------------------------- #
# KV-cached inference path (reference: inference v1 fused-module decode and
# v2 ``inference/v2/model_implementations/llama_v2`` — here a pure function
# over a stacked cache pytree, scanned per layer)
# --------------------------------------------------------------------------- #
def init_cache(cfg: LlamaConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    """Dense KV cache: [layers, batch, max_len, kv_heads, head_dim]."""
    L, nkv, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_size
    shape = (L, batch_size, max_len, nkv, hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: LlamaConfig) -> Params:
    spec = ("layers", None, None, "kv_heads", None)
    return {"k": spec, "v": spec}


def _write_cache(cache: jnp.ndarray, new: jnp.ndarray,
                 starts: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V rows into the cache at per-sequence offsets.
    cache [b, S, nkv, hd], new [b, t, nkv, hd], starts [b]."""
    def one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

    return jax.vmap(one)(cache, new, starts)


def _block_cached(cfg: LlamaConfig, x: jnp.ndarray, layer: Params,
                  k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                  cache_len: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
                  positions: jnp.ndarray):
    """One block with KV-cache read/write. x: [b, t, h]; cache_len: [b]."""
    b, t, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    S = k_cache.shape[1]

    y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv_proj(cfg, y, layer)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    k_cache = _write_cache(k_cache, k, cache_len)
    v_cache = _write_cache(v_cache, v, cache_len)

    # attend over the cache: kv slot j is visible to query i (absolute
    # position cache_len + i) iff j <= cache_len + i
    kv_pos = jnp.arange(S)[None, None, None, :]
    q_abs = cache_len[:, None, None, None] + jnp.arange(t)[None, None, :, None]
    mask = kv_pos <= q_abs  # [b, 1, t, S]
    attn_out = attention(q, k_cache, v_cache, causal=False, mask=mask)
    x = x + attn_out.reshape(b, t, nh * hd) @ layer["wo"]

    y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(y @ layer["w_gate"])
    up = y @ layer["w_up"]
    x = x + (gate * up) @ layer["w_down"]
    return x, k_cache, v_cache


def apply_cached(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Forward with KV cache (prefill when cache_len==0, decode otherwise).

    tokens [b, t]; cache_len [b] — number of valid cache slots per sequence.
    Returns (logits [b, t, vocab] fp32, updated cache)."""
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(tokens.shape[1])[None, :]

    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        x, k_c, v_c = _block_cached(cfg, x, layer, k_c, v_c, cache_len,
                                    cos, sin, positions)
        return x, (k_c, v_c)

    x, (new_k, new_v) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"].astype(compute_dtype), cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = x @ head.astype(compute_dtype)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


# --------------------------------------------------------------------------- #
# Paged (blocked) KV-cache path — reference: inference v2 blocked attention
# over ``BlockedKVCache`` (``inference/v2/ragged/kv_cache.py``) and the ragged
# decode kernels. Block tables are fixed-width; block 0 is the trash block.
# --------------------------------------------------------------------------- #
def init_paged_cache(cfg: LlamaConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None) -> Params:
    # [*, nkv, block_size, hd]: the decode kernel's per-block tile is then
    # (block_size, hd) — legal TPU tiling (second-to-last %8; a squeezed kv
    # head in the last two positions is rejected by the Mosaic lowering).
    # kv_quant_group (inference.kv_quant): int8 code pools + fp32 scale
    # pools instead — see models/_paged.py.
    return _init_paged_pools(cfg.num_layers, num_blocks, cfg.num_kv_heads,
                             block_size, cfg.head_size, dtype,
                             kv_quant_group)



def _block_paged(cfg: LlamaConfig, x: jnp.ndarray, layer: Params,
                 k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                 block_tables: jnp.ndarray, context_lens: jnp.ndarray,
                 valid: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
                 positions: jnp.ndarray):
    """One block over the paged cache. x [B, t, h]; block_tables
    [B, max_blocks]; context_lens [B]; valid [B, t] (False → write to trash)."""
    b, t, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size

    y = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv_proj(cfg, y, layer)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    attn_out, k_cache, v_cache = paged_attention_step(
        q, k, v, k_cache, v_cache, block_tables, context_lens, positions,
        valid)
    x = x + attn_out.reshape(b, t, nh * hd) @ layer["wo"]

    y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(y @ layer["w_gate"])
    up = y @ layer["w_up"]
    x = x + (gate * up) @ layer["w_down"]
    return x, k_cache, v_cache


def apply_paged(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                cache: Params, block_tables: jnp.ndarray,
                context_lens: jnp.ndarray, *,
                valid: Optional[jnp.ndarray] = None,
                compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Ragged forward over the paged cache (prefill chunks or decode steps).

    tokens [B, t]; context_lens [B] tokens already cached per sequence;
    block_tables [B, max_blocks] into the shared pool; valid [B, t] marks
    real (non-pad) tokens. Returns (logits [B, t, vocab] fp32, cache)."""
    b, t = tokens.shape
    if valid is None:
        valid = jnp.ones((b, t), bool)
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = context_lens[:, None] + jnp.arange(t)[None, :]

    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        x, k_c, v_c = _block_paged(cfg, x, layer, k_c, v_c, block_tables,
                                   context_lens, valid, cos, sin, positions)
        return x, (k_c, v_c)

    # quantized-KV mode threads (codes, scales) tuples per pool (split_kv)
    x, (new_k, new_v) = lax.scan(scan_body, x, (layers,) + split_kv(cache))
    x = rms_norm(x, params["final_norm"].astype(compute_dtype), cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = x @ head.astype(compute_dtype)
    return logits.astype(jnp.float32), join_kv(new_k, new_v)


def model_spec(cfg: LlamaConfig, compute_dtype=jnp.bfloat16):
    """Build the engine-facing ModelSpec for this config."""
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="llama",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(cfg, params, tokens,
                                                    compute_dtype=compute_dtype, **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=cfg.use_pipeline,
        pipeline_grad_fn=(make_pipeline_grad_fn(cfg, compute_dtype)
                          if cfg.use_pipeline else None),
    )


def make_pipeline_grad_fn(cfg: LlamaConfig, compute_dtype=jnp.bfloat16):
    """1F1B train-step grads (used by the engine when the mesh has a pipe
    axis ≥ 2). Embedding/norm/head params are shared stage-replicated state;
    their grads reduce over 'pipe' — tied-embedding reduction included."""

    def grad_fn(params: Params, batch: Dict[str, jnp.ndarray],
                loss_scale: Optional[jnp.ndarray] = None):
        from ..runtime.pipe.one_f_one_b import pipeline_value_and_grad

        tokens = batch["tokens"]
        if "labels" in batch:
            inputs, labels = tokens, batch["labels"]
        else:
            inputs, labels = tokens[:, :-1], tokens[:, 1:]
        cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len,
                                    cfg.rope_theta)
        attn_fn = _resolve_attention(cfg, in_pipeline=True)
        scale = 1.0 if loss_scale is None else loss_scale

        # each side carries only the params it reads (zero-grad vocab-sized
        # buffers would otherwise be psum'd over pipe every step); with tied
        # embeddings the head side includes 'embed' and the grad merge below
        # sums the two partials — ReduceTiedGrads
        E_params = {"embed": params["embed"]}
        H_params = {"final_norm": params["final_norm"]}
        if "lm_head" in params:
            H_params["lm_head"] = params["lm_head"]
        else:
            H_params["embed"] = params["embed"]

        def embed_fn(P, toks):
            return embedding_lookup(P["embed"], toks, compute_dtype)

        def block(layer, h):
            layer = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, layer)
            return _block(cfg, h, layer, cos, sin, None, attn_fn=attn_fn)

        def head_fn(P, h, lab):
            x = rms_norm(h, P["final_norm"].astype(compute_dtype),
                         cfg.rms_norm_eps)
            head = P.get("lm_head")
            head = P["embed"].T if head is None else head
            logits = (x @ head.astype(compute_dtype)).astype(jnp.float32)
            valid = lab != -100
            safe = jnp.where(valid, lab, 0)
            logp = jax.nn.log_softmax(logits, axis=-1)
            tl = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            # SUM of token losses — the global valid-token mean divides once
            # at the end (a per-micro mean would up-weight short microbatches
            # vs the unpipelined loss_fn). Loss scaling seeds the backward.
            return jnp.where(valid, tl, 0.0).sum() * scale

        loss, grads = pipeline_value_and_grad(
            embed_fn, block, head_fn,
            {"embed": E_params, "layers": params["layers"], "head": H_params},
            inputs, labels)
        # module returns (1/M)*sum_i loss_i and matching grads; rescale both
        # to the global valid-token mean
        from ..comm.mesh import get_mesh

        M = max(get_mesh().pp_world_size, 1)  # module default num_micro = S
        denom = jnp.maximum((labels != -100).sum(), 1).astype(jnp.float32)
        factor = M / denom
        g_merged = dict(grads["embed"])
        for k, v in grads["head"].items():
            g_merged[k] = jax.tree.map(jnp.add, g_merged[k], v) \
                if k in g_merged else v
        out_grads = {k: jax.tree.map(lambda g: g * factor, v)
                     for k, v in g_merged.items()}
        out_grads["layers"] = jax.tree.map(lambda g: g * factor,
                                           grads["layers"])
        loss = loss * factor / scale
        return out_grads, loss, {"loss": loss,
                                 "ntokens": (labels != -100).sum()}

    return grad_fn


def loss_fn(cfg: LlamaConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Next-token cross-entropy. batch: {"tokens": [b, s+1]} or
    {"tokens": [b, s], "labels": [b, s]} with -100 = ignore."""
    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    valid = labels != -100
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, token_loss, 0.0).sum() / denom
    return loss, {"loss": loss, "ntokens": valid.sum()}


def tiled_loss_fn(cfg: LlamaConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile
    (``sequence.tiled_loss``): the [B, S, V] logits tensor — the first OOM
    at long context — is never materialized; one [B, S/shards, V] tile
    lives at a time inside a rematerialized scan."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head = apply(cfg, params, inputs, compute_dtype=compute_dtype,
                         return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    return loss, {"loss": loss, "ntokens": (labels != -100).sum()}
