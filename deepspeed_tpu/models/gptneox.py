"""GPT-NeoX / GPT-J family, written TPU-first.

Reference parity: the reference serves both through v1 injection policies
(``module_inject/containers/gptneox.py`` and ``gptj.py``) over the fused
inference modules. One config covers both architectures here; the deltas are
all flags:

==============  ======================  =====================
                GPT-NeoX                GPT-J
==============  ======================  =====================
norms           ln1 + ln2 (parallel)    single shared ln
rotary          pct of head (split)     rotary_dim, interleaved
attn biases     yes                     no
mlp biases      yes                     yes
lm_head         no bias                 bias
==============  ======================  =====================

Both use parallel residual blocks (``x + attn(ln(x)) + mlp(ln'(x))``);
NeoX checkpoints with ``use_parallel_residual=False`` fall back to the
sequential ordering. Same TPU shape as ``models/llama``: stacked layers under
``lax.scan``, logical axis names per param for the sharding-rule engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm
from ..ops.rotary import apply_rotary_partial, rope_frequencies

Params = Dict[str, Any]


@dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    intermediate_size: int = 24576
    num_layers: int = 44
    num_heads: int = 64
    max_seq_len: int = 2048
    rotary_pct: float = 0.25
    rotary_dim: Optional[int] = None     # explicit override (GPT-J: 64)
    rotary_interleaved: bool = False     # GPT-J rotate-every-two
    parallel_residual: bool = True
    shared_ln: bool = False              # GPT-J: one ln feeds both branches
    qkv_bias: bool = True
    attn_out_bias: bool = True
    mlp_bias: bool = True
    lm_head_bias: bool = False           # GPT-J: True
    gelu_approx: bool = False            # NeoX 'gelu' (erf); GPT-J 'gelu_new'
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0

    def __post_init__(self):
        if self.shared_ln and not self.parallel_residual:
            raise ValueError("shared_ln requires parallel_residual (the "
                             "sequential ordering needs a distinct post-"
                             "attention norm)")

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rot_dim(self) -> int:
        if self.rotary_dim is not None:
            return self.rotary_dim
        return int(self.head_size * self.rotary_pct)

    @classmethod
    def tiny(cls, **kw) -> "GPTNeoXConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, max_seq_len=128)
        base.update(kw)
        return cls(**base)

    @classmethod
    def gptj_6b(cls) -> "GPTNeoXConfig":
        return cls(vocab_size=50400, hidden_size=4096, intermediate_size=16384,
                   num_layers=28, num_heads=16, max_seq_len=2048,
                   rotary_dim=64, rotary_interleaved=True, shared_ln=True,
                   qkv_bias=False, attn_out_bias=False, lm_head_bias=True,
                   gelu_approx=True)


def init(cfg: GPTNeoXConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, v, i = cfg.num_layers, cfg.num_heads, cfg.vocab_size, cfg.intermediate_size
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5).astype(dtype)

    layers: Params = {
        "ln1_scale": jnp.ones((L, h), dtype),
        "ln1_bias": jnp.zeros((L, h), dtype),
        "wq": normal(keys[1], (L, h, nh * hd), h),
        "wk": normal(keys[2], (L, h, nh * hd), h),
        "wv": normal(keys[3], (L, h, nh * hd), h),
        "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
        "w_up": normal(keys[5], (L, h, i), h),
        "w_down": normal(keys[6], (L, i, h), i),
    }
    if not cfg.shared_ln:
        layers["ln2_scale"] = jnp.ones((L, h), dtype)
        layers["ln2_bias"] = jnp.zeros((L, h), dtype)
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, nh * hd), dtype)
        layers["bk"] = jnp.zeros((L, nh * hd), dtype)
        layers["bv"] = jnp.zeros((L, nh * hd), dtype)
    if cfg.attn_out_bias:
        layers["bo"] = jnp.zeros((L, h), dtype)
    if cfg.mlp_bias:
        layers["b_up"] = jnp.zeros((L, i), dtype)
        layers["b_down"] = jnp.zeros((L, h), dtype)
    params: Params = {
        "embed": normal(keys[0], (v, h), h),
        "layers": layers,
        "final_ln_scale": jnp.ones((h,), dtype),
        "final_ln_bias": jnp.zeros((h,), dtype),
        "lm_head": normal(keys[7], (h, v), h),
    }
    if cfg.lm_head_bias:
        params["lm_head_bias"] = jnp.zeros((v,), dtype)
    return params


def param_logical_axes(cfg: GPTNeoXConfig) -> Params:
    layers = {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "heads"),
        "wv": ("layers", "embed", "heads"),
        "wo": ("layers", "heads", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    if not cfg.shared_ln:
        layers["ln2_scale"] = ("layers", "embed")
        layers["ln2_bias"] = ("layers", "embed")
    if cfg.qkv_bias:
        layers["bq"] = ("layers", "heads")
        layers["bk"] = ("layers", "heads")
        layers["bv"] = ("layers", "heads")
    if cfg.attn_out_bias:
        layers["bo"] = ("layers", "embed")
    if cfg.mlp_bias:
        layers["b_up"] = ("layers", "mlp")
        layers["b_down"] = ("layers", "embed")
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
        "lm_head": ("embed", "vocab"),
    }
    if cfg.lm_head_bias:
        axes["lm_head_bias"] = ("vocab",)
    return axes


def _qkv(cfg: GPTNeoXConfig, y: jnp.ndarray, layer: Params,
         cos, sin, positions):
    b, s, _ = y.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q, k, v = y @ layer["wq"], y @ layer["wk"], y @ layer["wv"]
    if "bq" in layer:
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nh, hd)
    v = v.reshape(b, s, nh, hd)
    rot = partial(apply_rotary_partial, rotary_dim=cfg.rot_dim,
                  interleaved=cfg.rotary_interleaved)
    return rot(q, cos, sin, positions), rot(k, cos, sin, positions), v


def _mlp(cfg: GPTNeoXConfig, y: jnp.ndarray, layer: Params) -> jnp.ndarray:
    u = y @ layer["w_up"]
    if "b_up" in layer:
        u = u + layer["b_up"]
    d = jax.nn.gelu(u, approximate=cfg.gelu_approx) @ layer["w_down"]
    if "b_down" in layer:
        d = d + layer["b_down"]
    return d


def _block(cfg: GPTNeoXConfig, x: jnp.ndarray, layer: Params,
           cos, sin, positions) -> jnp.ndarray:
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    y1 = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"],
                    cfg.layer_norm_eps)
    q, k, v = _qkv(cfg, y1, layer, cos, sin, positions)
    attn_out = attention(q, k, v, causal=True).reshape(b, s, nh * hd) @ layer["wo"]
    if "bo" in layer:
        attn_out = attn_out + layer["bo"]
    if cfg.parallel_residual:
        y2 = y1 if cfg.shared_ln else layer_norm(
            x, layer["ln2_scale"], layer["ln2_bias"], cfg.layer_norm_eps)
        return x + attn_out + _mlp(cfg, y2, layer)
    x = x + attn_out
    y2 = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"],
                    cfg.layer_norm_eps)
    return x + _mlp(cfg, y2, layer)


def _head_split(cfg: GPTNeoXConfig, params: Params, x: jnp.ndarray,
                compute_dtype):
    """Final norm + unembed matrix (+ optional logit bias) minus the
    logits matmul — consumed by the tiled fused logits+loss head."""
    x = layer_norm(x, params["final_ln_scale"].astype(compute_dtype),
                   params["final_ln_bias"].astype(compute_dtype),
                   cfg.layer_norm_eps)
    bias = params.get("lm_head_bias")
    return (x, params["lm_head"].astype(compute_dtype),
            None if bias is None else bias.astype(compute_dtype))


def _head(cfg: GPTNeoXConfig, params: Params, x: jnp.ndarray,
          compute_dtype) -> jnp.ndarray:
    x, head, bias = _head_split(cfg, params, x, compute_dtype)
    logits = x @ head
    if bias is not None:
        logits = logits + bias
    return logits.astype(jnp.float32)


def _cast_layers(params: Params, compute_dtype):
    return jax.tree.map(lambda p: p.astype(compute_dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p,
                        params["layers"])


def apply(cfg: GPTNeoXConfig, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.rot_dim, cfg.max_seq_len, cfg.rope_theta)
    layers = _cast_layers(params, compute_dtype)

    from ..comm import overlap as ov

    def scan_body(x, layer):
        return _block(cfg, x, ov.constrain_scan_slice(layer),
                      cos, sin, positions), None

    x, _ = lax.scan(scan_body, x, layers)
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# ---- KV-cached decode (v1-engine path) ---- #
def init_cache(cfg: GPTNeoXConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    L, nh, hd = cfg.num_layers, cfg.num_heads, cfg.head_size
    shape = (L, batch_size, max_len, nh, hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: GPTNeoXConfig) -> Params:
    spec = ("layers", None, None, "heads", None)
    return {"k": spec, "v": spec}


def _write_cache(cache, new, starts):
    def one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

    return jax.vmap(one)(cache, new, starts)


def apply_cached(cfg: GPTNeoXConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    b, t = tokens.shape
    nh, hd = cfg.num_heads, cfg.head_size
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.rot_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(t)[None, :]
    layers = _cast_layers(params, compute_dtype)

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        S = k_c.shape[1]
        y1 = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"],
                        cfg.layer_norm_eps)
        q, k, v = _qkv(cfg, y1, layer, cos, sin, positions)
        k_c = _write_cache(k_c, k, cache_len)
        v_c = _write_cache(v_c, v, cache_len)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = cache_len[:, None, None, None] + jnp.arange(t)[None, None, :, None]
        mask = kv_pos <= q_abs
        attn_out = attention(q, k_c, v_c, causal=False, mask=mask)
        attn_out = attn_out.reshape(b, t, nh * hd) @ layer["wo"]
        if "bo" in layer:
            attn_out = attn_out + layer["bo"]
        if cfg.parallel_residual:
            y2 = y1 if cfg.shared_ln else layer_norm(
                x, layer["ln2_scale"], layer["ln2_bias"], cfg.layer_norm_eps)
            x = x + attn_out + _mlp(cfg, y2, layer)
        else:
            x = x + attn_out
            y2 = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"],
                            cfg.layer_norm_eps)
            x = x + _mlp(cfg, y2, layer)
        return x, (k_c, v_c)

    x, (new_k, new_v) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    return _head(cfg, params, x, compute_dtype), {"k": new_k, "v": new_v}


def loss_fn(cfg: GPTNeoXConfig, params: Params, batch: Dict[str, jnp.ndarray],
            *, compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tl = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(valid, tl, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return loss, {"loss": loss, "ntokens": valid.sum()}


def tiled_loss_fn(cfg: GPTNeoXConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``)."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head, bias = apply(cfg, params, inputs,
                               compute_dtype=compute_dtype,
                               return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards,
                                   bias=bias)
    return loss, {"loss": loss, "ntokens": (labels != -100).sum()}


def model_spec(cfg: GPTNeoXConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="gptneox",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(
            cfg, params, tokens, compute_dtype=compute_dtype, **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )
