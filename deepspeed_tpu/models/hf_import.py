"""HF checkpoint import: transformers state dicts → stacked param pytrees.

Reference parity: the reference consumes HF models directly (``deepspeed.
initialize(model=hf_model)``, ``init_inference`` checkpoint loading
``inference/engine.py:303-471``) and reshards TP-degree-changing checkpoints
via ``SDLoaderFactory``/``MegatronSDLoader`` (``runtime/state_dict_factory.py:
21,190``). Here a user brings HF weights to the TPU framework by converting
once into the stacked [L, ...] pytree layout; resharding to any topology is
then the checkpoint layer's job (orbax/universal).

Supported families: Llama/Mistral/Qwen2/Phi-3 (→ ``models/llama``; fused
QKV/gate-up checkpoints are split), GPT-2 (→ ``models/gpt``),
Mixtral/Qwen2-MoE (→ ``models/mixtral``), Falcon (→ ``models/falcon``), OPT (→ ``models/gpt``,
ReLU/pre-LN), GPT-NeoX/GPT-J (→ ``models/gptneox``), BLOOM (→ ``models/bloom``,
ALiBi), BERT/DistilBERT (→ ``models/bert``), CLIP (→ ``models/clip``,
both towers + contrastive head), Megatron-GPT state dicts
(``megatron_gpt_params_from_sd``, composing with the TP-degree-changing
``SDLoaderFactory``). Accepts a live
``transformers`` model, a state-dict mapping, or a local checkpoint directory
(no network access is assumed). Un-annotated models TP-shard via the AutoTP
name-rule pass (``module_inject/auto_tp.py``).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Optional

import numpy as np

from ..utils.logging import log_dist

Params = Dict[str, Any]


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:  # torch tensor
        return t.detach().cpu().float().numpy()
    except AttributeError:
        return np.asarray(t)


def _normalize_state_dict(src) -> Dict[str, np.ndarray]:
    """Accept a transformers model, an nn.Module, or a mapping."""
    if hasattr(src, "state_dict") and callable(src.state_dict):
        src = src.state_dict()
    if not isinstance(src, Mapping):
        raise TypeError(f"cannot read weights from {type(src)}")
    return {k: _to_numpy(v) for k, v in src.items()}


def _count_indices(sd: Dict[str, np.ndarray], pattern: str) -> int:
    """1 + max index matched by ``pattern`` (one capture group) over keys."""
    idx = [int(m.group(1)) for k in sd if (m := re.match(pattern, k))]
    if not idx:
        raise KeyError(f"no keys match {pattern!r} — wrong family/prefix?")
    return 1 + max(idx)


def _stack(sd: Dict[str, np.ndarray], pattern: str, num_layers: int,
           transpose: bool = False) -> np.ndarray:
    """Collect per-layer tensors 'prefix.{i}.suffix' into one [L, ...] array."""
    mats = []
    for i in range(num_layers):
        key = pattern.format(i=i)
        if key not in sd:
            raise KeyError(f"missing weight {key}")
        m = sd[key]
        mats.append(m.T if transpose else m)
    return np.stack(mats)


def llama_config_from_hf(hf_config) -> "Any":
    """Map a transformers LlamaConfig/MistralConfig/Qwen2Config/Phi3Config."""
    from .llama import LlamaConfig

    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        # longrope (Phi-3-128k) / llama3 scaling rescale even short contexts;
        # silently applying plain RoPE would give wrong logits everywhere
        raise ValueError(
            f"rope_scaling={scaling.get('type', scaling.get('rope_type'))!r} "
            f"checkpoints are not supported yet — import the base "
            f"(non-scaled) variant")
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rms_norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        # Qwen2 always uses QKV biases; Llama exposes an attention_bias flag
        attention_bias=bool(getattr(hf_config, "attention_bias",
                                    hf_config.model_type == "qwen2")),
        # Qwen3: decoupled head_dim + per-head q/k RMSNorm, no QKV bias
        head_dim=getattr(hf_config, "head_dim", None),
        qk_norm=hf_config.model_type == "qwen3",
    )


def llama_params_from_hf(src, cfg=None) -> Params:
    """HF LlamaForCausalLM (or compatible) weights → ``models/llama`` pytree.
    HF nn.Linear stores [out, in]; our layout is [in, out] → transpose."""
    sd = _normalize_state_dict(src)
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.(\d+)\.")
    lay = pfx + "layers.{i}."
    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "layers": {
            "attn_norm": _stack(sd, lay + "input_layernorm.weight", L),
            "wq": _stack(sd, lay + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, lay + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, lay + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L, transpose=True),
            "mlp_norm": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "w_gate": _stack(sd, lay + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, lay + "mlp.up_proj.weight", L, transpose=True),
            "w_down": _stack(sd, lay + "mlp.down_proj.weight", L, transpose=True),
        },
        "final_norm": sd[pfx + "norm.weight"],
    }
    if "lm_head.weight" in sd and \
            not (cfg is not None and cfg.tie_embeddings):
        params["lm_head"] = sd["lm_head.weight"].T  # tied ckpts alias it
    has_bias = (lay.format(i=0) + "self_attn.q_proj.bias") in sd
    if has_bias:
        # Qwen2 QKV biases (ADVICE r1: these were silently dropped)
        params["layers"]["bq"] = _stack(sd, lay + "self_attn.q_proj.bias", L)
        params["layers"]["bk"] = _stack(sd, lay + "self_attn.k_proj.bias", L)
        params["layers"]["bv"] = _stack(sd, lay + "self_attn.v_proj.bias", L)
    has_qk_norm = (lay.format(i=0) + "self_attn.q_norm.weight") in sd
    if has_qk_norm:
        params["layers"]["q_norm"] = _stack(sd, lay + "self_attn.q_norm.weight", L)
        params["layers"]["k_norm"] = _stack(sd, lay + "self_attn.k_norm.weight", L)
    if cfg is not None and \
            bool(getattr(cfg, "qk_norm", False)) != has_qk_norm:
        # same silent-drop class as the attention_bias check below: a
        # missing norm would silently skip in _qkv_proj; an unexpected one
        # would load leaves with no logical-axes entry
        raise ValueError(
            f"qk_norm={getattr(cfg, 'qk_norm', False)} but checkpoint "
            f"{'has' if has_qk_norm else 'lacks'} q_norm.weight tensors")
    if cfg is not None and bool(getattr(cfg, "attention_bias", False)) != has_bias:
        raise ValueError(
            f"attention_bias={getattr(cfg, 'attention_bias', False)} but "
            f"checkpoint {'has' if has_bias else 'lacks'} q_proj.bias tensors")
    log_dist(f"imported HF llama-family weights: {L} layers, "
             f"vocab {params['embed'].shape[0]}, qkv_bias={has_bias}")
    return params


def gpt2_config_from_hf(hf_config) -> "Any":
    from .gpt import GPTConfig

    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        intermediate_size=getattr(hf_config, "n_inner", None) or 4 * hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        layer_norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        tie_embeddings=True,
    )


def gpt2_params_from_hf(src, cfg=None) -> Params:
    """HF GPT2LMHeadModel weights → ``models/gpt`` pytree. GPT-2 Conv1D
    already stores [in, out] — no transpose."""
    sd = _normalize_state_dict(src)
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}h\.(\d+)\.")
    lay = pfx + "h.{i}."
    params: Params = {
        "embed": sd[pfx + "wte.weight"],
        "pos_embed": sd[pfx + "wpe.weight"],
        "layers": {
            "ln1_scale": _stack(sd, lay + "ln_1.weight", L),
            "ln1_bias": _stack(sd, lay + "ln_1.bias", L),
            "wqkv": _stack(sd, lay + "attn.c_attn.weight", L),
            "bqkv": _stack(sd, lay + "attn.c_attn.bias", L),
            "wo": _stack(sd, lay + "attn.c_proj.weight", L),
            "bo": _stack(sd, lay + "attn.c_proj.bias", L),
            "ln2_scale": _stack(sd, lay + "ln_2.weight", L),
            "ln2_bias": _stack(sd, lay + "ln_2.bias", L),
            "w_up": _stack(sd, lay + "mlp.c_fc.weight", L),
            "b_up": _stack(sd, lay + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, lay + "mlp.c_proj.weight", L),
            "b_down": _stack(sd, lay + "mlp.c_proj.bias", L),
        },
        "final_ln_scale": sd[pfx + "ln_f.weight"],
        "final_ln_bias": sd[pfx + "ln_f.bias"],
    }
    log_dist(f"imported HF gpt2-family weights: {L} layers")
    return params


def opt_config_from_hf(hf_config) -> "Any":
    """Map a transformers OPTConfig onto the GPT family (pre-LN, ReLU,
    learned positions; reference ``inference/v2/model_implementations/opt``)."""
    from .gpt import GPTConfig

    if getattr(hf_config, "word_embed_proj_dim",
               hf_config.hidden_size) != hf_config.hidden_size:
        raise ValueError("OPT variants with word_embed_proj_dim != "
                         "hidden_size (opt-350m) are not supported")
    if not getattr(hf_config, "do_layer_norm_before", True):
        raise ValueError("OPT with do_layer_norm_before=False (opt-350m) "
                         "is not supported")
    act = getattr(hf_config, "activation_function", "relu")
    if act != "relu":
        # silently running a different activation would give wrong logits
        # (and HF 'gelu' is exact-erf vs jax's tanh default)
        raise ValueError(f"OPT activation_function={act!r} not supported "
                         "(only 'relu', the released OPT family)")
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.ffn_dim,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        max_seq_len=hf_config.max_position_embeddings,
        activation=act,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", True)),
    )


def opt_params_from_hf(src, cfg=None) -> Params:
    """HF OPTForCausalLM → ``models/gpt`` pytree: q/k/v/out projections fuse
    into wqkv/bqkv; OPT's learned positions carry a +2 offset, dropped here
    by slicing the table."""
    sd = _normalize_state_dict(src)
    pfx = "model.decoder." if any(k.startswith("model.decoder.") for k in sd) \
        else "decoder." if any(k.startswith("decoder.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.(\d+)\.")
    lay = pfx + "layers.{i}."

    def fuse_qkv(i):
        ws = [sd[lay.format(i=i) + f"self_attn.{p}_proj.weight"].T
              for p in ("q", "k", "v")]
        bs = [sd[lay.format(i=i) + f"self_attn.{p}_proj.bias"]
              for p in ("q", "k", "v")]
        return np.concatenate(ws, axis=1), np.concatenate(bs)

    fused = [fuse_qkv(i) for i in range(L)]
    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "pos_embed": sd[pfx + "embed_positions.weight"][2:],  # OPT offset
        "layers": {
            "ln1_scale": _stack(sd, lay + "self_attn_layer_norm.weight", L),
            "ln1_bias": _stack(sd, lay + "self_attn_layer_norm.bias", L),
            "wqkv": np.stack([w for w, _ in fused]),
            "bqkv": np.stack([b for _, b in fused]),
            "wo": _stack(sd, lay + "self_attn.out_proj.weight", L,
                         transpose=True),
            "bo": _stack(sd, lay + "self_attn.out_proj.bias", L),
            "ln2_scale": _stack(sd, lay + "final_layer_norm.weight", L),
            "ln2_bias": _stack(sd, lay + "final_layer_norm.bias", L),
            "w_up": _stack(sd, lay + "fc1.weight", L, transpose=True),
            "b_up": _stack(sd, lay + "fc1.bias", L),
            "w_down": _stack(sd, lay + "fc2.weight", L, transpose=True),
            "b_down": _stack(sd, lay + "fc2.bias", L),
        },
        "final_ln_scale": sd[pfx + "final_layer_norm.weight"],
        "final_ln_bias": sd[pfx + "final_layer_norm.bias"],
    }
    if cfg is not None and not cfg.tie_embeddings:
        if "lm_head.weight" not in sd:
            raise ValueError("untied OPT config but checkpoint has no "
                             "lm_head.weight")
        params["lm_head"] = sd["lm_head.weight"].T
    log_dist(f"imported HF opt weights: {L} layers")
    return params


def phi3_params_from_hf(src, cfg=None) -> Params:
    """HF Phi3ForCausalLM → ``models/llama`` pytree. Phi-3 fuses QKV into
    ``self_attn.qkv_proj`` and gate/up into ``mlp.gate_up_proj`` (reference
    ``inference/v2/model_implementations/phi3``) — split them here."""
    sd = _normalize_state_dict(src)
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.(\d+)\.")
    lay = pfx + "layers.{i}."
    qkv = _stack(sd, lay + "self_attn.qkv_proj.weight", L, transpose=True)
    gate_up = _stack(sd, lay + "mlp.gate_up_proj.weight", L, transpose=True)
    h = qkv.shape[1]
    if cfg is not None:
        nq = cfg.num_heads * cfg.head_size
        nkv = cfg.num_kv_heads * cfg.head_size
    else:  # phi3: q span == hidden, k/v split the rest evenly
        nq = h
        nkv = (qkv.shape[2] - nq) // 2
    inter = gate_up.shape[2] // 2
    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "layers": {
            "attn_norm": _stack(sd, lay + "input_layernorm.weight", L),
            "wq": qkv[:, :, :nq],
            "wk": qkv[:, :, nq:nq + nkv],
            "wv": qkv[:, :, nq + nkv:],
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L, transpose=True),
            "mlp_norm": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "w_gate": gate_up[:, :, :inter],
            "w_up": gate_up[:, :, inter:],
            "w_down": _stack(sd, lay + "mlp.down_proj.weight", L, transpose=True),
        },
        "final_norm": sd[pfx + "norm.weight"],
    }
    if "lm_head.weight" in sd:
        params["lm_head"] = sd["lm_head.weight"].T
    log_dist(f"imported HF phi3 weights: {L} layers (split fused qkv/gate_up)")
    return params


def mixtral_config_from_hf(hf_config) -> "Any":
    from .mixtral import MixtralConfig

    return MixtralConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        num_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        # HF Mixtral routes every token (no capacity limit): disable token
        # dropping so imported logits match exactly
        drop_tokens=False,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=float(getattr(hf_config, "rope_theta", 1e6)),
        rms_norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        aux_loss_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.02)),
    )


def mixtral_params_from_hf(src, cfg=None) -> Params:
    """HF MixtralForCausalLM → ``models/mixtral`` pytree. Experts stack to
    [L, E, ...] (reference ``inference/v2/model_implementations/mixtral``)."""
    sd = _normalize_state_dict(src)
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.(\d+)\.")
    lay = pfx + "layers.{i}."
    E = cfg.num_experts if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.0\.block_sparse_moe"
                           rf"\.experts\.(\d+)\.")

    def stack_expert(w: str) -> np.ndarray:  # → [L, E, out, in] pre-transpose
        return np.stack([
            np.stack([sd[lay.format(i=i) +
                         f"block_sparse_moe.experts.{e}.{w}.weight"].T
                      for e in range(E)]) for i in range(L)])

    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "layers": {
            "attn_norm": _stack(sd, lay + "input_layernorm.weight", L),
            "wq": _stack(sd, lay + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, lay + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, lay + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L, transpose=True),
            "mlp_norm": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "moe": {
                "router": _stack(sd, lay + "block_sparse_moe.gate.weight", L,
                                 transpose=True),
                "w_gate": stack_expert("w1"),
                "w_up": stack_expert("w3"),
                "w_down": stack_expert("w2"),
            },
        },
        "final_norm": sd[pfx + "norm.weight"],
        # tied checkpoints omit lm_head from the state dict — materialize the
        # transpose (models/mixtral always carries an explicit head)
        "lm_head": (sd["lm_head.weight"].T if "lm_head.weight" in sd
                    else sd[pfx + "embed_tokens.weight"].T.copy()),
    }
    log_dist(f"imported HF mixtral weights: {L} layers x {E} experts")
    return params


def qwen2_moe_config_from_hf(hf_config) -> "Any":
    """Map a transformers Qwen2MoeConfig (reference ``.../qwen_v2_moe``)."""
    from .mixtral import MixtralConfig

    if getattr(hf_config, "mlp_only_layers", None) or \
            getattr(hf_config, "decoder_sparse_step", 1) != 1:
        raise ValueError("Qwen2-MoE variants with dense interleaved layers "
                         "(mlp_only_layers/decoder_sparse_step>1) are not "
                         "supported — the layer stack must be uniform for "
                         "the scanned block")
    return MixtralConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.moe_intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=hf_config.num_key_value_heads,
        num_experts=hf_config.num_experts,
        top_k=hf_config.num_experts_per_tok,
        drop_tokens=False,
        norm_topk_prob=bool(getattr(hf_config, "norm_topk_prob", False)),
        attention_bias=True,  # Qwen2 family always carries QKV biases
        shared_expert_intermediate_size=int(
            getattr(hf_config, "shared_expert_intermediate_size", 0)),
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=float(getattr(hf_config, "rope_theta", 1e6)),
        rms_norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-6)),
        aux_loss_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.001)),
    )


def qwen2_moe_params_from_hf(src, cfg=None) -> Params:
    """HF Qwen2MoeForCausalLM → ``models/mixtral`` pytree (+ shared expert
    and QKV biases)."""
    sd = _normalize_state_dict(src)
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.(\d+)\.")
    lay = pfx + "layers.{i}."
    E = cfg.num_experts if cfg is not None else \
        _count_indices(sd, rf"{re.escape(pfx)}layers\.0\.mlp\.experts"
                           rf"\.(\d+)\.")

    def stack_expert(w: str) -> np.ndarray:
        return np.stack([
            np.stack([sd[lay.format(i=i) + f"mlp.experts.{e}.{w}.weight"].T
                      for e in range(E)]) for i in range(L)])

    moe: Params = {
        "router": _stack(sd, lay + "mlp.gate.weight", L, transpose=True),
        "w_gate": stack_expert("gate_proj"),
        "w_up": stack_expert("up_proj"),
        "w_down": stack_expert("down_proj"),
        "shared_w_gate": _stack(sd, lay + "mlp.shared_expert.gate_proj.weight",
                                L, transpose=True),
        "shared_w_up": _stack(sd, lay + "mlp.shared_expert.up_proj.weight",
                              L, transpose=True),
        "shared_w_down": _stack(sd, lay + "mlp.shared_expert.down_proj.weight",
                                L, transpose=True),
        "shared_gate": _stack(sd, lay + "mlp.shared_expert_gate.weight", L,
                              transpose=True),
    }
    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "layers": {
            "attn_norm": _stack(sd, lay + "input_layernorm.weight", L),
            "wq": _stack(sd, lay + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, lay + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, lay + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L, transpose=True),
            "bq": _stack(sd, lay + "self_attn.q_proj.bias", L),
            "bk": _stack(sd, lay + "self_attn.k_proj.bias", L),
            "bv": _stack(sd, lay + "self_attn.v_proj.bias", L),
            "mlp_norm": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "moe": moe,
        },
        "final_norm": sd[pfx + "norm.weight"],
        "lm_head": (sd["lm_head.weight"].T if "lm_head.weight" in sd
                    else sd[pfx + "embed_tokens.weight"].T.copy()),
    }
    log_dist(f"imported HF qwen2_moe weights: {L} layers x {E} experts "
             f"+ shared expert")
    return params


def falcon_config_from_hf(hf_config) -> "Any":
    from .falcon import FalconConfig

    if getattr(hf_config, "alibi", False):
        # models/falcon.py applies rotary embeddings; running an ALiBi
        # checkpoint through RoPE would give silently wrong logits
        raise ValueError("alibi=True falcon checkpoints are not supported — "
                         "models/falcon.py implements the RoPE variants "
                         "(7B/40B/180B); ALiBi (rw-*) needs an ALiBi "
                         "attention path")
    return FalconConfig(
        max_seq_len=int(getattr(hf_config, "max_position_embeddings", 2048)),
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=(hf_config.num_kv_heads
                      if getattr(hf_config, "new_decoder_architecture", False)
                      else (1 if getattr(hf_config, "multi_query", True)
                            else hf_config.num_attention_heads)),
        parallel_attn=bool(getattr(hf_config, "parallel_attn", True)),
        new_decoder_architecture=bool(getattr(hf_config,
                                              "new_decoder_architecture", False)),
        layer_norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        attention_bias=bool(getattr(hf_config, "bias", False)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", True)),
    )


def falcon_params_from_hf(src, cfg) -> Params:
    """HF FalconForCausalLM → ``models/falcon`` pytree (reference
    ``inference/v2/model_implementations/falcon``). Fused-QKV layouts (HF
    ``FalconAttention._split_heads``): new decoder architecture =
    [nkv groups of (q*g | k | v)]; classic multi_query = [q-block | k | v];
    classic multi-head (rw-1b) = per-head interleaved [nh, (q | k | v)].

    ``cfg`` is required (head split depends on it) — build via
    ``falcon_config_from_hf``."""
    if cfg is None:
        raise ValueError("falcon_params_from_hf requires cfg (the fused-QKV "
                         "split depends on head counts) — build it with "
                         "falcon_config_from_hf")
    sd = _normalize_state_dict(src)
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    L = cfg.num_layers
    lay = pfx + "h.{i}."
    if (lay.format(i=0) + "self_attention.query_key_value.bias") in sd:
        raise ValueError("falcon checkpoints with linear biases (bias=True) "
                         "are not supported — models/falcon.py has no bias "
                         "params (classic 7B/40B/180B are bias-free)")
    qkv = _stack(sd, lay + "self_attention.query_key_value.weight", L,
                 transpose=True)  # [L, h, (nh + 2*nkv) * hd]
    h = qkv.shape[1]
    nh = cfg.num_heads
    nkv = cfg.num_kv_heads
    hd = cfg.head_size
    if cfg.new_decoder_architecture:
        # interleaved [nkv groups of (q*g | k | v)]
        g = nh // nkv
        fused = qkv.reshape(L, h, nkv, g + 2, hd)
        wq = fused[:, :, :, :g].reshape(L, h, nh * hd)
        wk = fused[:, :, :, g].reshape(L, h, nkv * hd)
        wv = fused[:, :, :, g + 1].reshape(L, h, nkv * hd)
    elif nkv == nh:
        # classic multi-head (multi_query=False, e.g. rw-1b): per-head
        # interleave view(.., nh, 3, hd)
        fused = qkv.reshape(L, h, nh, 3, hd)
        wq = fused[:, :, :, 0].reshape(L, h, nh * hd)
        wk = fused[:, :, :, 1].reshape(L, h, nh * hd)
        wv = fused[:, :, :, 2].reshape(L, h, nh * hd)
    else:
        # classic multi_query (7B): [q-block | k | v]
        wq = qkv[:, :, :nh * hd]
        wk = qkv[:, :, nh * hd:(nh + nkv) * hd]
        wv = qkv[:, :, (nh + nkv) * hd:]
    params: Params = {
        "embed": sd[pfx + "word_embeddings.weight"],
        "layers": {
            "ln_attn_scale": _stack(
                sd, lay + ("ln_attn.weight" if cfg.new_decoder_architecture
                           else "input_layernorm.weight"), L),
            "ln_attn_bias": _stack(
                sd, lay + ("ln_attn.bias" if cfg.new_decoder_architecture
                           else "input_layernorm.bias"), L),
            "wq": wq, "wk": wk, "wv": wv,
            "wo": _stack(sd, lay + "self_attention.dense.weight", L,
                         transpose=True),
            "w_up": _stack(sd, lay + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "w_down": _stack(sd, lay + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
        },
        "final_ln_scale": sd[pfx + "ln_f.weight"],
        "final_ln_bias": sd[pfx + "ln_f.bias"],
    }
    if cfg.new_decoder_architecture:
        params["layers"]["ln_mlp_scale"] = _stack(sd, lay + "ln_mlp.weight", L)
        params["layers"]["ln_mlp_bias"] = _stack(sd, lay + "ln_mlp.bias", L)
    elif not cfg.parallel_attn:
        # sequential classic blocks carry a distinct second norm
        params["layers"]["ln_mlp_scale"] = _stack(
            sd, lay + "post_attention_layernorm.weight", L)
        params["layers"]["ln_mlp_bias"] = _stack(
            sd, lay + "post_attention_layernorm.bias", L)
    if "lm_head.weight" in sd and not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"].T  # tied ckpts alias it
    log_dist(f"imported HF falcon weights: {L} layers (nkv={nkv})")
    return params


def _split_fused_qkv(w: np.ndarray, nh: int, hd: int):
    """De-interleave an HF fused query_key_value projection whose output rows
    are grouped per head as [q(hd); k(hd); v(hd)] (GPT-NeoX views the fused
    tensor as (nh, 3*hd), BLOOM as (nh, 3, hd) — the same row layout).
    w: [3*nh*hd, in] or bias [3*nh*hd] → (q, k, v) each [nh*hd(, in)]."""
    shape = (nh, 3, hd) + w.shape[1:]
    grouped = w.reshape(shape)
    return tuple(grouped[:, j].reshape((nh * hd,) + w.shape[1:])
                 for j in range(3))


def gptneox_config_from_hf(hf_config) -> "Any":
    from .gptneox import GPTNeoXConfig

    return GPTNeoXConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        max_seq_len=hf_config.max_position_embeddings,
        rotary_pct=float(getattr(hf_config, "rotary_pct", 1.0)),
        rope_theta=float(getattr(hf_config, "rotary_emb_base", 10000.0)),
        parallel_residual=bool(getattr(hf_config, "use_parallel_residual",
                                       True)),
        gelu_approx=getattr(hf_config, "hidden_act", "gelu") in
        ("gelu_new", "gelu_pytorch_tanh", "gelu_fast"),
        layer_norm_eps=float(getattr(hf_config, "layer_norm_eps", 1e-5)),
    )


def gptneox_params_from_hf(src, cfg=None) -> Params:
    """HF GPTNeoXForCausalLM → ``models/gptneox`` pytree (fused QKV is
    de-interleaved per head so TP can shard the heads axis)."""
    sd = _normalize_state_dict(src)
    L = cfg.num_layers
    nh, hd = cfg.num_heads, cfg.head_size
    lay = "gpt_neox.layers.{i}."
    qkv_w = _stack(sd, lay + "attention.query_key_value.weight", L)
    qkv_b = _stack(sd, lay + "attention.query_key_value.bias", L)
    wq, wk, wv = zip(*(_split_fused_qkv(w, nh, hd) for w in qkv_w))
    bq, bk, bv = zip(*(_split_fused_qkv(b, nh, hd) for b in qkv_b))
    params: Params = {
        "embed": sd["gpt_neox.embed_in.weight"],
        "layers": {
            "ln1_scale": _stack(sd, lay + "input_layernorm.weight", L),
            "ln1_bias": _stack(sd, lay + "input_layernorm.bias", L),
            "ln2_scale": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "ln2_bias": _stack(sd, lay + "post_attention_layernorm.bias", L),
            "wq": np.stack([w.T for w in wq]),
            "wk": np.stack([w.T for w in wk]),
            "wv": np.stack([w.T for w in wv]),
            "bq": np.stack(bq), "bk": np.stack(bk), "bv": np.stack(bv),
            "wo": _stack(sd, lay + "attention.dense.weight", L, transpose=True),
            "bo": _stack(sd, lay + "attention.dense.bias", L),
            "w_up": _stack(sd, lay + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "b_up": _stack(sd, lay + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, lay + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
            "b_down": _stack(sd, lay + "mlp.dense_4h_to_h.bias", L),
        },
        "final_ln_scale": sd["gpt_neox.final_layer_norm.weight"],
        "final_ln_bias": sd["gpt_neox.final_layer_norm.bias"],
        "lm_head": sd["embed_out.weight"].T,
    }
    log_dist(f"imported HF gpt_neox weights: {L} layers")
    return params


def gptj_config_from_hf(hf_config) -> "Any":
    from .gptneox import GPTNeoXConfig

    inner = getattr(hf_config, "n_inner", None) or 4 * hf_config.n_embd
    # HF GPT-J rotates the FULL head dim when rotary_dim is None
    rotary_dim = getattr(hf_config, "rotary_dim", None)
    if rotary_dim is None:
        rotary_dim = hf_config.n_embd // hf_config.n_head
    return GPTNeoXConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        intermediate_size=inner,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        rotary_dim=rotary_dim,
        rotary_interleaved=True,
        shared_ln=True,
        qkv_bias=False,
        attn_out_bias=False,
        lm_head_bias=True,
        gelu_approx=True,   # 'gelu_new'
        layer_norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
    )


def gptj_params_from_hf(src, cfg=None) -> Params:
    """HF GPTJForCausalLM → ``models/gptneox`` pytree (shared-ln variant)."""
    sd = _normalize_state_dict(src)
    L = cfg.num_layers
    lay = "transformer.h.{i}."
    params: Params = {
        "embed": sd["transformer.wte.weight"],
        "layers": {
            "ln1_scale": _stack(sd, lay + "ln_1.weight", L),
            "ln1_bias": _stack(sd, lay + "ln_1.bias", L),
            "wq": _stack(sd, lay + "attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, lay + "attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, lay + "attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, lay + "attn.out_proj.weight", L, transpose=True),
            "w_up": _stack(sd, lay + "mlp.fc_in.weight", L, transpose=True),
            "b_up": _stack(sd, lay + "mlp.fc_in.bias", L),
            "w_down": _stack(sd, lay + "mlp.fc_out.weight", L, transpose=True),
            "b_down": _stack(sd, lay + "mlp.fc_out.bias", L),
        },
        "final_ln_scale": sd["transformer.ln_f.weight"],
        "final_ln_bias": sd["transformer.ln_f.bias"],
        "lm_head": sd["lm_head.weight"].T,
        "lm_head_bias": sd["lm_head.bias"],
    }
    log_dist(f"imported HF gptj weights: {L} layers")
    return params


def bloom_config_from_hf(hf_config) -> "Any":
    from .bloom import BloomConfig

    return BloomConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=getattr(hf_config, "seq_length", 2048),
        layer_norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
    )


def bloom_params_from_hf(src, cfg=None) -> Params:
    """HF BloomForCausalLM → ``models/bloom`` pytree. The fused
    query_key_value rows are per-head [q;k;v] groups — same layout as
    GPT-NeoX — de-interleaved here so the TP rules shard heads."""
    sd = _normalize_state_dict(src)
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    L = cfg.num_layers
    nh, hd = cfg.num_heads, cfg.head_size
    lay = pfx + "h.{i}."
    qkv_w = _stack(sd, lay + "self_attention.query_key_value.weight", L)
    qkv_b = _stack(sd, lay + "self_attention.query_key_value.bias", L)
    wq, wk, wv = zip(*(_split_fused_qkv(w, nh, hd) for w in qkv_w))
    bq, bk, bv = zip(*(_split_fused_qkv(b, nh, hd) for b in qkv_b))
    params: Params = {
        "embed": sd[pfx + "word_embeddings.weight"],
        "embed_ln_scale": sd[pfx + "word_embeddings_layernorm.weight"],
        "embed_ln_bias": sd[pfx + "word_embeddings_layernorm.bias"],
        "layers": {
            "ln1_scale": _stack(sd, lay + "input_layernorm.weight", L),
            "ln1_bias": _stack(sd, lay + "input_layernorm.bias", L),
            "wq": np.stack([w.T for w in wq]),
            "wk": np.stack([w.T for w in wk]),
            "wv": np.stack([w.T for w in wv]),
            "bq": np.stack(bq), "bk": np.stack(bk), "bv": np.stack(bv),
            "wo": _stack(sd, lay + "self_attention.dense.weight", L,
                         transpose=True),
            "bo": _stack(sd, lay + "self_attention.dense.bias", L),
            "ln2_scale": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "ln2_bias": _stack(sd, lay + "post_attention_layernorm.bias", L),
            "w_up": _stack(sd, lay + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "b_up": _stack(sd, lay + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, lay + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
            "b_down": _stack(sd, lay + "mlp.dense_4h_to_h.bias", L),
        },
        "final_ln_scale": sd[pfx + "ln_f.weight"],
        "final_ln_bias": sd[pfx + "ln_f.bias"],
    }
    log_dist(f"imported HF bloom weights: {L} layers (alibi heads={nh})")
    return params




def bert_config_from_hf(hf_config) -> "Any":
    from .bert import BertConfig

    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        max_seq_len=hf_config.max_position_embeddings,
        type_vocab_size=getattr(hf_config, "type_vocab_size", 2),
        layer_norm_eps=float(getattr(hf_config, "layer_norm_eps", 1e-12)),
        gelu_approx=getattr(hf_config, "hidden_act", "gelu") in
        ("gelu_new", "gelu_pytorch_tanh", "gelu_fast"),
    )


def bert_params_from_hf(src, cfg=None) -> Params:
    """HF BertModel / BertFor* → ``models/bert`` pytree (q/k/v fused into
    one [h, 3h] block column-wise; the MLM head stays the tied embedding)."""
    sd = _normalize_state_dict(src)
    pfx = "bert." if any(k.startswith("bert.") for k in sd) else ""
    L = cfg.num_layers
    lay = pfx + "encoder.layer.{i}."

    def qkv_w(i):
        return np.concatenate(
            [sd[lay.format(i=i) + f"attention.self.{n}.weight"].T
             for n in ("query", "key", "value")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [sd[lay.format(i=i) + f"attention.self.{n}.bias"]
             for n in ("query", "key", "value")])

    emb = pfx + "embeddings."
    params: Params = {
        "embed": sd[emb + "word_embeddings.weight"],
        "pos_embed": sd[emb + "position_embeddings.weight"],
        "type_embed": sd[emb + "token_type_embeddings.weight"],
        "embed_ln_scale": sd[emb + "LayerNorm.weight"],
        "embed_ln_bias": sd[emb + "LayerNorm.bias"],
        "layers": {
            "wqkv": np.stack([qkv_w(i) for i in range(L)]),
            "bqkv": np.stack([qkv_b(i) for i in range(L)]),
            "wo": _stack(sd, lay + "attention.output.dense.weight", L,
                         transpose=True),
            "bo": _stack(sd, lay + "attention.output.dense.bias", L),
            "attn_ln_scale": _stack(sd, lay + "attention.output.LayerNorm.weight", L),
            "attn_ln_bias": _stack(sd, lay + "attention.output.LayerNorm.bias", L),
            "w_up": _stack(sd, lay + "intermediate.dense.weight", L,
                           transpose=True),
            "b_up": _stack(sd, lay + "intermediate.dense.bias", L),
            "w_down": _stack(sd, lay + "output.dense.weight", L,
                             transpose=True),
            "b_down": _stack(sd, lay + "output.dense.bias", L),
            "mlp_ln_scale": _stack(sd, lay + "output.LayerNorm.weight", L),
            "mlp_ln_bias": _stack(sd, lay + "output.LayerNorm.bias", L),
        },
    }
    h = cfg.hidden_size
    if pfx + "pooler.dense.weight" in sd:
        params["pooler_w"] = sd[pfx + "pooler.dense.weight"].T
        params["pooler_b"] = sd[pfx + "pooler.dense.bias"]
    else:
        params["pooler_w"] = np.zeros((h, h), np.float32)
        params["pooler_b"] = np.zeros((h,), np.float32)
    log_dist(f"imported HF bert weights: {L} layers")
    return params


def distilbert_config_from_hf(hf_config) -> "Any":
    from .bert import BertConfig

    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.dim,
        intermediate_size=hf_config.hidden_dim,
        num_layers=hf_config.n_layers,
        num_heads=hf_config.n_heads,
        max_seq_len=hf_config.max_position_embeddings,
        type_vocab_size=1,   # DistilBERT drops token-type embeddings
        layer_norm_eps=1e-12,
        gelu_approx=getattr(hf_config, "activation", "gelu") in
        ("gelu_new", "gelu_pytorch_tanh", "gelu_fast"),
    )


def distilbert_params_from_hf(src, cfg=None) -> Params:
    """HF DistilBertModel / DistilBertFor* → ``models/bert`` pytree
    (reference policy ``module_inject/containers/distil_bert.py``). The
    missing token-type table becomes a zero row; the missing pooler becomes
    zeros (pooled output is then a constant — DistilBERT has none)."""
    sd = _normalize_state_dict(src)
    pfx = "distilbert." if any(k.startswith("distilbert.") for k in sd) else ""
    L, h = cfg.num_layers, cfg.hidden_size
    lay = pfx + "transformer.layer.{i}."

    def qkv_w(i):
        return np.concatenate(
            [sd[lay.format(i=i) + f"attention.{n}.weight"].T
             for n in ("q_lin", "k_lin", "v_lin")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [sd[lay.format(i=i) + f"attention.{n}.bias"]
             for n in ("q_lin", "k_lin", "v_lin")])

    emb = pfx + "embeddings."
    params: Params = {
        "embed": sd[emb + "word_embeddings.weight"],
        "pos_embed": sd[emb + "position_embeddings.weight"],
        "type_embed": np.zeros((1, h), np.float32),
        "embed_ln_scale": sd[emb + "LayerNorm.weight"],
        "embed_ln_bias": sd[emb + "LayerNorm.bias"],
        "layers": {
            "wqkv": np.stack([qkv_w(i) for i in range(L)]),
            "bqkv": np.stack([qkv_b(i) for i in range(L)]),
            "wo": _stack(sd, lay + "attention.out_lin.weight", L,
                         transpose=True),
            "bo": _stack(sd, lay + "attention.out_lin.bias", L),
            "attn_ln_scale": _stack(sd, lay + "sa_layer_norm.weight", L),
            "attn_ln_bias": _stack(sd, lay + "sa_layer_norm.bias", L),
            "w_up": _stack(sd, lay + "ffn.lin1.weight", L, transpose=True),
            "b_up": _stack(sd, lay + "ffn.lin1.bias", L),
            "w_down": _stack(sd, lay + "ffn.lin2.weight", L, transpose=True),
            "b_down": _stack(sd, lay + "ffn.lin2.bias", L),
            "mlp_ln_scale": _stack(sd, lay + "output_layer_norm.weight", L),
            "mlp_ln_bias": _stack(sd, lay + "output_layer_norm.bias", L),
        },
        "pooler_w": np.zeros((h, h), np.float32),
        "pooler_b": np.zeros((h,), np.float32),
    }
    log_dist(f"imported HF distilbert weights: {L} layers")
    return params


def megatron_gpt_params_from_sd(sd, cfg=None, ckpt_ver=None) -> Params:
    """Megatron-GPT state dict (merged to TP=1 via ``SDLoaderFactory``) →
    ``models/gpt`` pytree (reference policy
    ``module_inject/containers/megatron_gpt.py`` + ``MegatronSDLoader``).

    The fused query_key_value layouts by checkpoint version (reference
    ``state_dict_factory.py:220``): v0 = whole-tensor [q;k;v] blocks (the
    GPT-2 layout our model uses directly); v2 = per-head [q;k;v] groups,
    de-interleaved here. v1.0's (np·hn·3) ordering is rejected."""
    if ckpt_ver is None:
        # read the version BEFORE unwrapping 'module' (it lives at the top
        # level of Megatron checkpoints); default 0 matches
        # SDLoaderBase.get_checkpoint_version — defaulting to 2 would
        # silently scramble v0 whole-block QKV tensors as per-head groups
        ckpt_ver = sd.get("checkpoint_version",
                          sd.get("module", {}).get("checkpoint_version", 0))
    sd = {k: _to_numpy(v) for k, v in (sd.get("module", sd)).items()
          if k != "checkpoint_version"}
    # strip megatron prefixes down to the transformer block names
    def find(suffix):
        hits = [k for k in sd if k.endswith(suffix)]
        if len(hits) != 1:
            raise KeyError(f"expected exactly one key ending {suffix!r}, "
                           f"got {hits}")
        return sd[hits[0]]

    L = _count_indices(sd, r".*?layers\.(\d+)\.")
    nh, hd = (cfg.num_heads, cfg.head_size) if cfg is not None else (None, None)

    def layer(i, suffix):
        return find(f"layers.{i}.{suffix}")

    def qkv_to_gpt2(w):
        """[3h(, h)] megatron fused → [q|k|v] blocks (transposed for weights)."""
        if ckpt_ver in (0, 0.0):
            out = w  # already [q;k;v] whole blocks
        elif ckpt_ver in (2, 2.0):
            assert nh is not None, "cfg (num_heads) required for v2 layout"
            grouped = w.reshape((nh, 3, hd) + w.shape[1:])
            out = np.concatenate(
                [grouped[:, j].reshape((nh * hd,) + w.shape[1:])
                 for j in range(3)], axis=0)
        else:
            raise ValueError(f"unsupported megatron checkpoint_version "
                             f"{ckpt_ver} (v0 and v2 layouts supported)")
        return out.T if out.ndim == 2 else out

    params: Params = {
        "embed": find("word_embeddings.weight"),
        "pos_embed": find("position_embeddings.weight"),
        "layers": {
            "ln1_scale": np.stack([layer(i, "input_layernorm.weight")
                                   for i in range(L)]),
            "ln1_bias": np.stack([layer(i, "input_layernorm.bias")
                                  for i in range(L)]),
            "wqkv": np.stack([qkv_to_gpt2(
                layer(i, "attention.query_key_value.weight"))
                for i in range(L)]),
            "bqkv": np.stack([qkv_to_gpt2(
                layer(i, "attention.query_key_value.bias"))
                for i in range(L)]),
            "wo": np.stack([layer(i, "attention.dense.weight").T
                            for i in range(L)]),
            "bo": np.stack([layer(i, "attention.dense.bias")
                            for i in range(L)]),
            "ln2_scale": np.stack([layer(i, "post_attention_layernorm.weight")
                                   for i in range(L)]),
            "ln2_bias": np.stack([layer(i, "post_attention_layernorm.bias")
                                  for i in range(L)]),
            "w_up": np.stack([layer(i, "mlp.dense_h_to_4h.weight").T
                              for i in range(L)]),
            "b_up": np.stack([layer(i, "mlp.dense_h_to_4h.bias")
                              for i in range(L)]),
            "w_down": np.stack([layer(i, "mlp.dense_4h_to_h.weight").T
                                for i in range(L)]),
            "b_down": np.stack([layer(i, "mlp.dense_4h_to_h.bias")
                                for i in range(L)]),
        },
        "final_ln_scale": find("final_layernorm.weight"),
        "final_ln_bias": find("final_layernorm.bias"),
    }
    log_dist(f"imported megatron-gpt weights: {L} layers "
             f"(ckpt_ver={ckpt_ver})")
    return params


def clip_config_from_hf(hf_config) -> "Any":
    from .clip import CLIPConfig, CLIPTowerConfig

    t, v = hf_config.text_config, hf_config.vision_config
    return CLIPConfig(
        vocab_size=t.vocab_size,
        max_seq_len=t.max_position_embeddings,
        eos_token_id=t.eos_token_id,
        text=CLIPTowerConfig(hidden_size=t.hidden_size,
                             intermediate_size=t.intermediate_size,
                             num_layers=t.num_hidden_layers,
                             num_heads=t.num_attention_heads,
                             layer_norm_eps=float(t.layer_norm_eps),
                             hidden_act=getattr(t, "hidden_act",
                                                "quick_gelu")),
        image_size=v.image_size,
        patch_size=v.patch_size,
        num_channels=getattr(v, "num_channels", 3),
        vision=CLIPTowerConfig(hidden_size=v.hidden_size,
                               intermediate_size=v.intermediate_size,
                               num_layers=v.num_hidden_layers,
                               num_heads=v.num_attention_heads,
                               layer_norm_eps=float(v.layer_norm_eps),
                               hidden_act=getattr(v, "hidden_act",
                                                  "quick_gelu")),
        projection_dim=hf_config.projection_dim,
    )


def _clip_tower_from_hf(sd, prefix: str, L: int) -> Params:
    lay = prefix + "encoder.layers.{i}."
    return {
        "ln1_scale": _stack(sd, lay + "layer_norm1.weight", L),
        "ln1_bias": _stack(sd, lay + "layer_norm1.bias", L),
        "wq": _stack(sd, lay + "self_attn.q_proj.weight", L, transpose=True),
        "bq": _stack(sd, lay + "self_attn.q_proj.bias", L),
        "wk": _stack(sd, lay + "self_attn.k_proj.weight", L, transpose=True),
        "bk": _stack(sd, lay + "self_attn.k_proj.bias", L),
        "wv": _stack(sd, lay + "self_attn.v_proj.weight", L, transpose=True),
        "bv": _stack(sd, lay + "self_attn.v_proj.bias", L),
        "wo": _stack(sd, lay + "self_attn.out_proj.weight", L, transpose=True),
        "bo": _stack(sd, lay + "self_attn.out_proj.bias", L),
        "ln2_scale": _stack(sd, lay + "layer_norm2.weight", L),
        "ln2_bias": _stack(sd, lay + "layer_norm2.bias", L),
        "w_up": _stack(sd, lay + "mlp.fc1.weight", L, transpose=True),
        "b_up": _stack(sd, lay + "mlp.fc1.bias", L),
        "w_down": _stack(sd, lay + "mlp.fc2.weight", L, transpose=True),
        "b_down": _stack(sd, lay + "mlp.fc2.bias", L),
    }


def clip_params_from_hf(src, cfg=None) -> Params:
    """HF CLIPModel → ``models/clip`` pytree. The vision conv patch embed
    (out, c, p, p) flattens to the unfold+matmul layout [c·p·p, out]."""
    if cfg is None:
        if not hasattr(src, "config"):
            raise ValueError("clip_params_from_hf needs cfg= when given a "
                             "bare state dict (no .config to derive it from)")
        cfg = clip_config_from_hf(src.config)
    sd = _normalize_state_dict(src)
    h_v = cfg.vision.hidden_size
    params: Params = {
        "text": {
            "embed": sd["text_model.embeddings.token_embedding.weight"],
            "pos_embed": sd["text_model.embeddings.position_embedding.weight"],
            "layers": _clip_tower_from_hf(sd, "text_model.",
                                          cfg.text.num_layers),
            "final_ln_scale": sd["text_model.final_layer_norm.weight"],
            "final_ln_bias": sd["text_model.final_layer_norm.bias"],
        },
        "vision": {
            "class_embed": sd["vision_model.embeddings.class_embedding"],
            "patch_embed": sd["vision_model.embeddings.patch_embedding.weight"]
            .reshape(h_v, -1).T,
            "pos_embed": sd["vision_model.embeddings.position_embedding.weight"],
            "pre_ln_scale": sd["vision_model.pre_layrnorm.weight"],
            "pre_ln_bias": sd["vision_model.pre_layrnorm.bias"],
            "layers": _clip_tower_from_hf(sd, "vision_model.",
                                          cfg.vision.num_layers),
            "post_ln_scale": sd["vision_model.post_layernorm.weight"],
            "post_ln_bias": sd["vision_model.post_layernorm.bias"],
        },
        "text_projection": sd["text_projection.weight"].T,
        "visual_projection": sd["visual_projection.weight"].T,
        "logit_scale": sd["logit_scale"],
    }
    log_dist(f"imported HF clip weights: text {cfg.text.num_layers}L / "
             f"vision {cfg.vision.num_layers}L")
    return params


def exaone4_config_from_hf(hf_config) -> "Any":
    from .exaone4 import Exaone4Config

    if getattr(hf_config, "rope_scaling", None):
        # same hazard as the llama guard: silently applying plain RoPE to a
        # scaled-rope checkpoint gives wrong logits everywhere
        raise ValueError(
            "rope_scaling checkpoints are not supported yet — import the "
            "base (non-scaled) EXAONE-4 variant")
    return Exaone4Config(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim", None),
        max_seq_len=hf_config.max_position_embeddings,
        sliding_window=getattr(hf_config, "sliding_window", None),
        sliding_window_pattern=getattr(hf_config, "sliding_window_pattern",
                                       4) or 4,
        rope_theta=float(getattr(hf_config, "rope_theta", 1000000.0)),
        rms_norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        layer_types=tuple(hf_config.layer_types)
        if getattr(hf_config, "layer_types", None) else None,
    )


def exaone4_params_from_hf(src, cfg=None) -> Params:
    """HF Exaone4ForCausalLM → ``models/exaone4`` pytree (post-norm +
    QK-norm + hybrid attention)."""
    sd = _normalize_state_dict(src)
    L = cfg.num_layers
    lay = "model.layers.{i}."
    params: Params = {
        "embed": sd["model.embed_tokens.weight"],
        "layers": {
            "wq": _stack(sd, lay + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, lay + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, lay + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L,
                         transpose=True),
            "q_norm": _stack(sd, lay + "self_attn.q_norm.weight", L),
            "k_norm": _stack(sd, lay + "self_attn.k_norm.weight", L),
            "post_attn_norm": _stack(
                sd, lay + "post_attention_layernorm.weight", L),
            "w_gate": _stack(sd, lay + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, lay + "mlp.up_proj.weight", L, transpose=True),
            "w_down": _stack(sd, lay + "mlp.down_proj.weight", L,
                             transpose=True),
            "post_mlp_norm": _stack(
                sd, lay + "post_feedforward_layernorm.weight", L),
        },
        "final_norm": sd["model.norm.weight"],
    }
    if "lm_head.weight" in sd and not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"].T
    log_dist(f"imported HF exaone4 weights: {L} layers "
             f"(types={cfg.resolved_layer_types()[:4]}...)")
    return params


def resolve_module(family: str):
    """Family name → the ``deepspeed_tpu.models`` module that executes it."""
    from . import bloom, falcon, gpt, gptneox, llama, mixtral

    from . import bert as bert_mod
    from . import clip as clip_mod
    from . import exaone4 as exaone4_mod

    modules = {
        "llama": llama, "mistral": llama, "qwen2": llama, "qwen3": llama,
        "phi3": llama,
        "gpt2": gpt, "opt": gpt,
        "mixtral": mixtral, "qwen2_moe": mixtral,
        "falcon": falcon,
        "gpt_neox": gptneox, "gptj": gptneox,
        "bloom": bloom,
        "bert": bert_mod, "distilbert": bert_mod,
        "clip": clip_mod,
        "exaone4": exaone4_mod,
    }
    if family not in modules:
        raise ValueError(f"unsupported HF family '{family}' "
                         f"(supported: {sorted(modules)})")
    return modules[family]


def is_hf_model(model) -> bool:
    """True for a live transformers/torch model (as opposed to a ModelSpec
    or one of our model modules)."""
    return (hasattr(model, "state_dict") and callable(model.state_dict)
            and hasattr(model, "config")
            and hasattr(model.config, "model_type"))


def spec_from_hf(model, family: Optional[str] = None,
                 compute_dtype=None):
    """Live transformers model → a ``ModelSpec`` carrying the imported
    weights — makes ``deepspeed_tpu.initialize(model=hf_model, ...)`` work
    exactly like the reference's ``deepspeed.initialize(model=hf_model)``
    (engine selection ``deepspeed/__init__.py:198-241``)."""
    import dataclasses

    import jax.numpy as jnp

    family = family or getattr(model.config, "model_type", None)
    module = resolve_module(family)
    cfg, params = from_hf(model, family)
    spec = module.model_spec(
        cfg, compute_dtype=compute_dtype or jnp.bfloat16)
    return dataclasses.replace(spec, params=params)


_FAMILIES = {
    "llama": (llama_config_from_hf, llama_params_from_hf),
    "mistral": (llama_config_from_hf, llama_params_from_hf),
    "qwen2": (llama_config_from_hf, llama_params_from_hf),
    "qwen3": (llama_config_from_hf, llama_params_from_hf),
    "phi3": (llama_config_from_hf, phi3_params_from_hf),
    "gpt2": (gpt2_config_from_hf, gpt2_params_from_hf),
    "opt": (opt_config_from_hf, opt_params_from_hf),
    "mixtral": (mixtral_config_from_hf, mixtral_params_from_hf),
    "qwen2_moe": (qwen2_moe_config_from_hf, qwen2_moe_params_from_hf),
    "falcon": (falcon_config_from_hf, falcon_params_from_hf),
    "gpt_neox": (gptneox_config_from_hf, gptneox_params_from_hf),
    "gptj": (gptj_config_from_hf, gptj_params_from_hf),
    "bloom": (bloom_config_from_hf, bloom_params_from_hf),
    "bert": (bert_config_from_hf, bert_params_from_hf),
    "distilbert": (distilbert_config_from_hf, distilbert_params_from_hf),
    "clip": (clip_config_from_hf, clip_params_from_hf),
    "exaone4": (exaone4_config_from_hf, exaone4_params_from_hf),
}


def from_hf(model, family: Optional[str] = None):
    """One-stop conversion: (our_config, our_params) from a transformers
    model instance. Family is sniffed from ``model.config.model_type``."""
    family = family or getattr(model.config, "model_type", None)
    if family not in _FAMILIES:
        raise ValueError(f"unsupported HF family '{family}' "
                         f"(supported: {sorted(_FAMILIES)})")
    cfg_fn, params_fn = _FAMILIES[family]
    cfg = cfg_fn(model.config)
    return cfg, params_fn(model, cfg)


def load_hf_checkpoint_with_family(path: str,
                                   family: Optional[str] = None):
    """Load a LOCAL HF checkpoint directory (no network) → (family_name,
    our_config, our_params). Causal-LM head classes are tried first; encoder
    and contrastive families (bert/distilbert/clip) fall back to the base
    AutoModel class."""
    import transformers

    try:
        model = transformers.AutoModelForCausalLM.from_pretrained(
            path, local_files_only=True, torch_dtype="float32")
    except ValueError:
        model = transformers.AutoModel.from_pretrained(
            path, local_files_only=True, torch_dtype="float32")
    family = family or model.config.model_type
    cfg, params = from_hf(model, family)
    return family, cfg, params


def load_hf_checkpoint(path: str, family: Optional[str] = None):
    """Load a LOCAL HF checkpoint directory (no network) and convert."""
    _, cfg, params = load_hf_checkpoint_with_family(path, family)
    return cfg, params


def load_checkpoint_dir_module(path: str):
    """Checkpoint directory → (family_name, model_module, our_config,
    our_params) — the shared resolution step behind
    ``init_inference(checkpoint=)`` and the v2 ``build_hf_engine``; callers
    gate on the module capability they need (``apply_cached`` for v1 decode,
    ``apply_paged`` for the paged v2 path). The family name is kept separate
    from the module name for error messages (aliases: distilbert → bert)."""
    fam_name, cfg, params = load_hf_checkpoint_with_family(path)
    return fam_name, resolve_module(fam_name), cfg, params
