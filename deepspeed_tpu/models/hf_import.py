"""HF checkpoint import: transformers state dicts → stacked param pytrees.

Reference parity: the reference consumes HF models directly (``deepspeed.
initialize(model=hf_model)``, ``init_inference`` checkpoint loading
``inference/engine.py:303-471``) and reshards TP-degree-changing checkpoints
via ``SDLoaderFactory``/``MegatronSDLoader`` (``runtime/state_dict_factory.py:
21,190``). Here a user brings HF weights to the TPU framework by converting
once into the stacked [L, ...] pytree layout; resharding to any topology is
then the checkpoint layer's job (orbax/universal).

Supported families: Llama/Mistral/Qwen2-dense (→ ``models/llama``), GPT-2
(→ ``models/gpt``). Accepts a live ``transformers`` model, a state-dict
mapping, or a local checkpoint directory (no network access is assumed).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Optional

import numpy as np

from ..utils.logging import log_dist

Params = Dict[str, Any]


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:  # torch tensor
        return t.detach().cpu().float().numpy()
    except AttributeError:
        return np.asarray(t)


def _normalize_state_dict(src) -> Dict[str, np.ndarray]:
    """Accept a transformers model, an nn.Module, or a mapping."""
    if hasattr(src, "state_dict") and callable(src.state_dict):
        src = src.state_dict()
    if not isinstance(src, Mapping):
        raise TypeError(f"cannot read weights from {type(src)}")
    return {k: _to_numpy(v) for k, v in src.items()}


def _stack(sd: Dict[str, np.ndarray], pattern: str, num_layers: int,
           transpose: bool = False) -> np.ndarray:
    """Collect per-layer tensors 'prefix.{i}.suffix' into one [L, ...] array."""
    mats = []
    for i in range(num_layers):
        key = pattern.format(i=i)
        if key not in sd:
            raise KeyError(f"missing weight {key}")
        m = sd[key]
        mats.append(m.T if transpose else m)
    return np.stack(mats)


def llama_config_from_hf(hf_config) -> "Any":
    """Map a transformers LlamaConfig/MistralConfig/Qwen2Config."""
    from .llama import LlamaConfig

    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rms_norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        # Qwen2 always uses QKV biases; Llama exposes an attention_bias flag
        attention_bias=bool(getattr(hf_config, "attention_bias",
                                    hf_config.model_type == "qwen2")),
    )


def llama_params_from_hf(src, cfg=None) -> Params:
    """HF LlamaForCausalLM (or compatible) weights → ``models/llama`` pytree.
    HF nn.Linear stores [out, in]; our layout is [in, out] → transpose."""
    sd = _normalize_state_dict(src)
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        1 + max(int(m.group(1)) for k in sd
                if (m := re.match(rf"{re.escape(pfx)}layers\.(\d+)\.", k)))
    lay = pfx + "layers.{i}."
    params: Params = {
        "embed": sd[pfx + "embed_tokens.weight"],
        "layers": {
            "attn_norm": _stack(sd, lay + "input_layernorm.weight", L),
            "wq": _stack(sd, lay + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, lay + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, lay + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, lay + "self_attn.o_proj.weight", L, transpose=True),
            "mlp_norm": _stack(sd, lay + "post_attention_layernorm.weight", L),
            "w_gate": _stack(sd, lay + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, lay + "mlp.up_proj.weight", L, transpose=True),
            "w_down": _stack(sd, lay + "mlp.down_proj.weight", L, transpose=True),
        },
        "final_norm": sd[pfx + "norm.weight"],
    }
    if "lm_head.weight" in sd:
        params["lm_head"] = sd["lm_head.weight"].T
    has_bias = (lay.format(i=0) + "self_attn.q_proj.bias") in sd
    if has_bias:
        # Qwen2 QKV biases (ADVICE r1: these were silently dropped)
        params["layers"]["bq"] = _stack(sd, lay + "self_attn.q_proj.bias", L)
        params["layers"]["bk"] = _stack(sd, lay + "self_attn.k_proj.bias", L)
        params["layers"]["bv"] = _stack(sd, lay + "self_attn.v_proj.bias", L)
    if cfg is not None and bool(getattr(cfg, "attention_bias", False)) != has_bias:
        raise ValueError(
            f"attention_bias={getattr(cfg, 'attention_bias', False)} but "
            f"checkpoint {'has' if has_bias else 'lacks'} q_proj.bias tensors")
    log_dist(f"imported HF llama-family weights: {L} layers, "
             f"vocab {params['embed'].shape[0]}, qkv_bias={has_bias}")
    return params


def gpt2_config_from_hf(hf_config) -> "Any":
    from .gpt import GPTConfig

    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        intermediate_size=getattr(hf_config, "n_inner", None) or 4 * hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        layer_norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        tie_embeddings=True,
    )


def gpt2_params_from_hf(src, cfg=None) -> Params:
    """HF GPT2LMHeadModel weights → ``models/gpt`` pytree. GPT-2 Conv1D
    already stores [in, out] — no transpose."""
    sd = _normalize_state_dict(src)
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    L = cfg.num_layers if cfg is not None else \
        1 + max(int(m.group(1)) for k in sd
                if (m := re.match(rf"{re.escape(pfx)}h\.(\d+)\.", k)))
    lay = pfx + "h.{i}."
    params: Params = {
        "embed": sd[pfx + "wte.weight"],
        "pos_embed": sd[pfx + "wpe.weight"],
        "layers": {
            "ln1_scale": _stack(sd, lay + "ln_1.weight", L),
            "ln1_bias": _stack(sd, lay + "ln_1.bias", L),
            "wqkv": _stack(sd, lay + "attn.c_attn.weight", L),
            "bqkv": _stack(sd, lay + "attn.c_attn.bias", L),
            "wo": _stack(sd, lay + "attn.c_proj.weight", L),
            "bo": _stack(sd, lay + "attn.c_proj.bias", L),
            "ln2_scale": _stack(sd, lay + "ln_2.weight", L),
            "ln2_bias": _stack(sd, lay + "ln_2.bias", L),
            "w_up": _stack(sd, lay + "mlp.c_fc.weight", L),
            "b_up": _stack(sd, lay + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, lay + "mlp.c_proj.weight", L),
            "b_down": _stack(sd, lay + "mlp.c_proj.bias", L),
        },
        "final_ln_scale": sd[pfx + "ln_f.weight"],
        "final_ln_bias": sd[pfx + "ln_f.bias"],
    }
    log_dist(f"imported HF gpt2-family weights: {L} layers")
    return params


_FAMILIES = {
    "llama": (llama_config_from_hf, llama_params_from_hf),
    "mistral": (llama_config_from_hf, llama_params_from_hf),
    "qwen2": (llama_config_from_hf, llama_params_from_hf),
    "gpt2": (gpt2_config_from_hf, gpt2_params_from_hf),
}


def from_hf(model, family: Optional[str] = None):
    """One-stop conversion: (our_config, our_params) from a transformers
    model instance. Family is sniffed from ``model.config.model_type``."""
    family = family or getattr(model.config, "model_type", None)
    if family not in _FAMILIES:
        raise ValueError(f"unsupported HF family '{family}' "
                         f"(supported: {sorted(_FAMILIES)})")
    cfg_fn, params_fn = _FAMILIES[family]
    cfg = cfg_fn(model.config)
    return cfg, params_fn(model, cfg)


def load_hf_checkpoint(path: str, family: Optional[str] = None):
    """Load a LOCAL HF checkpoint directory (no network) and convert."""
    import transformers

    model = transformers.AutoModelForCausalLM.from_pretrained(
        path, local_files_only=True, torch_dtype="float32")
    return from_hf(model, family)
