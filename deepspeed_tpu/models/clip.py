"""CLIP (contrastive text–image), written TPU-first.

Reference parity: the reference serves CLIP through a v1 injection policy
(``module_inject/containers/clip.py``) as part of its stable-diffusion
stack. Here CLIP is a first-class family: both towers are pre-LN ViT-style
encoders (quick-gelu MLPs) sharing one block implementation — the vision
tower embeds image patches with an MXU-friendly unfold+matmul instead of a
conv — plus the contrastive head (projections + learned logit scale).

Same TPU shape as the sibling models: stacked layers under ``lax.scan``,
logical axis names per param for the sharding-rule engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class CLIPTowerConfig:
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_layers: int = 12
    num_heads: int = 8
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"   # OpenAI CLIP; LAION/OpenCLIP use 'gelu'

    def __post_init__(self):
        if self.hidden_act not in ("quick_gelu", "gelu"):
            raise ValueError(f"unsupported CLIP activation "
                             f"{self.hidden_act!r} (quick_gelu | gelu)")

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads


@dataclass(frozen=True)
class CLIPConfig:
    # text tower
    vocab_size: int = 49408
    max_seq_len: int = 77
    eos_token_id: int = 49407
    text: CLIPTowerConfig = CLIPTowerConfig()
    # vision tower
    image_size: int = 224
    patch_size: int = 32
    num_channels: int = 3
    vision: CLIPTowerConfig = CLIPTowerConfig(hidden_size=768,
                                              intermediate_size=3072,
                                              num_heads=12)
    projection_dim: int = 512

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def tiny(cls, **kw) -> "CLIPConfig":
        base = dict(
            vocab_size=64, max_seq_len=16, eos_token_id=63,
            text=CLIPTowerConfig(hidden_size=32, intermediate_size=64,
                                 num_layers=2, num_heads=2),
            image_size=32, patch_size=8,
            vision=CLIPTowerConfig(hidden_size=32, intermediate_size=64,
                                   num_layers=2, num_heads=2),
            projection_dim=24)
        base.update(kw)
        return cls(**base)


def _act(tcfg: CLIPTowerConfig, x):
    if tcfg.hidden_act == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x, approximate=False)


def _tower_init(cfg: CLIPTowerConfig, rng, dtype) -> Params:
    h, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    ks = jax.random.split(rng, 6)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    return {
        "ln1_scale": jnp.ones((L, h), dtype), "ln1_bias": jnp.zeros((L, h), dtype),
        "wq": normal(ks[0], (L, h, h), h), "bq": jnp.zeros((L, h), dtype),
        "wk": normal(ks[1], (L, h, h), h), "bk": jnp.zeros((L, h), dtype),
        "wv": normal(ks[2], (L, h, h), h), "bv": jnp.zeros((L, h), dtype),
        "wo": normal(ks[3], (L, h, h), h), "bo": jnp.zeros((L, h), dtype),
        "ln2_scale": jnp.ones((L, h), dtype), "ln2_bias": jnp.zeros((L, h), dtype),
        "w_up": normal(ks[4], (L, h, i), h), "b_up": jnp.zeros((L, i), dtype),
        "w_down": normal(ks[5], (L, i, h), i), "b_down": jnp.zeros((L, h), dtype),
    }


def _tower_axes(cfg: CLIPTowerConfig) -> Params:
    return {
        "ln1_scale": ("layers", "embed"), "ln1_bias": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"), "bq": ("layers", "heads"),
        "wk": ("layers", "embed", "heads"), "bk": ("layers", "heads"),
        "wv": ("layers", "embed", "heads"), "bv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
        "ln2_scale": ("layers", "embed"), "ln2_bias": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"), "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"), "b_down": ("layers", "embed"),
    }


def init(cfg: CLIPConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    kt, kv, kp = jax.random.split(rng, 3)
    h_t, h_v, p = cfg.text.hidden_size, cfg.vision.hidden_size, cfg.projection_dim
    patch_dim = cfg.num_channels * cfg.patch_size ** 2

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    ks = jax.random.split(kp, 5)
    return {
        "text": {
            "embed": normal(kt, (cfg.vocab_size, h_t), h_t),
            "pos_embed": normal(ks[0], (cfg.max_seq_len, h_t), h_t),
            "layers": _tower_init(cfg.text, jax.random.fold_in(kt, 1), dtype),
            "final_ln_scale": jnp.ones((h_t,), dtype),
            "final_ln_bias": jnp.zeros((h_t,), dtype),
        },
        "vision": {
            "class_embed": jnp.zeros((h_v,), dtype),
            "patch_embed": normal(kv, (patch_dim, h_v), patch_dim),
            "pos_embed": normal(ks[1], (cfg.num_patches + 1, h_v), h_v),
            "pre_ln_scale": jnp.ones((h_v,), dtype),
            "pre_ln_bias": jnp.zeros((h_v,), dtype),
            "layers": _tower_init(cfg.vision, jax.random.fold_in(kv, 1), dtype),
            "post_ln_scale": jnp.ones((h_v,), dtype),
            "post_ln_bias": jnp.zeros((h_v,), dtype),
        },
        "text_projection": normal(ks[2], (h_t, p), h_t),
        "visual_projection": normal(ks[3], (h_v, p), h_v),
        "logit_scale": jnp.asarray(2.6592, dtype),  # ln(1/0.07), HF init
    }


def param_logical_axes(cfg: CLIPConfig) -> Params:
    return {
        "text": {
            "embed": ("vocab", "embed"), "pos_embed": (None, "embed"),
            "layers": _tower_axes(cfg.text),
            "final_ln_scale": ("embed",), "final_ln_bias": ("embed",),
        },
        "vision": {
            "class_embed": ("embed",),
            "patch_embed": (None, "embed"),
            "pos_embed": (None, "embed"),
            "pre_ln_scale": ("embed",), "pre_ln_bias": ("embed",),
            "layers": _tower_axes(cfg.vision),
            "post_ln_scale": ("embed",), "post_ln_bias": ("embed",),
        },
        "text_projection": ("embed", None),
        "visual_projection": ("embed", None),
        "logit_scale": (),
    }


def _block(tcfg: CLIPTowerConfig, x: jnp.ndarray, layer: Params,
           causal: bool) -> jnp.ndarray:
    b, s, h = x.shape
    nh, hd = tcfg.num_heads, tcfg.head_size
    eps = tcfg.layer_norm_eps
    y = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"], eps)
    q = (y @ layer["wq"] + layer["bq"]).reshape(b, s, nh, hd)
    k = (y @ layer["wk"] + layer["bk"]).reshape(b, s, nh, hd)
    v = (y @ layer["wv"] + layer["bv"]).reshape(b, s, nh, hd)
    a = attention(q, k, v, causal=causal)
    x = x + a.reshape(b, s, h) @ layer["wo"] + layer["bo"]
    y = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"], eps)
    return x + _act(tcfg, y @ layer["w_up"] + layer["b_up"]) @ layer["w_down"] \
        + layer["b_down"]


def _run_tower(tcfg: CLIPTowerConfig, layers: Params, x: jnp.ndarray,
               causal: bool) -> jnp.ndarray:
    def body(x, layer):
        return _block(tcfg, x, layer, causal), None

    x, _ = lax.scan(body, x, layers)
    return x


from ..utils.tree import cast_floating as _cast  # noqa: E402


def encode_text(cfg: CLIPConfig, params: Params, tokens: jnp.ndarray, *,
                compute_dtype=jnp.float32, project: bool = True) -> jnp.ndarray:
    """tokens [b, s] → pooled text features [b, proj] (EOS-position pooling,
    HF CLIPTextModel semantics)."""
    tp = _cast(params["text"], compute_dtype)
    s = tokens.shape[1]
    x = embedding_lookup(tp["embed"], tokens, compute_dtype) \
        + tp["pos_embed"][:s][None]
    x = _run_tower(cfg.text, tp["layers"], x, causal=True)
    x = layer_norm(x, tp["final_ln_scale"], tp["final_ln_bias"],
                   cfg.text.layer_norm_eps)
    if cfg.eos_token_id == 2:
        # legacy OpenAI checkpoints carry eos_token_id=2 in their configs
        # while the actual EOT token is the vocab max — HF's
        # CLIPTextTransformer keeps this exact special case; without it,
        # pooling would match token 2 (never present) and select position 0
        eos_pos = jnp.argmax(tokens, axis=-1)
    else:
        eos_pos = jnp.argmax((tokens == cfg.eos_token_id).astype(jnp.int32),
                             axis=-1)
    pooled = x[jnp.arange(x.shape[0]), eos_pos]
    if not project:
        return pooled
    return pooled @ params["text_projection"].astype(compute_dtype)


def _patchify(cfg: CLIPConfig, images: jnp.ndarray) -> jnp.ndarray:
    """[b, c, H, W] → [b, num_patches, c*p*p] matching conv-with-stride-p
    weight layout (out_ch, c, p, p) flattened per patch."""
    b, c, H, W = images.shape
    p = cfg.patch_size
    x = images.reshape(b, c, H // p, p, W // p, p)
    x = x.transpose(0, 2, 4, 1, 3, 5)          # [b, gh, gw, c, p, p]
    return x.reshape(b, (H // p) * (W // p), c * p * p)


def encode_image(cfg: CLIPConfig, params: Params, images: jnp.ndarray, *,
                 compute_dtype=jnp.float32, project: bool = True) -> jnp.ndarray:
    """images [b, c, H, W] → pooled image features [b, proj]."""
    vp = _cast(params["vision"], compute_dtype)
    patches = _patchify(cfg, images.astype(compute_dtype)) @ vp["patch_embed"]
    b = patches.shape[0]
    cls = jnp.broadcast_to(vp["class_embed"],
                           (b, 1, cfg.vision.hidden_size))
    x = jnp.concatenate([cls, patches], axis=1) + vp["pos_embed"][None]
    x = layer_norm(x, vp["pre_ln_scale"], vp["pre_ln_bias"],
                   cfg.vision.layer_norm_eps)
    x = _run_tower(cfg.vision, vp["layers"], x, causal=False)
    pooled = layer_norm(x[:, 0], vp["post_ln_scale"], vp["post_ln_bias"],
                        cfg.vision.layer_norm_eps)
    if not project:
        return pooled
    return pooled @ params["visual_projection"].astype(compute_dtype)


def apply(cfg: CLIPConfig, params: Params, tokens: jnp.ndarray,
          images: jnp.ndarray, *,
          compute_dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (logits_per_text [bt, bi], logits_per_image [bi, bt])."""
    t = encode_text(cfg, params, tokens, compute_dtype=compute_dtype)
    v = encode_image(cfg, params, images, compute_dtype=compute_dtype)
    t = t / jnp.linalg.norm(t, axis=-1, keepdims=True)
    v = v / jnp.linalg.norm(v, axis=-1, keepdims=True)
    scale = jnp.exp(params["logit_scale"].astype(compute_dtype))
    logits_per_text = scale * t @ v.T
    return logits_per_text, logits_per_text.T


def loss_fn(cfg: CLIPConfig, params: Params, batch: Dict[str, jnp.ndarray],
            *, compute_dtype=jnp.float32):
    """Symmetric InfoNCE over in-batch pairs (CLIP pretraining loss)."""
    lt, li = apply(cfg, params, batch["tokens"], batch["images"],
                   compute_dtype=compute_dtype)
    n = lt.shape[0]
    labels = jnp.arange(n)
    ce = lambda lg: -jnp.mean(  # noqa: E731
        jnp.take_along_axis(jax.nn.log_softmax(lg, axis=-1),
                            labels[:, None], axis=-1))
    loss = 0.5 * (ce(lt) + ce(li))
    return loss, {"loss": loss}


def model_spec(cfg: CLIPConfig, compute_dtype=jnp.float32):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="clip",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )
