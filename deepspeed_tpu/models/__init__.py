from . import (bert, bloom, clip, diffusion, exaone4, falcon,  # noqa: F401
               gpt, gptneox, llama, mixtral)
