from . import bert, gpt, llama, mixtral  # noqa: F401
