from . import bert, bloom, falcon, gpt, gptneox, llama, mixtral  # noqa: F401
