from . import (bert, bloom, clip, exaone4, falcon, gpt, gptneox,  # noqa: F401
               llama, mixtral)
