"""EXAONE-4 family, written TPU-first.

Reference parity: ``inference/v2/model_implementations`` lists exaone4 as a
served family. Architecture deltas vs llama, all handled here:

- **Post-norm placement**: ``x = x + rms(attn(x)); x = x + rms(mlp(x))`` —
  the RMSNorm wraps the sublayer OUTPUT (no input norms).
- **QK-Norm**: per-head RMSNorm on q/k (as Qwen3).
- **Hybrid attention**: a layer-type pattern mixes sliding-window layers
  (RoPE + windowed causal mask) with global layers (full causal, NoPE — no
  rotary at all). Under ``lax.scan`` the per-layer variation rides two
  scanned scalars: the window size (∞ ≈ max_seq for global) and a
  rope-on/off flag resolved with ``jnp.where`` — compiler-friendly, no
  per-layer Python branching.

Same TPU shape as the sibling models: stacked layers, logical axis names
per param for the sharding-rule engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ._paged import join_kv, paged_attention_step, split_kv
from ._paged import init_paged_pools as _init_paged_pools
from ..ops.embedding import embedding_lookup
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rotary, rope_frequencies

Params = Dict[str, Any]


@dataclass(frozen=True)
class Exaone4Config:
    vocab_size: int = 102400
    hidden_size: int = 4096
    intermediate_size: int = 16384
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    sliding_window: Optional[int] = 4096
    sliding_window_pattern: int = 4   # every Nth layer is global
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    layer_types: Optional[Tuple[str, ...]] = None  # override the pattern

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def resolved_layer_types(self) -> Tuple[str, ...]:
        if self.layer_types is not None:
            return tuple(self.layer_types)
        if self.sliding_window is None:
            return ("full_attention",) * self.num_layers
        # HF pattern: every `pattern`-th layer (1-indexed) is global
        return tuple(
            "full_attention" if (i + 1) % self.sliding_window_pattern == 0
            else "sliding_attention" for i in range(self.num_layers))

    @classmethod
    def tiny(cls, **kw) -> "Exaone4Config":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=4, num_heads=4, num_kv_heads=2,
                    max_seq_len=64, sliding_window=16,
                    sliding_window_pattern=2, rope_theta=10000.0)
        base.update(kw)
        return cls(**base)


def init(cfg: Exaone4Config, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, nkv = cfg.num_layers, cfg.num_heads, cfg.num_kv_heads
    i, v = cfg.intermediate_size, cfg.vocab_size
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    params: Params = {
        "embed": normal(keys[0], (v, h), h),
        "layers": {
            "wq": normal(keys[1], (L, h, nh * hd), h),
            "wk": normal(keys[2], (L, h, nkv * hd), h),
            "wv": normal(keys[3], (L, h, nkv * hd), h),
            "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
            "q_norm": jnp.ones((L, hd), dtype),
            "k_norm": jnp.ones((L, hd), dtype),
            "post_attn_norm": jnp.ones((L, h), dtype),
            "w_gate": normal(keys[5], (L, h, i), h),
            "w_up": normal(keys[6], (L, h, i), h),
            "w_down": normal(keys[7], (L, i, h), i),
            "post_mlp_norm": jnp.ones((L, h), dtype),
        },
        "final_norm": jnp.ones((h,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(jax.random.fold_in(rng, 99), (h, v), h)
    return params


def param_logical_axes(cfg: Exaone4Config) -> Params:
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "q_norm": ("layers", None),
            "k_norm": ("layers", None),
            "post_attn_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "post_mlp_norm": ("layers", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _layer_scalars(cfg: Exaone4Config):
    """(windows [L], use_rope [L]) scanned alongside the stacked weights."""
    types = cfg.resolved_layer_types()
    big = 1 << 30  # effectively unwindowed
    windows = jnp.asarray(
        [cfg.sliding_window if t == "sliding_attention" else big
         for t in types], jnp.int32)
    # global NoPE: rotary only on sliding layers (when hybrid at all)
    use_rope = jnp.asarray(
        [1 if (cfg.sliding_window is None or t == "sliding_attention")
         else 0 for t in types], jnp.int32)
    return windows, use_rope


def _qkv(cfg: Exaone4Config, x, layer, cos, sin, positions, use_rope):
    b, s, _ = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    q = (x @ layer["wq"]).reshape(b, s, nh, hd)
    k = (x @ layer["wk"]).reshape(b, s, nkv, hd)
    v = (x @ layer["wv"]).reshape(b, s, nkv, hd)
    q = rms_norm(q, layer["q_norm"], cfg.rms_norm_eps)
    k = rms_norm(k, layer["k_norm"], cfg.rms_norm_eps)
    q = jnp.where(use_rope > 0, apply_rotary(q, cos, sin, positions), q)
    k = jnp.where(use_rope > 0, apply_rotary(k, cos, sin, positions), k)
    return q, k, v


def _block(cfg: Exaone4Config, x, layer, cos, sin, positions,
           window, use_rope):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q, k, v = _qkv(cfg, x, layer, cos, sin, positions, use_rope)
    if cfg.sliding_window is None:
        # pure-global config: plain causal keeps the Pallas flash path (a
        # dense mask would force the XLA fallback on every layer)
        attn_out = attention(q, k, v, causal=True)
    else:
        # per-layer windows are SCANNED traced scalars, so the static
        # flash `window=` fast path can't apply — the dense mask routes to
        # the XLA reference, which under attention.gqa_native computes
        # grouped einsums on the NARROW K/V (no q-width repeat; the
        # gqa-native lint traces this apply)
        q_pos = jnp.arange(s)[:, None]
        kv_pos = jnp.arange(s)[None, :]
        mask = (q_pos >= kv_pos) & (q_pos - kv_pos < window)
        attn_out = attention(q, k, v, causal=False, mask=mask[None, None])
    attn_out = attn_out.reshape(b, s, nh * hd) @ layer["wo"]
    x = x + rms_norm(attn_out, layer["post_attn_norm"], cfg.rms_norm_eps)
    mlp = (jax.nn.silu(x @ layer["w_gate"]) * (x @ layer["w_up"])) \
        @ layer["w_down"]
    return x + rms_norm(mlp, layer["post_mlp_norm"], cfg.rms_norm_eps)


def _cast_layers(params, compute_dtype):
    return jax.tree.map(lambda p: p.astype(compute_dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p,
                        params["layers"])


def _head_split(cfg, params, x, compute_dtype):
    """Final norm + unembed matrix minus the logits matmul — consumed by
    the tiled fused logits+loss head (``tiled_loss_fn``)."""
    x = rms_norm(x, params["final_norm"].astype(compute_dtype),
                 cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return x, head.astype(compute_dtype)


def _head(cfg, params, x, compute_dtype):
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def apply(cfg: Exaone4Config, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len,
                                cfg.rope_theta)
    layers = _cast_layers(params, compute_dtype)
    windows, use_rope = _layer_scalars(cfg)

    def body(x, scanned):
        layer, window, rope = scanned
        return _block(cfg, x, layer, cos, sin, positions, window, rope), None

    x, _ = lax.scan(body, x, (layers, windows, use_rope))
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# ---- KV-cached decode (v1-engine path) ---- #
def init_cache(cfg: Exaone4Config, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads,
             cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: Exaone4Config) -> Params:
    spec = ("layers", None, None, "kv_heads", None)
    return {"k": spec, "v": spec}


def _write_cache(cache, new, starts):
    def one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

    return jax.vmap(one)(cache, new, starts)


def apply_cached(cfg: Exaone4Config, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    b, t = tokens.shape
    nh, hd = cfg.num_heads, cfg.head_size
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len,
                                cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(t)[None, :]
    layers = _cast_layers(params, compute_dtype)
    windows, use_rope = _layer_scalars(cfg)

    def body(x, scanned):
        layer, k_c, v_c, window, rope = scanned
        S = k_c.shape[1]
        q, k, v = _qkv(cfg, x, layer, cos, sin, positions, rope)
        k_c = _write_cache(k_c, k, cache_len)
        v_c = _write_cache(v_c, v, cache_len)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = cache_len[:, None, None, None] + \
            jnp.arange(t)[None, None, :, None]
        mask = (kv_pos <= q_abs) & (q_abs - kv_pos < window)
        attn_out = attention(q, k_c, v_c, causal=False, mask=mask)
        attn_out = attn_out.reshape(b, t, nh * hd) @ layer["wo"]
        x = x + rms_norm(attn_out, layer["post_attn_norm"], cfg.rms_norm_eps)
        mlp = (jax.nn.silu(x @ layer["w_gate"]) * (x @ layer["w_up"])) \
            @ layer["w_down"]
        x = x + rms_norm(mlp, layer["post_mlp_norm"], cfg.rms_norm_eps)
        return x, (k_c, v_c)

    x, (new_k, new_v) = lax.scan(
        body, x, (layers, cache["k"], cache["v"], windows, use_rope))
    return _head(cfg, params, x, compute_dtype), {"k": new_k, "v": new_v}


def loss_fn(cfg: Exaone4Config, params: Params,
            batch: Dict[str, jnp.ndarray], *, compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tl = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(valid, tl, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return loss, {"loss": loss, "ntokens": valid.sum()}


def tiled_loss_fn(cfg: Exaone4Config, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``)."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head = apply(cfg, params, inputs, compute_dtype=compute_dtype,
                         return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    return loss, {"loss": loss, "ntokens": (labels != -100).sum()}


def model_spec(cfg: Exaone4Config, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="exaone4",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(
            cfg, params, tokens, compute_dtype=compute_dtype, **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )


# --------------------------------------------------------------------------- #
# Paged (blocked) KV-cache path — the v2 continuous-batching protocol
# (reference lists exaone4 among the v2 model implementations). The hybrid
# sliding/global masks rule out the plain-causal paged decode kernel, so
# both prefill and decode run the gathered-view attention with the windowed
# mask; block-table layout as in models/llama.py (block 0 = trash).
# --------------------------------------------------------------------------- #
def init_paged_cache(cfg: Exaone4Config, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None) -> Params:
    return _init_paged_pools(cfg.num_layers, num_blocks, cfg.num_kv_heads,
                             block_size, cfg.head_size, dtype,
                             kv_quant_group)


def apply_paged(cfg: Exaone4Config, params: Params, tokens: jnp.ndarray,
                cache: Params, block_tables: jnp.ndarray,
                context_lens: jnp.ndarray, *,
                valid: Optional[jnp.ndarray] = None,
                compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    b, t = tokens.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    if valid is None:
        valid = jnp.ones((b, t), bool)
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len,
                                cfg.rope_theta)
    positions = context_lens[:, None] + jnp.arange(t)[None, :]
    layers = _cast_layers(params, compute_dtype)
    windows, use_rope = _layer_scalars(cfg)

    def scan_body(x, scanned):
        layer, k_c, v_c, window, rope = scanned
        q, k, v = _qkv(cfg, x, layer, cos, sin, positions, rope)
        # pure-global configs (static) take window=None (plain-causal
        # decode kernel); hybrid configs pass the traced per-layer window —
        # single-token decode runs the WINDOWED Pallas kernel (the window
        # rides scalar prefetch), prefill takes the gathered mask path
        attn_out, k_c, v_c = paged_attention_step(
            q, k, v, k_c, v_c, block_tables, context_lens, positions, valid,
            window=None if cfg.sliding_window is None else window)
        attn_out = attn_out.reshape(b, t, nh * hd) @ layer["wo"]
        x = x + rms_norm(attn_out, layer["post_attn_norm"], cfg.rms_norm_eps)
        mlp = (jax.nn.silu(x @ layer["w_gate"]) * (x @ layer["w_up"])) \
            @ layer["w_down"]
        x = x + rms_norm(mlp, layer["post_mlp_norm"], cfg.rms_norm_eps)
        return x, (k_c, v_c)

    x, (nk, nv) = lax.scan(
        scan_body, x, (layers,) + split_kv(cache) + (windows, use_rope))
    return _head(cfg, params, x, compute_dtype), join_kv(nk, nv)
