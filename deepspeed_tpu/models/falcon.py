"""Falcon family (RW architecture), written TPU-first.

Reference parity: the reference serves Falcon via
``inference/v2/model_implementations/falcon`` and a v1 injection policy.
Falcon differs from the Llama family in three ways, all handled here:
parallel attention+MLP blocks (``x + attn(ln(x)) + mlp(ln(x))``), LayerNorm
(with bias) instead of RMSNorm, and MQA (classic 7B: one shared KV head) or
grouped KV (new decoder architecture, 40B/180B: separate ln_attn/ln_mlp).

Same TPU shape as ``models/llama``: stacked layers under ``lax.scan``,
logical axis names per param, attention through the op registry.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ._paged import join_kv, paged_attention_step, split_kv
from ._paged import init_paged_pools as _init_paged_pools
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm
from ..ops.rotary import apply_rotary, rope_frequencies

Params = Dict[str, Any]


@dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_layers: int = 32
    num_heads: int = 71
    num_kv_heads: int = 1          # classic 7B MQA
    max_seq_len: int = 2048
    parallel_attn: bool = True
    new_decoder_architecture: bool = False
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    attention_bias: bool = False
    tie_embeddings: bool = True    # falcon ties lm_head to word embeddings

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def tiny(cls, **kw) -> "FalconConfig":
        base = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    num_kv_heads=1, max_seq_len=128)
        base.update(kw)
        return cls(**base)


def init(cfg: FalconConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_size
    L, nh, nkv, v = cfg.num_layers, cfg.num_heads, cfg.num_kv_heads, cfg.vocab_size
    i = cfg.intermediate_size
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5).astype(dtype)

    params: Params = {
        "embed": normal(keys[0], (v, h), h),
        "layers": {
            "ln_attn_scale": jnp.ones((L, h), dtype),
            "ln_attn_bias": jnp.zeros((L, h), dtype),
            "wq": normal(keys[1], (L, h, nh * hd), h),
            "wk": normal(keys[2], (L, h, nkv * hd), h),
            "wv": normal(keys[3], (L, h, nkv * hd), h),
            "wo": normal(keys[4], (L, nh * hd, h), nh * hd),
            "w_up": normal(keys[5], (L, h, i), h),
            "w_down": normal(keys[6], (L, i, h), i),
        },
        "final_ln_scale": jnp.ones((h,), dtype),
        "final_ln_bias": jnp.zeros((h,), dtype),
    }
    if cfg.new_decoder_architecture or not cfg.parallel_attn:
        # 40B+: parallel block with separate MLP norm; sequential classic
        # (rw-1b): distinct post-attention norm
        params["layers"]["ln_mlp_scale"] = jnp.ones((L, h), dtype)
        params["layers"]["ln_mlp_bias"] = jnp.zeros((L, h), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(keys[7], (h, v), h)
    return params


def param_logical_axes(cfg: FalconConfig) -> Params:
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "ln_attn_scale": ("layers", "embed"),
            "ln_attn_bias": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }
    if cfg.new_decoder_architecture or not cfg.parallel_attn:
        axes["layers"]["ln_mlp_scale"] = ("layers", "embed")
        axes["layers"]["ln_mlp_bias"] = ("layers", "embed")
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _attn_part(cfg: FalconConfig, y: jnp.ndarray, layer: Params,
               cos, sin, positions, mask_args=None) -> jnp.ndarray:
    b, s, _ = y.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    q = (y @ layer["wq"]).reshape(b, s, nh, hd)
    k = (y @ layer["wk"]).reshape(b, s, nkv, hd)
    v = (y @ layer["wv"]).reshape(b, s, nkv, hd)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    # K/V pass NARROW (classic Falcon MQA: ONE kv head) into the attention
    # op — under attention.gqa_native the flash kernels keep them narrow
    # end to end (nq× less KV HBM traffic; the gqa-native lint traces this)
    out = attention(q, k, v, causal=True)
    return out.reshape(b, s, nh * hd) @ layer["wo"]


def _block(cfg: FalconConfig, x: jnp.ndarray, layer: Params,
           cos, sin, positions) -> jnp.ndarray:
    """Parallel Falcon block: x + attn(ln_attn(x)) + mlp(ln_mlp_or_attn(x))."""
    y_attn = layer_norm(x, layer["ln_attn_scale"], layer["ln_attn_bias"],
                        cfg.layer_norm_eps)
    if cfg.new_decoder_architecture:
        y_mlp = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                           cfg.layer_norm_eps)
    else:
        y_mlp = y_attn
    attn_out = _attn_part(cfg, y_attn, layer, cos, sin, positions)
    mlp_out = jax.nn.gelu(y_mlp @ layer["w_up"], approximate=False) @ layer["w_down"]
    if cfg.parallel_attn:
        return x + attn_out + mlp_out
    # sequential variant (parallel_attn=False checkpoints): the second norm
    # is the checkpoint's post_attention_layernorm (imported as ln_mlp_*)
    x = x + attn_out
    y2 = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                    cfg.layer_norm_eps)
    return x + jax.nn.gelu(y2 @ layer["w_up"], approximate=False) @ layer["w_down"]


def _head_split(cfg: FalconConfig, params: Params, x: jnp.ndarray,
                compute_dtype):
    """Final norm + unembed matrix minus the logits matmul — consumed by
    the tiled fused logits+loss head (``tiled_loss_fn``)."""
    x = layer_norm(x, params["final_ln_scale"].astype(compute_dtype),
                   params["final_ln_bias"].astype(compute_dtype),
                   cfg.layer_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return x, head.astype(compute_dtype)


def _head(cfg: FalconConfig, params: Params, x: jnp.ndarray,
          compute_dtype) -> jnp.ndarray:
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def _cast_layers(params: Params, compute_dtype):
    return jax.tree.map(lambda p: p.astype(compute_dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p,
                        params["layers"])


def apply(cfg: FalconConfig, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    layers = _cast_layers(params, compute_dtype)
    block = partial(_block, cfg)

    from ..comm import overlap as ov

    def scan_body(x, layer):
        return block(x, ov.constrain_scan_slice(layer),
                     cos, sin, positions), None

    x, _ = lax.scan(scan_body, x, layers)
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# ---- KV-cached decode (v1-engine path) ---- #
def init_cache(cfg: FalconConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    L, nkv, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_size
    shape = (L, batch_size, max_len, nkv, hd)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: FalconConfig) -> Params:
    spec = ("layers", None, None, "kv_heads", None)
    return {"k": spec, "v": spec}


def _write_cache(cache, new, starts):
    def one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

    return jax.vmap(one)(cache, new, starts)


def apply_cached(cfg: FalconConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    b, t = tokens.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(t)[None, :]
    layers = _cast_layers(params, compute_dtype)

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        S = k_c.shape[1]
        y_attn = layer_norm(x, layer["ln_attn_scale"], layer["ln_attn_bias"],
                            cfg.layer_norm_eps)
        y_mlp = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                           cfg.layer_norm_eps) \
            if cfg.new_decoder_architecture else y_attn
        q = (y_attn @ layer["wq"]).reshape(b, t, nh, hd)
        k = (y_attn @ layer["wk"]).reshape(b, t, nkv, hd)
        v = (y_attn @ layer["wv"]).reshape(b, t, nkv, hd)
        q = apply_rotary(q, cos, sin, positions)
        k = apply_rotary(k, cos, sin, positions)
        k_c = _write_cache(k_c, k, cache_len)
        v_c = _write_cache(v_c, v, cache_len)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = cache_len[:, None, None, None] + jnp.arange(t)[None, None, :, None]
        mask = kv_pos <= q_abs
        attn_out = attention(q, k_c, v_c, causal=False, mask=mask)
        attn_out = attn_out.reshape(b, t, nh * hd) @ layer["wo"]
        if cfg.parallel_attn:
            mlp_out = jax.nn.gelu(y_mlp @ layer["w_up"], approximate=False) \
                @ layer["w_down"]
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            y2 = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                            cfg.layer_norm_eps)
            x = x + jax.nn.gelu(y2 @ layer["w_up"], approximate=False) \
                @ layer["w_down"]
        return x, (k_c, v_c)

    x, (new_k, new_v) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    return _head(cfg, params, x, compute_dtype), {"k": new_k, "v": new_v}


def loss_fn(cfg: FalconConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tl = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(valid, tl, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return loss, {"loss": loss, "ntokens": valid.sum()}


def tiled_loss_fn(cfg: FalconConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``)."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head = apply(cfg, params, inputs, compute_dtype=compute_dtype,
                         return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    return loss, {"loss": loss, "ntokens": (labels != -100).sum()}


def model_spec(cfg: FalconConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="falcon",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(
            cfg, params, tokens, compute_dtype=compute_dtype, **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )


# --------------------------------------------------------------------------- #
# Paged (blocked) KV-cache path — the v2 continuous-batching protocol
# (reference serves Falcon through inference/v2; block-table layout as in
# models/llama.py: fixed-width tables, block 0 is the trash block)
# --------------------------------------------------------------------------- #
def init_paged_cache(cfg: FalconConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None) -> Params:
    return _init_paged_pools(cfg.num_layers, num_blocks, cfg.num_kv_heads,
                             block_size, cfg.head_size, dtype,
                             kv_quant_group)


def apply_paged(cfg: FalconConfig, params: Params, tokens: jnp.ndarray,
                cache: Params, block_tables: jnp.ndarray,
                context_lens: jnp.ndarray, *,
                valid: Optional[jnp.ndarray] = None,
                compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Ragged forward over the paged cache (see llama.apply_paged for the
    contract); handles the parallel / sequential / new-decoder variants."""
    b, t = tokens.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    if valid is None:
        valid = jnp.ones((b, t), bool)
    x = embedding_lookup(params["embed"], tokens, compute_dtype)
    cos, sin = rope_frequencies(cfg.head_size, cfg.max_seq_len, cfg.rope_theta)
    positions = context_lens[:, None] + jnp.arange(t)[None, :]
    layers = _cast_layers(params, compute_dtype)

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        y_attn = layer_norm(x, layer["ln_attn_scale"], layer["ln_attn_bias"],
                            cfg.layer_norm_eps)
        y_mlp = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                           cfg.layer_norm_eps) \
            if cfg.new_decoder_architecture else y_attn
        q = (y_attn @ layer["wq"]).reshape(b, t, nh, hd)
        k = (y_attn @ layer["wk"]).reshape(b, t, nkv, hd)
        v = (y_attn @ layer["wv"]).reshape(b, t, nkv, hd)
        q = apply_rotary(q, cos, sin, positions)
        k = apply_rotary(k, cos, sin, positions)
        attn_out, k_c, v_c = paged_attention_step(
            q, k, v, k_c, v_c, block_tables, context_lens, positions, valid)
        attn_out = attn_out.reshape(b, t, nh * hd) @ layer["wo"]
        if cfg.parallel_attn:
            mlp_out = jax.nn.gelu(y_mlp @ layer["w_up"], approximate=False) \
                @ layer["w_down"]
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            y2 = layer_norm(x, layer["ln_mlp_scale"], layer["ln_mlp_bias"],
                            cfg.layer_norm_eps)
            x = x + jax.nn.gelu(y2 @ layer["w_up"], approximate=False) \
                @ layer["w_down"]
        return x, (k_c, v_c)

    x, (nk, nv) = lax.scan(scan_body, x, (layers,) + split_kv(cache))
    return _head(cfg, params, x, compute_dtype), join_kv(nk, nv)
