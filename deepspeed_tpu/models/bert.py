"""BERT-family encoder (bidirectional attention, learned positions + token
types, post-LN blocks, MLM head).

Reference parity: the reference's oldest supported family — kernel injection
policy ``module_inject/containers/bert.py`` and the fused training
``DeepSpeedTransformerLayer`` (``csrc/transformer``) were built for BERT.
Same TPU-first structure as the other families: stacked layers + ``lax.scan``,
logical axes, op-registry norms/attention (bidirectional: ``causal=False``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    gelu_approx: bool = False  # HF 'gelu' is the exact erf form
    remat: bool = False

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, max_seq_len=64,
                    type_vocab_size=2)
        base.update(kw)
        return cls(**base)

    @classmethod
    def bert_base(cls) -> "BertConfig":
        return cls()


def init(cfg: BertConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, i, v, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_layers)
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "embed": normal(keys[0], (v, h), h),
        "pos_embed": normal(keys[1], (cfg.max_seq_len, h), h),
        "type_embed": normal(keys[2], (cfg.type_vocab_size, h), h),
        "embed_ln_scale": jnp.ones((h,), dtype),
        "embed_ln_bias": jnp.zeros((h,), dtype),
        "layers": {
            "wqkv": normal(keys[3], (L, h, 3 * h), h),
            "bqkv": jnp.zeros((L, 3 * h), dtype),
            "wo": normal(keys[4], (L, h, h), h),
            "bo": jnp.zeros((L, h), dtype),
            "attn_ln_scale": jnp.ones((L, h), dtype),
            "attn_ln_bias": jnp.zeros((L, h), dtype),
            "w_up": normal(keys[5], (L, h, i), h),
            "b_up": jnp.zeros((L, i), dtype),
            "w_down": normal(keys[6], (L, i, h), i),
            "b_down": jnp.zeros((L, h), dtype),
            "mlp_ln_scale": jnp.ones((L, h), dtype),
            "mlp_ln_bias": jnp.zeros((L, h), dtype),
        },
        "pooler_w": normal(keys[7], (h, h), h),
        "pooler_b": jnp.zeros((h,), dtype),
    }


def param_logical_axes(cfg: BertConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "type_embed": (None, "embed"),
        "embed_ln_scale": ("embed",), "embed_ln_bias": ("embed",),
        "layers": {
            "wqkv": ("layers", "embed", "heads"), "bqkv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
            "attn_ln_scale": ("layers", "embed"),
            "attn_ln_bias": ("layers", "embed"),
            "w_up": ("layers", "embed", "mlp"), "b_up": ("layers", "mlp"),
            "w_down": ("layers", "mlp", "embed"), "b_down": ("layers", "embed"),
            "mlp_ln_scale": ("layers", "embed"),
            "mlp_ln_bias": ("layers", "embed"),
        },
        "pooler_w": ("embed", "embed"), "pooler_b": ("embed",),
    }


def _block(cfg: BertConfig, x: jnp.ndarray, layer: Params,
           mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Post-LN encoder block. mask: [b, 1, 1, s] boolean (True = attend)."""
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    eps = cfg.layer_norm_eps
    qkv = x @ layer["wqkv"] + layer["bqkv"]
    q, k, v = [t.reshape(b, s, nh, hd) for t in jnp.split(qkv, 3, axis=-1)]
    a = attention(q, k, v, causal=False, mask=mask)
    a = a.reshape(b, s, nh * hd) @ layer["wo"] + layer["bo"]
    x = layer_norm(x + a, layer["attn_ln_scale"], layer["attn_ln_bias"], eps)
    m = jax.nn.gelu(x @ layer["w_up"] + layer["b_up"],
                    approximate=cfg.gelu_approx) @ layer["w_down"] \
        + layer["b_down"]
    return layer_norm(x + m, layer["mlp_ln_scale"], layer["mlp_ln_bias"], eps)


def apply(cfg: BertConfig, params: Params, tokens: jnp.ndarray, *,
          token_types: Optional[jnp.ndarray] = None,
          attention_mask: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    """→ {"hidden": [b,s,h], "pooled": [b,h], "mlm_logits": [b,s,vocab]}."""
    b, s = tokens.shape
    if token_types is None:
        token_types = jnp.zeros_like(tokens)
    # embeddings + LN deliberately fp32 (BERT embed-LN precision); the cast
    # to compute dtype happens after the norm below
    x = (embedding_lookup(params["embed"], tokens, jnp.float32) + params["pos_embed"][jnp.arange(s)][None]
         + params["type_embed"][token_types])
    x = layer_norm(x, params["embed_ln_scale"], params["embed_ln_bias"],
                   cfg.layer_norm_eps).astype(compute_dtype)
    mask = None
    if attention_mask is not None:
        mask = attention_mask[:, None, None, :].astype(bool)
    layers = jax.tree.map(lambda p: p.astype(compute_dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p,
                          params["layers"])
    block = partial(_block, cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    from ..comm import overlap as ov

    def scan_body(x, layer):
        return block(x, ov.constrain_scan_slice(layer), mask), None

    x, _ = lax.scan(scan_body, x, layers)
    pooled = jnp.tanh(x[:, 0] @ params["pooler_w"].astype(compute_dtype)
                      + params["pooler_b"].astype(compute_dtype))
    mlm = (x @ params["embed"].T.astype(compute_dtype)).astype(jnp.float32)
    return {"hidden": x, "pooled": pooled, "mlm_logits": mlm}


def loss_fn(cfg: BertConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16):
    """Masked-LM loss: labels -100 = unmasked (ignored)."""
    out = apply(cfg, params, batch["tokens"],
                token_types=batch.get("token_types"),
                attention_mask=batch.get("attention_mask"),
                compute_dtype=compute_dtype)
    labels = batch["labels"]
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(out["mlm_logits"], axis=-1)
    tok_loss = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(valid, tok_loss, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return loss, {"loss": loss}


def model_spec(cfg: BertConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="bert",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        apply_fn=lambda params, tokens, **kw: apply(cfg, params, tokens,
                                                    compute_dtype=compute_dtype,
                                                    **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )
