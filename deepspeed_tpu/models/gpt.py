"""GPT-2/OPT-family model (learned positions, LayerNorm, GELU MLP, MHA).

Reference parity: the reference injects kernels into these HF families via
``module_inject/containers/{gpt2,gptneo,opt,bloom}.py`` and serves OPT in
inference v2 (``inference/v2/model_implementations/opt``). Same TPU-first
shape as ``models/llama.py``: stacked layers under ``lax.scan``, logical axis
names for the shared partitioner, op-registry norms/attention, KV-cached
decode path for the inference engines.

Covers GPT-2, OPT (pre-LN), and with ``post_ln=True`` the original
post-LN ordering (BLOOM-style alibi is not modeled)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import attention
from ._paged import join_kv, paged_attention_step, split_kv
from ._paged import init_paged_pools as _init_paged_pools
from ..ops.embedding import embedding_lookup
from ..ops.norms import layer_norm

Params = Dict[str, Any]

# checkpoint names this family's TRAINING block attaches (the selective-
# remat saveables; no "mlp_gate" — the GPT FFN has no gate projection)
CHECKPOINT_NAMES_EMITTED = ("qkv_proj", "attn_mix", "attn_out",
                            "mlp_up", "mlp_out")


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    post_ln: bool = False     # True = original transformer/BLOOM ordering
    activation: str = "gelu"  # "gelu" (GPT-2) | "relu" (OPT)
    remat: bool = False
    remat_policy: str = "none"  # none | full | dots | any registry policy

    def __post_init__(self):
        if self.activation not in ("gelu", "relu"):
            raise ValueError(f"unsupported activation {self.activation!r} "
                             "(gelu | relu)")

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        h, i, v, L, s = (self.hidden_size, self.intermediate_size,
                         self.vocab_size, self.num_layers, self.max_seq_len)
        # weights 4h²+2hi; biases bqkv 3h + bo h + b_up i + b_down h; LN 4h
        block = 4 * h * h + 2 * h * i + 9 * h + i
        embed = v * h * (1 if self.tie_embeddings else 2) + s * h
        return L * block + embed + 2 * h

    @classmethod
    def tiny(cls, **kw) -> "GPTConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, max_seq_len=128)
        base.update(kw)
        return cls(**base)

    @classmethod
    def gpt2_small(cls) -> "GPTConfig":
        return cls()

    @classmethod
    def opt_1_3b(cls) -> "GPTConfig":
        return cls(vocab_size=50272, hidden_size=2048, intermediate_size=8192,
                   num_layers=24, num_heads=32, max_seq_len=2048)


def init(cfg: GPTConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    h, i, v, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_layers)
    keys = jax.random.split(rng, 8)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    params: Params = {
        "embed": normal(keys[0], (v, h), h),
        "pos_embed": normal(keys[1], (cfg.max_seq_len, h), h),
        "layers": {
            "ln1_scale": jnp.ones((L, h), dtype),
            "ln1_bias": jnp.zeros((L, h), dtype),
            "wqkv": normal(keys[2], (L, h, 3 * h), h),
            "bqkv": jnp.zeros((L, 3 * h), dtype),
            "wo": normal(keys[3], (L, h, h), h),
            "bo": jnp.zeros((L, h), dtype),
            "ln2_scale": jnp.ones((L, h), dtype),
            "ln2_bias": jnp.zeros((L, h), dtype),
            "w_up": normal(keys[4], (L, h, i), h),
            "b_up": jnp.zeros((L, i), dtype),
            "w_down": normal(keys[5], (L, i, h), i),
            "b_down": jnp.zeros((L, h), dtype),
        },
        "final_ln_scale": jnp.ones((h,), dtype),
        "final_ln_bias": jnp.zeros((h,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(keys[6], (h, v), h)
    return params


def param_logical_axes(cfg: GPTConfig) -> Params:
    axes = {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "layers": {
            "ln1_scale": ("layers", "embed"), "ln1_bias": ("layers", "embed"),
            "wqkv": ("layers", "embed", "heads"), "bqkv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
            "ln2_scale": ("layers", "embed"), "ln2_bias": ("layers", "embed"),
            "w_up": ("layers", "embed", "mlp"), "b_up": ("layers", "mlp"),
            "w_down": ("layers", "mlp", "embed"), "b_down": ("layers", "embed"),
        },
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _attn(cfg: GPTConfig, x: jnp.ndarray, layer: Params,
          kv: Optional[Tuple] = None, cache_len: Optional[jnp.ndarray] = None):
    """QKV projection + (cached) attention. Returns (out, (k, v))."""
    b, t, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    qkv = checkpoint_name(x @ layer["wqkv"] + layer["bqkv"], "qkv_proj")
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, nh, hd)
    # GPT-2/OPT are MHA (kv heads == query heads): K/V enter the attention
    # op already at query width, so attention.gqa_native is a no-op here —
    # the gqa-native lint still traces this apply to pin that no widening
    # ever appears
    k = k.reshape(b, t, nh, hd)
    v = v.reshape(b, t, nh, hd)
    if kv is None:
        out = attention(q, k, v, causal=True)
    else:
        k_cache, v_cache = kv
        S = k_cache.shape[1]

        def write(c, n, s):
            return lax.dynamic_update_slice(c, n.astype(c.dtype), (s, 0, 0))

        k_cache = jax.vmap(write)(k_cache, k, cache_len)
        v_cache = jax.vmap(write)(v_cache, v, cache_len)
        kv_pos = jnp.arange(S)[None, None, None, :]
        q_abs = (cache_len[:, None] + jnp.arange(t)[None, :])[:, None, :, None]
        out = attention(q, k_cache, v_cache, causal=False,
                        mask=kv_pos <= q_abs)
        k, v = k_cache, v_cache
    out = checkpoint_name(out, "attn_mix")
    return out.reshape(b, t, nh * hd) @ layer["wo"] + layer["bo"], (k, v)


def _block(cfg: GPTConfig, x, layer, kv=None, cache_len=None,
           attn_call=None):
    """One block; ``attn_call(y) -> (attn_out, kv_state)`` overrides the
    default dense/cached attention (the paged path supplies its own)."""
    if attn_call is None:
        attn_call = lambda y: _attn(cfg, y, layer, kv, cache_len)  # noqa: E731
    eps = cfg.layer_norm_eps
    act = jax.nn.relu if cfg.activation == "relu" else jax.nn.gelu
    # "attn_out"/"mlp_out" mark the selective-remat saveables (identity
    # outside a targeting jax.checkpoint policy) — see the registry in
    # runtime/activation_checkpointing/checkpointing.py
    if cfg.post_ln:
        a, kv = attn_call(x)
        a = checkpoint_name(a, "attn_out")
        x = layer_norm(x + a, layer["ln1_scale"], layer["ln1_bias"], eps)
        up = checkpoint_name(x @ layer["w_up"] + layer["b_up"], "mlp_up")
        m = checkpoint_name(act(up) @ layer["w_down"], "mlp_out") \
            + layer["b_down"]
        x = layer_norm(x + m, layer["ln2_scale"], layer["ln2_bias"], eps)
    else:  # pre-LN (GPT-2/OPT)
        y = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"], eps)
        a, kv = attn_call(y)
        x = x + checkpoint_name(a, "attn_out")
        y = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"], eps)
        up = checkpoint_name(y @ layer["w_up"] + layer["b_up"], "mlp_up")
        x = x + checkpoint_name(act(up) @ layer["w_down"], "mlp_out") \
            + layer["b_down"]
    return x, kv


def _cast_layers(params: Params, dtype) -> Params:
    return jax.tree.map(lambda p: p.astype(dtype)
                        if jnp.issubdtype(p.dtype, jnp.floating) else p,
                        params["layers"])


def _head_split(cfg: GPTConfig, params: Params, x: jnp.ndarray,
                compute_dtype):
    """Final norm + unembed matrix minus the logits matmul — consumed by
    the tiled fused logits+loss head (``tiled_loss_fn``)."""
    x = layer_norm(x, params["final_ln_scale"].astype(compute_dtype),
                   params["final_ln_bias"].astype(compute_dtype),
                   cfg.layer_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return x, head.astype(compute_dtype)


def _head(cfg: GPTConfig, params: Params, x: jnp.ndarray,
          compute_dtype) -> jnp.ndarray:
    x, head = _head_split(cfg, params, x, compute_dtype)
    return (x @ head).astype(jnp.float32)


def apply(cfg: GPTConfig, params: Params, tokens: jnp.ndarray, *,
          positions: Optional[jnp.ndarray] = None,
          compute_dtype=jnp.bfloat16, return_hidden: bool = False):
    b, t = tokens.shape
    if positions is None:
        positions = jnp.arange(t)[None, :]
    x = (embedding_lookup(params["embed"], tokens, compute_dtype) + params["pos_embed"][positions].astype(compute_dtype)) \
        .astype(compute_dtype)
    layers = _cast_layers(params, compute_dtype)
    block = partial(_block, cfg)
    if cfg.remat:
        # route through the shared remat-policy registry (same name map as
        # models/llama.py) so the config knob and the model agree
        from ..runtime.activation_checkpointing import checkpointing as ac

        name = {"none": "full", "full": "full",
                "dots": "dots_saveable"}.get(cfg.remat_policy,
                                             cfg.remat_policy)
        block = jax.checkpoint(block, policy=ac.get_policy(name))

    from ..comm import overlap as ov

    def scan_body(x, layer):
        x, _ = block(x, ov.constrain_scan_slice(layer))
        return x, None

    if ov.layer_prefetch_active():
        x, _ = ov.prefetch_scan(scan_body, x, layers)
    else:
        x, _ = lax.scan(scan_body, x, layers)
    if return_hidden:
        return _head_split(cfg, params, x, compute_dtype)
    return _head(cfg, params, x, compute_dtype)


# --- KV-cached inference path (engine ModelFamily protocol) ---------------- #
def init_cache(cfg: GPTConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_heads, cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: GPTConfig) -> Params:
    spec = ("layers", None, None, "heads", None)
    return {"k": spec, "v": spec}


def apply_cached(cfg: GPTConfig, params: Params, tokens: jnp.ndarray,
                 cache: Params, cache_len: jnp.ndarray, *,
                 compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    if cache_len.ndim == 0:
        cache_len = jnp.broadcast_to(cache_len, (tokens.shape[0],))
    positions = jnp.minimum(cache_len[:, None] + jnp.arange(tokens.shape[1]),
                            cfg.max_seq_len - 1)
    x = (embedding_lookup(params["embed"], tokens, compute_dtype) + params["pos_embed"][positions].astype(compute_dtype)) \
        .astype(compute_dtype)
    layers = _cast_layers(params, compute_dtype)

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned
        x, (k_c, v_c) = _block(cfg, x, layer, (k_c, v_c), cache_len)
        return x, (k_c, v_c)

    x, (nk, nv) = lax.scan(scan_body, x, (layers, cache["k"], cache["v"]))
    return _head(cfg, params, x, compute_dtype), {"k": nk, "v": nv}


# --------------------------------------------------------------------------- #
# Paged (blocked) KV-cache path — the v2 continuous-batching protocol
# (reference serves OPT through inference/v2; see models/llama.py for the
# block-table layout: fixed-width tables, block 0 is the trash block)
# --------------------------------------------------------------------------- #
def init_paged_cache(cfg: GPTConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16,
                     kv_quant_group: Optional[int] = None) -> Params:
    return _init_paged_pools(cfg.num_layers, num_blocks, cfg.num_heads,
                             block_size, cfg.head_size, dtype,
                             kv_quant_group)



def _attn_paged(cfg: GPTConfig, y: jnp.ndarray, layer: Params,
                k_cache, v_cache, block_tables, context_lens, valid,
                positions):
    b, t, _ = y.shape
    nh, hd = cfg.num_heads, cfg.head_size
    qkv = y @ layer["wqkv"] + layer["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    out, k_cache, v_cache = paged_attention_step(
        q.reshape(b, t, nh, hd), k.reshape(b, t, nh, hd),
        v.reshape(b, t, nh, hd), k_cache, v_cache, block_tables,
        context_lens, positions, valid)
    out = out.reshape(b, t, nh * hd) @ layer["wo"] + layer["bo"]
    return out, k_cache, v_cache


def apply_paged(cfg: GPTConfig, params: Params, tokens: jnp.ndarray,
                cache: Params, block_tables: jnp.ndarray,
                context_lens: jnp.ndarray, *,
                valid: Optional[jnp.ndarray] = None,
                compute_dtype=jnp.bfloat16) -> Tuple[jnp.ndarray, Params]:
    """Ragged forward over the paged cache (see llama.apply_paged for the
    contract); handles both LN orderings and the relu/gelu variants."""
    b, t = tokens.shape
    if valid is None:
        valid = jnp.ones((b, t), bool)
    positions = context_lens[:, None] + jnp.arange(t)[None, :]
    # clamp ONLY the learned-position lookup; the cache scatter/mask must see
    # the true absolute positions or slots past max_seq_len silently collide
    pos_idx = jnp.minimum(positions, cfg.max_seq_len - 1)
    x = (embedding_lookup(params["embed"], tokens, compute_dtype)
         + params["pos_embed"][pos_idx].astype(compute_dtype))
    layers = _cast_layers(params, compute_dtype)

    def scan_body(x, scanned):
        layer, k_c, v_c = scanned

        def attn_call(y):
            out, nk, nv = _attn_paged(cfg, y, layer, k_c, v_c, block_tables,
                                      context_lens, valid, positions)
            return out, (nk, nv)

        x, kv = _block(cfg, x, layer, attn_call=attn_call)
        return x, kv

    x, (nk, nv) = lax.scan(scan_body, x, (layers,) + split_kv(cache))
    return _head(cfg, params, x, compute_dtype), join_kv(nk, nv)


def loss_fn(cfg: GPTConfig, params: Params, batch: Dict[str, jnp.ndarray], *,
            compute_dtype=jnp.bfloat16):
    tokens = batch["tokens"]
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    logits = apply(cfg, params, inputs, compute_dtype=compute_dtype)
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))
    return loss, {"loss": loss}


def tiled_loss_fn(cfg: GPTConfig, params: Params,
                  batch: Dict[str, jnp.ndarray], *,
                  compute_dtype=jnp.bfloat16, shards: int = 8):
    """``loss_fn`` with the unembed matmul + CE fused per sequence tile —
    [B, S, V] logits are never materialized (``sequence.tiled_loss``)."""
    from ..sequence.tiled import tiled_fused_logits_loss

    tokens = batch["tokens"]
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    hidden, head = apply(cfg, params, inputs, compute_dtype=compute_dtype,
                         return_hidden=True)
    loss = tiled_fused_logits_loss(hidden, head, labels, shards=shards)
    return loss, {"loss": loss}


def model_spec(cfg: GPTConfig, compute_dtype=jnp.bfloat16):
    from ..runtime.engine import ModelSpec

    return ModelSpec(
        name="gpt",
        init_fn=lambda rng: init(cfg, rng),
        loss_fn=lambda params, batch: loss_fn(cfg, params, batch,
                                              compute_dtype=compute_dtype),
        tiled_loss_fn=lambda params, batch, shards=8: tiled_loss_fn(
            cfg, params, batch, compute_dtype=compute_dtype, shards=shards),
        apply_fn=lambda params, tokens, **kw: apply(cfg, params, tokens,
                                                    compute_dtype=compute_dtype,
                                                    **kw),
        logical_axes=param_logical_axes(cfg),
        pipeline_capable=False,
    )
