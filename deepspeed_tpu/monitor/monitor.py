"""Metrics monitoring — fan-out to TensorBoard / WandB / Comet / CSV backends.

Reference parity: ``deepspeed/monitor/monitor.py:30 MonitorMaster`` with
``tensorboard.py``, ``wandb.py``, ``comet.py``, ``csv_monitor.py`` (the Comet
backend enables only when the comet_ml SDK imports). Each backend is
config-gated and degrades to disabled with a warning when its library is
missing. Events are ``(name, value, step)`` tuples, written by rank 0 only
(``jax.process_index() == 0``), matching the reference's rank-0 gating.
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Sequence, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class MonitorBackend:
    name = "base"

    def __init__(self, cfg):
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enabled", False))

    def write_events(self, events: Sequence[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


class TensorBoardMonitor(MonitorBackend):
    """Reference ``monitor/tensorboard.py``. Uses torch's SummaryWriter (cpu
    torch is in-image); falls back to tensorboardX if present."""

    name = "tensorboard"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.writer = None
        if not self.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            path = os.path.join(cfg.output_path or "runs", cfg.job_name)
            self.writer = SummaryWriter(log_dir=path)
        except Exception as e:
            logger.warning(f"tensorboard monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.writer:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))

    def flush(self) -> None:
        if self.writer:
            self.writer.flush()


class WandbMonitor(MonitorBackend):
    """Reference ``monitor/wandb.py``; requires the wandb SDK."""

    name = "wandb"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.run = None
        if not self.enabled:
            return
        try:
            import wandb

            self.run = wandb.init(project=cfg.project or cfg.job_name,
                                  entity=cfg.team, group=cfg.group,
                                  dir=cfg.output_path or None)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.run:
            return
        for name, value, step in events:
            self._wandb.log({name: float(value)}, step=int(step))


class CometMonitor(MonitorBackend):
    """Reference ``monitor/comet.py``; requires the comet_ml SDK."""

    name = "comet"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.experiment = None
        if not self.enabled:
            return
        try:
            import comet_ml

            self.experiment = comet_ml.Experiment(
                project_name=getattr(cfg, "project", None) or cfg.job_name,
                workspace=getattr(cfg, "workspace", None) or
                getattr(cfg, "team", None))
            name = getattr(cfg, "experiment_name", None)
            if name:
                self.experiment.set_name(name)
        except Exception as e:
            logger.warning(f"comet monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.experiment:
            return
        for name, value, step in events:
            self.experiment.log_metric(name, float(value), step=int(step))

    def flush(self) -> None:
        if self.experiment:
            self.experiment.flush()


class CSVMonitor(MonitorBackend):
    """Reference ``monitor/csv_monitor.py`` — one CSV per metric name."""

    name = "csv"

    def __init__(self, cfg):
        super().__init__(cfg)
        self._files = {}
        if self.enabled:
            self.root = os.path.join(cfg.output_path or "csv_monitor",
                                     cfg.job_name)
            os.makedirs(self.root, exist_ok=True)

    def _writer(self, name: str):
        if name not in self._files:
            fn = os.path.join(self.root, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fn)
            f = open(fn, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name])
            self._files[name] = (f, w)
        return self._files[name]

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            f, w = self._writer(name)
            w.writerow([int(step), float(value)])

    def flush(self) -> None:
        for f, _ in self._files.values():
            f.flush()


class MonitorMaster(MonitorBackend):
    """Fans every event out to all enabled backends (reference
    ``monitor.py:30``)."""

    name = "master"

    def __init__(self, monitor_config):
        self.backends: List[MonitorBackend] = []
        cfg = monitor_config
        self.enabled = False
        if jax.process_index() != 0:
            return
        for cls, sub in ((TensorBoardMonitor, getattr(cfg, "tensorboard", None)),
                         (WandbMonitor, getattr(cfg, "wandb", None)),
                         (CometMonitor, getattr(cfg, "comet", None)),
                         (CSVMonitor, getattr(cfg, "csv_monitor", None))):
            if sub is not None and getattr(sub, "enabled", False):
                b = cls(sub)
                if b.enabled:
                    self.backends.append(b)
        self.enabled = bool(self.backends)

    def write_events(self, events: Sequence[Event]) -> None:
        for b in self.backends:
            b.write_events(events)

    def flush(self) -> None:
        for b in self.backends:
            b.flush()


def get_monitor(config) -> MonitorMaster:
    return MonitorMaster(config)
