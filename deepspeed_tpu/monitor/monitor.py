"""Metrics monitoring — fan-out to TensorBoard / WandB / Comet / CSV backends.

Reference parity: ``deepspeed/monitor/monitor.py:30 MonitorMaster`` with
``tensorboard.py``, ``wandb.py``, ``comet.py``, ``csv_monitor.py`` (the Comet
backend enables only when the comet_ml SDK imports). Each backend is
config-gated and degrades to disabled with a warning when its library is
missing. Events are ``(name, value, step)`` tuples, written by rank 0 only
(``jax.process_index() == 0``), matching the reference's rank-0 gating.
"""

from __future__ import annotations

import atexit
import csv
import json
import os
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class MonitorBackend:
    name = "base"

    def __init__(self, cfg):
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enabled", False))

    def write_events(self, events: Sequence[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        """Flush and release any held resources (file handles, SDK runs).
        Idempotent; called from engine shutdown / atexit so partial rows are
        never lost."""
        self.flush()


class TensorBoardMonitor(MonitorBackend):
    """Reference ``monitor/tensorboard.py``. Uses torch's SummaryWriter (cpu
    torch is in-image); falls back to tensorboardX if present."""

    name = "tensorboard"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.writer = None
        if not self.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            path = os.path.join(cfg.output_path or "runs", cfg.job_name)
            self.writer = SummaryWriter(log_dir=path)
        except Exception as e:
            logger.warning(f"tensorboard monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.writer:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))

    def flush(self) -> None:
        if self.writer:
            self.writer.flush()

    def close(self) -> None:
        if self.writer:
            self.writer.close()
            self.writer = None
        self.enabled = False


class WandbMonitor(MonitorBackend):
    """Reference ``monitor/wandb.py``; requires the wandb SDK."""

    name = "wandb"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.run = None
        if not self.enabled:
            return
        try:
            import wandb

            self.run = wandb.init(project=cfg.project or cfg.job_name,
                                  entity=cfg.team, group=cfg.group,
                                  dir=cfg.output_path or None)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.run:
            return
        for name, value, step in events:
            self._wandb.log({name: float(value)}, step=int(step))

    def close(self) -> None:
        if self.run:
            try:
                self.run.finish()
            except Exception:
                pass
            self.run = None
        self.enabled = False


class CometMonitor(MonitorBackend):
    """Reference ``monitor/comet.py``; requires the comet_ml SDK."""

    name = "comet"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.experiment = None
        if not self.enabled:
            return
        try:
            import comet_ml

            self.experiment = comet_ml.Experiment(
                project_name=getattr(cfg, "project", None) or cfg.job_name,
                workspace=getattr(cfg, "workspace", None) or
                getattr(cfg, "team", None))
            name = getattr(cfg, "experiment_name", None)
            if name:
                self.experiment.set_name(name)
        except Exception as e:
            logger.warning(f"comet monitor disabled: {e}")
            self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.experiment:
            return
        for name, value, step in events:
            self.experiment.log_metric(name, float(value), step=int(step))

    def flush(self) -> None:
        if self.experiment:
            self.experiment.flush()

    def close(self) -> None:
        if self.experiment:
            try:
                self.experiment.end()
            except Exception:
                pass
            self.experiment = None
        self.enabled = False


class CSVMonitor(MonitorBackend):
    """Reference ``monitor/csv_monitor.py`` — one CSV per metric name."""

    name = "csv"

    def __init__(self, cfg):
        super().__init__(cfg)
        self._files = {}
        if self.enabled:
            self.root = os.path.join(cfg.output_path or "csv_monitor",
                                     cfg.job_name)
            os.makedirs(self.root, exist_ok=True)

    def _writer(self, name: str):
        if name not in self._files:
            fn = os.path.join(self.root, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fn)
            f = open(fn, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name])
            self._files[name] = (f, w)
        return self._files[name]

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            f, w = self._writer(name)
            w.writerow([int(step), float(value)])

    def flush(self) -> None:
        for f, _ in self._files.values():
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files = {}
        self.enabled = False


class JSONLMonitor(MonitorBackend):
    """Append-only JSONL sink: one ``{"name", "value", "step", "ts"}`` object
    per line in ``<output_path>/<job_name>/events.jsonl``. The machine-readable
    counterpart of the CSV backend — a single ordered stream that
    ``scripts/telemetry_report.py`` can replay, and the sink the TelemetryHub
    acceptance path writes through.

    Size-capped rotation (``telemetry.jsonl_max_mb``, default off): when the
    file exceeds the cap it rotates to ``events.jsonl.1`` (one generation —
    bounded disk for week-long serving runs, and the report can still read
    the previous window). Reopening after a crash is torn-tail-safe: a final
    line the dying process tore mid-``write(2)`` is newline-terminated
    before new records append, so it stays ONE bad interior line instead of
    gluing onto the next record."""

    name = "jsonl"

    def __init__(self, cfg, max_mb: Optional[float] = None):
        super().__init__(cfg)
        self._f = None
        self.path: Optional[str] = None
        if max_mb is None:
            max_mb = getattr(cfg, "jsonl_max_mb", 0.0)
        self.max_bytes = int(float(max_mb or 0.0) * 1024 * 1024)
        if not self.enabled:
            return
        try:
            root = os.path.join(cfg.output_path or "jsonl_monitor",
                                cfg.job_name)
            os.makedirs(root, exist_ok=True)
            self.path = os.path.join(root, "events.jsonl")
            self._f = self._open_append(self.path)
        except Exception as e:
            logger.warning(f"jsonl monitor disabled: {e}")
            self.enabled = False

    @staticmethod
    def _open_append(path: str):
        torn = False
        try:
            if os.path.getsize(path) > 0:
                with open(path, "rb") as g:
                    g.seek(-1, os.SEEK_END)
                    torn = g.read(1) != b"\n"
        except OSError:  # no previous file — nothing to repair
            pass
        f = open(path, "a")
        if torn:
            f.write("\n")
            f.flush()
        return f

    def write_events(self, events: Sequence[Event]) -> None:
        # guard the CLOSED handle too, not just None: a failed rotation or
        # an out-of-order close()/atexit pair can leave _f set but closed,
        # and writing through it raises ValueError out of shutdown paths
        if self._f is None or self._f.closed:
            return
        now = time.time()
        for name, value, step in events:
            self._f.write(json.dumps({"name": name, "value": float(value),
                                      "step": int(step), "ts": now}) + "\n")
        # flush per batch, not only on close(): a crash/SIGKILL between
        # steps must not lose the tail of the step log (the flight-recorder
        # dump and the JSONL stream are the two post-mortem artifacts)
        self._f.flush()
        if self.max_bytes and self._f.tell() >= self.max_bytes:
            self._rotate()

    def _rotate(self) -> None:
        try:
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = self._open_append(self.path)
        except Exception as e:  # rotation is protective, never fatal
            logger.warning(f"jsonl rotation failed: {e}")
            if self._f is None or self._f.closed:
                try:
                    self._f = self._open_append(self.path)
                except Exception:
                    self.enabled = False
                    self._f = None

    def flush(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        """Idempotent and atexit-safe: tolerant of an already-closed handle
        (explicit close() THEN the MonitorMaster atexit hook, possibly with
        a rotation's handle swap in between)."""
        if self._f is not None:
            try:
                if not self._f.closed:
                    self._f.close()
            except Exception:
                pass
            self._f = None
        self.enabled = False


class MonitorMaster(MonitorBackend):
    """Fans every event out to all enabled backends (reference
    ``monitor.py:30``)."""

    name = "master"

    def __init__(self, monitor_config):
        self.backends: List[MonitorBackend] = []
        cfg = monitor_config
        self.enabled = False
        if jax.process_index() != 0:
            return
        for cls, sub in ((TensorBoardMonitor, getattr(cfg, "tensorboard", None)),
                         (WandbMonitor, getattr(cfg, "wandb", None)),
                         (CometMonitor, getattr(cfg, "comet", None)),
                         (CSVMonitor, getattr(cfg, "csv_monitor", None)),
                         (JSONLMonitor, getattr(cfg, "jsonl_monitor", None))):
            if sub is not None and getattr(sub, "enabled", False):
                if cls is JSONLMonitor:
                    # rotation cap lives in the telemetry block (the sink's
                    # own sub-config stays reference-shaped)
                    b = cls(sub, max_mb=getattr(
                        getattr(cfg, "telemetry", None), "jsonl_max_mb",
                        None))
                else:
                    b = cls(sub)
                if b.enabled:
                    self.backends.append(b)
        self.enabled = bool(self.backends)
        if self.backends:
            # engine shutdown calls close(); atexit is the backstop so an
            # interrupted run still lands its buffered rows on disk
            atexit.register(self.close)

    def write_events(self, events: Sequence[Event]) -> None:
        for b in self.backends:
            b.write_events(events)

    def flush(self) -> None:
        for b in self.backends:
            b.flush()

    def close(self) -> None:
        for b in self.backends:
            try:
                b.close()
            except Exception:
                pass
        if self.backends:
            try:
                atexit.unregister(self.close)
            except Exception:
                pass
        self.backends = []
        self.enabled = False


def get_monitor(config) -> MonitorMaster:
    return MonitorMaster(config)
