from .monitor import MonitorMaster, get_monitor  # noqa: F401
