from .monitor import (JSONLMonitor, MonitorBackend, MonitorMaster,  # noqa: F401
                      get_monitor)
