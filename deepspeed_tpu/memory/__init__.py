"""Tiered memory subsystem — host RAM (and disk) as explicit capacity tiers.

The ZeRO-Infinity direction (PAPERS.md, arXiv:2104.07857) for TPU: model
state larger than HBM-per-chip trains and serves by placing cold pytree
leaves on an explicit tier — HBM (``device``), pinned host RAM (``host``),
or a host-file "nvme" tier (``file``) — with asynchronous, double-buffered
device↔host transfers driven from a background transfer worker so the copies
hide behind compute.

Three layers:

- :mod:`placement` — memory-space capability probing and the in-jit /
  eager placement primitives (``to_host``/``to_device``/``move_tree``).
  On backends with real separate memory spaces (TPU ``pinned_host``) these
  lower to XLA host-memory annotations; on single-space backends (the CPU
  test mesh) eager moves fall back to :class:`~placement.HostBuffer` numpy
  residency and in-jit annotations are identity — same API, no branches in
  caller code.
- :mod:`tiered_store` — :class:`~tiered_store.TieredStore`: pytree
  offload/restore/prefetch across tiers, the shared
  :class:`~tiered_store.TransferWorker`, byte accounting per tier, and the
  ``Memory/tier/*`` telemetry series (transfer overlap fraction, prefetch
  hit/miss — telemetry/schema.py ``MEMORY_TIER_SERIES``).
- :mod:`kv_spill` — :class:`~kv_spill.HostKVPool`: the serving consumer's
  host pool for evicted prefix-cache KV blocks, keyed by the prefix index's
  chain hashes (``inference/ragged.py``; docs/memory.md).

Consumers: ``runtime/offload_states.py`` (the ``offload_states`` /
``reload_states`` engine API), the engine's ``memory.tiering``
optimizer-offload train path, ``runtime/superoffload.py``, and the v2
serving engine's ``inference.prefix_cache.host_spill`` path.
"""

from .kv_spill import HostKVPool
from .placement import (HostBuffer, default_memory_kind, host_memory_kind,
                        move_tree, offloaded_memory_kinds,
                        supports_memory_kind, to_device, to_host)
from .tiered_store import (PrefetchHandle, TieredStore, TransferWorker)

__all__ = [
    "HostBuffer", "HostKVPool", "PrefetchHandle", "TieredStore",
    "TransferWorker", "default_memory_kind", "host_memory_kind",
    "move_tree", "offloaded_memory_kinds", "supports_memory_kind",
    "to_device", "to_host",
]
