"""Memory-space placement primitives for the tiered memory subsystem.

One capability story for every backend:

- **TPU** exposes separate ``device`` (HBM) and ``pinned_host`` memory
  spaces; ``jax.device_put`` with a memory kind moves an array between them
  (async DMA over PCIe), and inside jit a ``TransferToMemoryKind``
  annotation lowers to an XLA host-memory (``S(5)``) placement the
  latency-hiding scheduler can stream around.
- the **CPU test mesh** has exactly one memory space (``unpinned_host``),
  so real memory-kind moves are impossible. Eager moves fall back to
  :class:`HostBuffer` — a numpy-resident leaf that carries its logical tier
  and original sharding so restore is exact — and in-jit annotations are
  identity. Callers write one code path; the semantics ("this leaf is on
  the host tier / bring it back") hold everywhere, and on CPU the
  host-tier leaves really do leave the device allocator (``HostBuffer`` is
  not a ``jax.Array``, so ``jax.live_arrays`` no longer counts it).

``offloaded_memory_kinds`` reports LOGICAL tier kinds: a leaf in its
device's default memory reports ``device`` (on CPU the default memory is
literally named ``unpinned_host`` — normalizing it keeps test and caller
logic backend-independent), a host-kind ``jax.Array`` or ``HostBuffer``
reports its host kind.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

import jax
import numpy as np

try:  # the in-jit memory-kind annotation (jax >= 0.4.35 public behavior;
    # the old ``jax.memory.Space`` aliases were removed)
    from jax._src.sharding_impls import TransferToMemoryKind
except ImportError:  # pragma: no cover - depends on jax version
    TransferToMemoryKind = None

PINNED = "pinned_host"
UNPINNED = "unpinned_host"

_KIND_CACHE: Dict[Any, Tuple[str, frozenset]] = {}


def _device_kinds(device=None) -> Tuple[str, frozenset]:
    """(default memory kind, all addressable kinds) for ``device``."""
    if device is None:
        device = jax.local_devices()[0]
    cached = _KIND_CACHE.get(device)
    if cached is not None:
        return cached
    try:
        default = device.default_memory().kind
        kinds = frozenset(m.kind for m in device.addressable_memories())
    except Exception:  # pragma: no cover - exotic backends
        default, kinds = "device", frozenset(["device"])
    _KIND_CACHE[device] = (default, kinds)
    return default, kinds


def default_memory_kind(device=None) -> str:
    return _device_kinds(device)[0]


def supports_memory_kind(kind: str, device=None) -> bool:
    return kind in _device_kinds(device)[1]


def host_memory_kind(device=None, pin: bool = True) -> Optional[str]:
    """The host-tier memory kind this backend can actually address, or None
    when the backend has no separate host space (single-memory backends —
    the CPU mesh — use the :class:`HostBuffer` fallback instead)."""
    default, kinds = _device_kinds(device)
    want = PINNED if pin else UNPINNED
    if want in kinds and want != default:
        return want
    # pin preference degrades rather than failing (e.g. a backend with only
    # an unpinned host space)
    other = UNPINNED if pin else PINNED
    if other in kinds and other != default:
        return other
    return None


# --------------------------------------------------------------------------- #
# in-jit annotations (traced values)
# --------------------------------------------------------------------------- #
def _tracing() -> bool:
    try:
        return not jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - depends on jax version
        return True


def to_host(x, pin: bool = True):
    """Place a value in host memory: a ``TransferToMemoryKind`` annotation
    under a trace (XLA host placement), a concrete sharding move eagerly.
    Identity when the backend has a single memory space."""
    kind = host_memory_kind(pin=pin)
    if kind is None or TransferToMemoryKind is None:
        return x
    if _tracing():
        return jax.device_put(x, TransferToMemoryKind(kind))
    return _leaf_to_host(x, pin)


def to_device(x):
    """Place a value back into device (HBM) memory — the inverse of
    :func:`to_host`, identity on single-memory backends."""
    if TransferToMemoryKind is None or host_memory_kind() is None:
        return x
    if _tracing():
        return jax.device_put(x, TransferToMemoryKind(default_memory_kind()))
    return _leaf_to_device(x)


def tree_to_host(tree, pin: bool = True):
    return jax.tree.map(lambda x: to_host(x, pin), tree)


def tree_to_device(tree):
    return jax.tree.map(to_device, tree)


# --------------------------------------------------------------------------- #
# eager moves (committed arrays)
# --------------------------------------------------------------------------- #
class HostBuffer:
    """A host-tier pytree leaf on backends without a separate host memory
    space: numpy residency + the logical memory kind + the sharding needed
    to restore the exact device layout. Quacks enough like an array
    (``shape``/``dtype``/``nbytes``/``__array__``) that generic consumers
    (checkpoint savers, byte accounting) keep working, but is NOT a
    ``jax.Array`` — host-tier leaves leave the device allocator for real."""

    __slots__ = ("data", "memory_kind", "sharding")

    def __init__(self, data: np.ndarray, memory_kind: str = PINNED,
                 sharding=None):
        self.data = data
        self.memory_kind = memory_kind
        self.sharding = sharding

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes)

    def __array__(self, dtype=None):
        return np.asarray(self.data, dtype)

    def __repr__(self):
        return (f"HostBuffer(shape={self.data.shape}, "
                f"dtype={self.data.dtype}, kind={self.memory_kind})")


def _leaf_to_host(leaf, pin: bool):
    if not isinstance(leaf, jax.Array):
        return leaf
    kind = host_memory_kind(pin=pin)
    logical = PINNED if pin else UNPINNED
    if kind is not None:
        sh = leaf.sharding
        if getattr(sh, "memory_kind", None) == kind:
            return leaf
        return jax.device_put(leaf, sh.with_memory_kind(kind))
    # single-memory backend: numpy residency, exact-restore metadata
    return HostBuffer(np.asarray(leaf), logical, sharding=leaf.sharding)


def _leaf_to_device(leaf):
    if isinstance(leaf, HostBuffer):
        if leaf.sharding is not None:
            return jax.device_put(leaf.data, leaf.sharding)
        return jax.device_put(leaf.data)
    if not isinstance(leaf, jax.Array):
        return leaf
    sh = leaf.sharding
    kind = getattr(sh, "memory_kind", None)
    default = default_memory_kind()
    if kind is None or kind == default:
        return leaf
    return jax.device_put(leaf, sh.with_memory_kind(default))


def move_tree(tree: Any, tier: str, pin: bool = True) -> Any:
    """Eagerly move every array leaf of ``tree`` onto ``tier`` (``"host"``
    or ``"device"``). Host moves use real memory kinds where the backend has
    them and :class:`HostBuffer` numpy residency otherwise; device moves
    invert either representation exactly (bit-identical roundtrip)."""
    if tier == "host":
        return jax.tree.map(lambda l: _leaf_to_host(l, pin), tree)
    if tier == "device":
        return jax.tree.map(_leaf_to_device, tree)
    raise ValueError(f"unknown placement tier {tier!r} (host|device)")


def offloaded_memory_kinds(tree: Any) -> Set[str]:
    """The set of LOGICAL memory kinds the array leaves of ``tree`` occupy:
    ``device`` for leaves in their device's default memory (whatever the
    backend names it), the host kind for host-tier leaves (real memory-kind
    arrays AND :class:`HostBuffer` fallbacks)."""
    kinds: Set[str] = set()
    default = default_memory_kind()
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, HostBuffer):
            kinds.add(leaf.memory_kind)
        elif isinstance(leaf, jax.Array):
            kind = getattr(leaf.sharding, "memory_kind", None)
            kinds.add("device" if kind is None or kind == default else kind)
    return kinds
