"""HostKVPool — host-RAM spill tier for evicted prefix-cache KV blocks.

The serving consumer of the tiered memory subsystem (docs/memory.md,
docs/serving.md): when the paged allocator's retained prefix pool evicts an
unreferenced block under allocation pressure, the block's KV contents are
copied to this host pool KEYED BY ITS EXISTING CHAIN HASH
(``inference/ragged.py PrefixBlockIndex`` — the key already proves the whole
token prefix, so a host entry is exactly as matchable as a resident block).
``admit_prompt`` extends its longest-resident-prefix match through the pool:
spilled blocks restore into freshly allocated device blocks and rejoin the
index, multiplying the retained pool past HBM.

Entries are ``(chain_hash → list of per-cache-leaf block arrays)``; the
device→host copy may ride a :class:`~tiered_store.TransferWorker` (async,
overlapped with serving compute) — ``get`` resolves any in-flight copy.
LRU-bounded by block count (``max_blocks``) with byte accounting.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np


class HostKVPool:
    def __init__(self, max_blocks: int = -1, worker: Any = None):
        self.max_blocks = int(max_blocks)
        self.worker = worker
        self._lock = threading.Lock()
        # hash → list-of-arrays OR a Future resolving to one
        self._entries: "OrderedDict[bytes, Any]" = OrderedDict()
        self._bytes: Dict[bytes, int] = {}
        self.stats: Dict[str, int] = {
            "spills": 0, "restores": 0, "spill_evictions": 0,
            "spilled_bytes": 0}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, h: bytes) -> bool:
        with self._lock:
            return h in self._entries

    @property
    def spilled_blocks(self) -> int:
        return len(self)

    @property
    def spilled_bytes(self) -> int:
        return int(self.stats["spilled_bytes"])

    @staticmethod
    def _nbytes(data: List[Any]) -> int:
        return int(sum(getattr(d, "nbytes", 0) for d in data))

    def put(self, h: bytes, block_data: List[Any]) -> None:
        """Store one evicted block's per-leaf KV arrays under its chain
        hash. ``block_data`` may be device arrays; the host materialization
        runs on the transfer worker when one is attached (the snapshot
        slices are already private copies, so the source block may be
        reused immediately). Over-cap inserts evict the pool's own LRU."""
        nbytes = self._nbytes(block_data)
        if self.worker is not None:
            entry = self.worker.submit(
                lambda data=block_data: [np.asarray(d) for d in data])
        else:
            entry = [np.asarray(d) for d in block_data]
        with self._lock:
            if h in self._entries:       # same prefix re-spilled: refresh
                self.stats["spilled_bytes"] -= self._bytes.pop(h, 0)
                self._entries.pop(h)
            self._entries[h] = entry
            self._bytes[h] = nbytes
            self.stats["spills"] += 1
            self.stats["spilled_bytes"] += nbytes
            while self.max_blocks >= 0 and len(self._entries) > self.max_blocks:
                old, _ = self._entries.popitem(last=False)
                self.stats["spilled_bytes"] -= self._bytes.pop(old, 0)
                self.stats["spill_evictions"] += 1

    def _resolve(self, h: bytes, entry: Any) -> Optional[List[np.ndarray]]:
        if hasattr(entry, "result"):     # in-flight D2H copy
            entry = entry.result()
            with self._lock:
                if h in self._entries:
                    self._entries[h] = entry
        return entry

    def get(self, h: bytes) -> Optional[List[np.ndarray]]:
        """The spilled block data for ``h`` (LRU-touched), or None."""
        with self._lock:
            entry = self._entries.get(h)
            if entry is not None:
                self._entries.move_to_end(h)
        if entry is None:
            return None
        return self._resolve(h, entry)

    def pop(self, h: bytes) -> Optional[List[np.ndarray]]:
        """Remove and return the entry for ``h`` (restore consumed it, or a
        resident canonical block makes the host copy redundant)."""
        with self._lock:
            entry = self._entries.pop(h, None)
            if entry is None:
                return None
            self.stats["spilled_bytes"] -= self._bytes.pop(h, 0)
        return self._resolve(h, entry)

    def note_restore(self) -> None:
        with self._lock:
            self.stats["restores"] += 1

    def keys(self):
        with self._lock:
            return list(self._entries.keys())

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes.clear()
            self.stats["spilled_bytes"] = 0
