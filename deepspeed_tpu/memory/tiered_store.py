"""TieredStore — pytree leaves on explicit memory tiers with async transfers.

The core of the tiered memory subsystem (docs/memory.md): place the array
leaves of a pytree on ``device`` (HBM), ``host`` (pinned host RAM), or
``file`` (the host-file "nvme" tier, backed by the ``swap_tensor`` aio
stack), and move them with asynchronous transfers driven from ONE background
:class:`TransferWorker` so device↔host copies hide behind compute.

Overlap accounting is measured, not asserted: the consumer brackets its
device compute with :meth:`TieredStore.compute_window`, the worker records
every transfer's wall interval, and ``overlap_frac`` is the measured
fraction of total transfer time that intersected a compute window — the
``Memory/tier/overlap_frac`` series the bench acceptance reads. The clock is
injectable for deterministic ordering tests.

Double-buffered prefetch: :meth:`prefetch` enqueues the host→device copies
for a tree and returns a :class:`PrefetchHandle`; ``handle.wait()`` that
finds every transfer already finished counts a prefetch HIT (the copy was
fully hidden), otherwise a MISS (the consumer blocked on the tail of the
transfer) — ``Memory/tier/prefetch_{hits,misses}``.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist
from . import placement
from .placement import HostBuffer

Event = Tuple[str, float, int]

TIERS = ("device", "host", "file")


class TransferWorker:
    """One daemon thread draining a FIFO of transfer jobs, with wall-clock
    accounting of how much transfer time was hidden under compute windows.

    Jobs are plain callables; :meth:`submit` returns a Future. The thread
    starts lazily on the first submit, so constructing a store (every engine
    does) costs nothing until a tier is actually used."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 name: str = "dstpu-tier-xfer"):
        self.clock = clock or time.monotonic
        self.name = name
        self._lock = threading.Lock()
        self._jobs: List[Tuple[Callable, Future]] = []
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # accounting (under _lock)
        self.busy_s = 0.0          # total transfer wall time
        self.overlap_s = 0.0       # transfer time inside compute windows
        self.jobs_done = 0
        self._win_open: Optional[float] = None   # open compute window start
        self._windows: List[Tuple[float, float]] = []  # closed, undrained

    # -- compute windows ------------------------------------------------- #
    def compute_begin(self) -> None:
        with self._lock:
            if self._win_open is None:
                self._win_open = self.clock()

    def compute_end(self) -> None:
        with self._lock:
            if self._win_open is not None:
                self._windows.append((self._win_open, self.clock()))
                if len(self._windows) > 256:
                    del self._windows[:-256]
                self._win_open = None

    def _overlap_of(self, t0: float, t1: float) -> float:
        """Intersection of [t0, t1] with the recorded compute windows (call
        under _lock)."""
        ov = 0.0
        for w0, w1 in self._windows:
            ov += max(0.0, min(t1, w1) - max(t0, w0))
        if self._win_open is not None:
            ov += max(0.0, t1 - max(t0, self._win_open))
        return ov

    # -- job loop -------------------------------------------------------- #
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=self.name)
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._wake:
                while not self._jobs and not self._closed:
                    self._wake.wait(timeout=1.0)
                if self._closed and not self._jobs:
                    return
                fn, fut = self._jobs.pop(0)
            t0 = self.clock()
            try:
                result = fn()
            except BaseException as e:  # delivered at .result()
                fut.set_exception(e)
            else:
                fut.set_result(result)
            t1 = self.clock()
            with self._lock:
                self.busy_s += t1 - t0
                self.overlap_s += self._overlap_of(t0, t1)
                self.jobs_done += 1

    def submit(self, fn: Callable[[], Any]) -> Future:
        fut: Future = Future()
        with self._wake:
            if self._closed:
                raise RuntimeError("TransferWorker is closed")
            self._jobs.append((fn, fut))
            self._wake.notify()
        self._ensure_thread()
        return fut

    def drain(self) -> None:
        """Block until every previously submitted job has run (a sentinel
        job is the fence; FIFO order guarantees it runs last)."""
        if self._thread is not None and self._thread.is_alive():
            self.submit(lambda: None).result()

    def overlap_frac(self) -> float:
        with self._lock:
            return self.overlap_s / self.busy_s if self.busy_s > 0 else 0.0

    def close(self) -> None:
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)


class PrefetchHandle:
    """Futures for one prefetched pytree. ``wait()`` assembles the restored
    tree; it counts a HIT on the owning store when every transfer had
    already finished (the copy was fully hidden behind compute)."""

    def __init__(self, store: "TieredStore", treedef, futures: List[Future],
                 passthrough: List[Any], mask: List[bool]):
        self._store = store
        self._treedef = treedef
        self._futures = futures
        self._passthrough = passthrough
        self._mask = mask
        self._done = False

    def ready(self) -> bool:
        return all(f.done() for f in self._futures)

    def wait(self):
        if self._done:
            raise RuntimeError("PrefetchHandle.wait() called twice")
        self._done = True
        hit = self.ready()
        self._store._note_prefetch(hit)
        leaves, fi = [], 0
        for is_fut, leaf in zip(self._mask, self._passthrough):
            if is_fut:
                leaves.append(self._futures[fi].result())
                fi += 1
            else:
                leaves.append(leaf)
        return jax.tree_util.tree_unflatten(self._treedef, leaves)


class TieredStore:
    """Explicit-tier placement for pytrees with async double-buffered
    transfers and closed ``Memory/tier/*`` telemetry.

    ``host`` tier: real memory-kind arrays where the backend has a host
    space, :class:`HostBuffer` numpy residency otherwise (see
    :mod:`placement`). ``file`` tier: one ``.swp`` file per leaf through the
    ``swap_tensor`` aio stack (``AsyncTensorSwapper``) — leaves become
    ``SwappedTensorMeta`` records. Byte accounting per tier feeds
    ``Memory/tier/resident_bytes_{host,file}`` / ``spilled_bytes``;
    transfers feed ``transfer_{d2h,h2d}_bytes`` and the worker's measured
    ``overlap_frac`` (see module docstring)."""

    def __init__(self, config: Any = None, *,
                 nvme_dir: Optional[str] = None,
                 pin_memory: Optional[bool] = None,
                 clock: Optional[Callable[[], float]] = None,
                 worker: Optional[TransferWorker] = None):
        self.cfg = config
        self.pin = bool(getattr(config, "pin_memory", True)
                        if pin_memory is None else pin_memory)
        self.nvme_dir = nvme_dir or getattr(config, "nvme_path", None)
        self.worker = worker or TransferWorker(clock=clock)
        self._swappers: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.stats: Dict[str, float] = {
            "resident_bytes_host": 0.0, "resident_bytes_file": 0.0,
            "transfer_d2h_bytes": 0.0, "transfer_h2d_bytes": 0.0,
            "prefetch_hits": 0.0, "prefetch_misses": 0.0,
            "offloads": 0.0, "restores": 0.0,
        }

    # -- accounting ------------------------------------------------------ #
    def _track(self, key: str, delta: float) -> None:
        with self._lock:
            self.stats[key] = self.stats.get(key, 0.0) + delta

    def _note_prefetch(self, hit: bool) -> None:
        self._track("prefetch_hits" if hit else "prefetch_misses", 1.0)

    def resident_bytes(self, tier: str) -> int:
        return int(self.stats.get(f"resident_bytes_{tier}", 0.0))

    def overlap_frac(self) -> float:
        return self.worker.overlap_frac()

    @contextmanager
    def compute_window(self):
        """Bracket device compute so transfer overlap can be measured."""
        self.worker.compute_begin()
        try:
            yield
        finally:
            self.worker.compute_end()

    # -- host tier ------------------------------------------------------- #
    @staticmethod
    def _leaf_bytes(leaf) -> int:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            return int(nbytes)
        try:
            return int(np.prod(leaf.shape, dtype=np.int64)
                       * np.dtype(leaf.dtype).itemsize)
        except Exception:
            return 0

    def offload(self, tree: Any, tier: str = "host", *,
                name: str = "tree", async_: bool = True) -> Any:
        """Move the array leaves of ``tree`` to ``tier``; returns the
        replaced tree immediately. Host-tier D2H copies run on the transfer
        worker when ``async_`` (single-memory backends — the copies are real
        numpy materializations there); leaves carry futures transparently:
        the returned tree's ``HostBuffer`` data fields are filled when the
        worker finishes, and :meth:`restore`/:meth:`prefetch` synchronize.
        ``file`` tier writes through the aio swapper (bounded, synchronous
        publish so the ``.swp`` files exist on return)."""
        if tier == "file":
            return self._offload_file(tree, name)
        if tier != "host":
            raise ValueError(f"offload tier {tier!r} not in ('host', 'file')")
        kind = placement.host_memory_kind(pin=self.pin)

        def one(leaf):
            if not isinstance(leaf, jax.Array):
                return leaf
            n = self._leaf_bytes(leaf)
            self._track("transfer_d2h_bytes", n)
            self._track("resident_bytes_host", n)
            if kind is not None:
                # real host memory space: device_put is itself async DMA
                sh = leaf.sharding
                if getattr(sh, "memory_kind", None) == kind:
                    return leaf
                return jax.device_put(leaf, sh.with_memory_kind(kind))
            buf = HostBuffer(None, placement.PINNED if self.pin
                             else placement.UNPINNED, sharding=leaf.sharding)
            if async_:
                fut = self.worker.submit(lambda l=leaf: np.asarray(l))
                buf.data = _LazyArray(fut, leaf.shape, leaf.dtype)
            else:
                buf.data = np.asarray(leaf)
            return buf

        out = jax.tree.map(one, tree)
        self._track("offloads", 1.0)
        return out

    def restore(self, tree: Any, shardings: Any = None) -> Any:
        """Bring every offloaded leaf of ``tree`` back to device memory,
        synchronously (prefetch + wait). ``shardings``: optional pytree of
        target shardings overriding each leaf's recorded one."""
        return self.prefetch(tree, shardings).wait()

    def prefetch(self, tree: Any, shardings: Any = None) -> PrefetchHandle:
        """Enqueue host→device copies for every offloaded leaf; returns a
        :class:`PrefetchHandle` (``wait()`` → restored tree). File-tier
        leaves issue their aio reads first, then device_put on the worker."""
        leaves, treedef = jax.tree_util.tree_flatten(
            tree, is_leaf=_is_tier_leaf)
        sh_leaves = [None] * len(leaves)
        if shardings is not None:
            sh_flat = jax.tree_util.tree_flatten(shardings)[0]
            if len(sh_flat) == len(leaves):
                sh_leaves = sh_flat
        futures: List[Future] = []
        mask: List[bool] = []
        for leaf, sh in zip(leaves, sh_leaves):
            job = self._restore_job(leaf, sh)
            if job is None:
                mask.append(False)
            else:
                futures.append(self.worker.submit(job))
                mask.append(True)
        self._track("restores", 1.0)
        return PrefetchHandle(self, treedef, futures, leaves, mask)

    def _restore_job(self, leaf, sharding) -> Optional[Callable[[], Any]]:
        from ..runtime.swap_tensor.swapper import SwappedTensorMeta

        if isinstance(leaf, HostBuffer):
            n = self._leaf_bytes(leaf)

            def job(buf=leaf, sh=sharding, n=n):
                data = buf.data
                if isinstance(data, _LazyArray):
                    data = data.resolve()
                self._track("transfer_h2d_bytes", n)
                self._track("resident_bytes_host", -n)
                target = sh if sh is not None else buf.sharding
                return jax.device_put(data, target) if target is not None \
                    else jax.device_put(data)

            return job
        if isinstance(leaf, SwappedTensorMeta):
            swapper = self._swapper_for(leaf)
            buf = swapper.start_swap_in(leaf)  # aio read issued NOW
            n = leaf.nbytes()

            def job(meta=leaf, buf=buf, sw=swapper, sh=sharding, n=n):
                sw.wait()
                self._track("transfer_h2d_bytes", n)
                self._track("resident_bytes_file", -n)
                sw.remove(meta)
                return jax.device_put(buf, sh) if sh is not None \
                    else jax.device_put(buf)

            return job
        if isinstance(leaf, jax.Array):
            kind = getattr(leaf.sharding, "memory_kind", None)
            default = placement.default_memory_kind()
            if kind is not None and kind != default:
                n = self._leaf_bytes(leaf)

                def job(l=leaf, n=n):
                    self._track("transfer_h2d_bytes", n)
                    self._track("resident_bytes_host", -n)
                    return jax.device_put(
                        l, l.sharding.with_memory_kind(default))

                return job
        return None

    # -- file tier ------------------------------------------------------- #
    def _file_dir(self, name: str) -> str:
        import tempfile

        base = self.nvme_dir or os.path.join(tempfile.gettempdir(),
                                             "dstpu_tier_file")
        return os.path.join(base, name)

    def _swapper_for(self, meta) -> Any:
        from ..runtime.swap_tensor.swapper import AsyncTensorSwapper

        d = os.path.dirname(meta.path)
        if d not in self._swappers:
            self._swappers[d] = AsyncTensorSwapper(d)
        return self._swappers[d]

    def _offload_file(self, tree: Any, name: str) -> Any:
        from ..runtime.swap_tensor.swapper import AsyncTensorSwapper

        swap_dir = self._file_dir(name)
        swapper = self._swappers.get(swap_dir)
        if swapper is None:
            swapper = self._swappers[swap_dir] = AsyncTensorSwapper(swap_dir)
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        from ..utils.tree import path_to_str

        metas = []
        for i, (path, leaf) in enumerate(flat):
            if not isinstance(leaf, (jax.Array, np.ndarray, HostBuffer)):
                metas.append(leaf)
                continue
            arr = np.asarray(leaf)
            n = int(arr.nbytes)
            self._track("transfer_d2h_bytes", n)
            self._track("resident_bytes_file", n)
            metas.append(swapper.swap_out(
                f"{i:05d}_{path_to_str(path, '_') or 'leaf'}", arr))
        swapper.wait()
        self._track("offloads", 1.0)
        log_dist(f"TieredStore: {len(metas)} leaves -> file tier ({swap_dir})")
        return jax.tree_util.tree_unflatten(treedef, metas)

    # -- telemetry ------------------------------------------------------- #
    def events(self, step: int = 0) -> List[Event]:
        """Closed ``Memory/tier/*`` series (telemetry/schema.py
        MEMORY_TIER_SERIES) for one drain point."""
        with self._lock:
            snap = dict(self.stats)
        with self.worker._lock:
            busy, ov = self.worker.busy_s, self.worker.overlap_s
        evs = [(f"Memory/tier/{k}", float(v), step)
               for k, v in sorted(snap.items())]
        evs.append(("Memory/tier/transfer_busy_ms", busy * 1e3, step))
        evs.append(("Memory/tier/overlap_ms", ov * 1e3, step))
        evs.append(("Memory/tier/overlap_frac",
                    ov / busy if busy > 0 else 0.0, step))
        return evs

    def close(self) -> None:
        self.worker.close()


class _LazyArray:
    """A numpy-array-to-be: the D2H copy is still on the worker. Resolves
    (and caches) on first use; ``HostBuffer.__array__`` reaches it through
    ``np.asarray``."""

    __slots__ = ("_fut", "shape", "dtype", "_value")

    def __init__(self, fut: Future, shape, dtype):
        self._fut = fut
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self._value = None

    def resolve(self) -> np.ndarray:
        if self._value is None:
            self._value = self._fut.result()
        return self._value

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64) * self.dtype.itemsize)

    def __array__(self, dtype=None):
        return np.asarray(self.resolve(), dtype)


def _is_tier_leaf(x) -> bool:
    from ..runtime.swap_tensor.swapper import SwappedTensorMeta

    return isinstance(x, (HostBuffer, SwappedTensorMeta))
