"""Collectives API with telemetry — capability parity with ``deepspeed/comm``.

The reference exposes a ``torch.distributed``-mirror (``comm/comm.py:223-680``:
all_reduce / all_gather / reduce_scatter / all_to_all / broadcast / barrier /
send / recv, each wrapped by ``timed_op`` for logging) backed by NCCL.

On TPU there is no runtime RPC layer: collectives are *traced* ops compiled by
XLA onto ICI/DCN. This module therefore provides:

- traced collectives over named mesh axes (``lax.psum`` etc.) for use inside
  ``shard_map``/``jit`` — with a byte/op telemetry recorder that observes them
  at trace time (the comms-logger parity, see ``utils/comms_logging.py`` in the
  reference);
- host-level helpers (``init_distributed``, ``barrier``, ``broadcast_host``)
  for the small amount of genuinely-runtime coordination (bootstrap, ckpt
  rendezvous), built on ``jax.distributed`` + ``jax.experimental.multihost_utils``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.logging import log_dist, logger

AxisName = Union[str, Sequence[str]]


# --------------------------------------------------------------------------- #
# telemetry (comms-logger parity)
# --------------------------------------------------------------------------- #
def _tree_bytes(x: Any) -> tuple:
    """Total payload bytes + element count + representative shape(s) for an
    arbitrary pytree (arrays, scalars, dicts/lists of either). Leaves that
    carry no countable payload (strings, None) contribute zero instead of
    poisoning the total. Element count feeds the default fp32-equivalent
    accounting (what the payload would weigh uncompressed at fp32)."""
    total = 0
    elems = 0
    shapes = []
    for leaf in jax.tree_util.tree_leaves(x):
        try:
            shp = tuple(np.shape(leaf))
            n = int(np.prod(shp, dtype=np.int64))
            total += n * jnp.result_type(leaf).itemsize
            elems += n
            shapes.append(shp)
        except Exception:
            continue
    shape = shapes[0] if len(shapes) == 1 else tuple(shapes)
    return total, elems, shape


def _axis_world(axis: AxisName) -> int:
    """Members of the axis (product over tuple axes); 0 when unknown. Reads
    the installed global mesh only — never creates one as a side effect."""
    names = axis if isinstance(axis, (tuple, list)) else (axis,)
    try:
        from . import mesh as _mesh_mod

        mm = _mesh_mod._global_mesh
        if mm is None:
            return 0
        return int(np.prod([mm.axis_size(a) for a in names]))
    except Exception:
        return 0


# busbw convention (NCCL-style): wire bytes per member as a function of the
# payload and the axis world size n. Keyed by op-name prefix.
_ALGO_FACTORS = (
    ("all_reduce", lambda b, n: 2.0 * b * (n - 1) / n),
    ("inference_all_reduce", lambda b, n: 2.0 * b * (n - 1) / n),
    ("all_gather", lambda b, n: float(b) * (n - 1)),
    ("reduce_scatter", lambda b, n: b * (n - 1) / n),
    ("all_to_all", lambda b, n: b * (n - 1) / n),
    ("gather", lambda b, n: float(b) * (n - 1)),
)


def _algo_bytes(op: str, nbytes: int, world: int) -> float:
    """Estimated algorithmic ("bus") bytes a member puts on the wire."""
    if world == 1:
        return 0.0
    if world <= 0:  # axis size unknown at record time — report the payload
        return float(nbytes)
    for prefix, f in _ALGO_FACTORS:
        if op.startswith(prefix):
            return f(nbytes, world)
    return float(nbytes)  # broadcast / ppermute / send_recv / scatter


def _link_class(axis: AxisName) -> str:
    """Classify the slowest link tier a collective over ``axis`` crosses:
    ``"dcn"`` when any named axis is in the installed mesh's ``dcn_axes``
    (the cross-island tier — multi-slice DCN, or the 2-level ``data`` axis
    of an hpZ/MiCS carve) with size > 1, else ``"ici"``. Unknown mesh →
    ``"ici"`` (single-tier)."""
    names = axis if isinstance(axis, (tuple, list)) else (axis,)
    try:
        from . import mesh as _mesh_mod

        mm = _mesh_mod._global_mesh
        if mm is None:
            return "ici"
        dcn = tuple(getattr(mm, "dcn_axes", ()) or ())
        for a in names:
            if a in dcn and mm.axis_size(a) > 1:
                return "dcn"
    except Exception:
        pass
    return "ici"


def _trace_site() -> str:
    """Nearest stack frame outside this module — where the collective was
    issued from (the reference comms logger's caller_func analog)."""
    import traceback

    this = os.path.abspath(__file__)
    for fr in reversed(traceback.extract_stack()):
        if os.path.abspath(fr.filename) != this:
            return f"{os.path.basename(fr.filename)}:{fr.lineno}"
    return "?"


@dataclass
class CommsTelemetry:
    """Records every traced collective: op name, axis, payload bytes,
    trace-site, and estimated algorithmic (bus) bytes. Since collectives are
    compile-time constructs, records are per-trace (not per-step) — one entry
    describes what every execution of the compiled step does. Byte accounting
    is pytree-aware: payloads may be arrays, scalars, or nested containers.

    ``repeats`` covers collectives traced once but executed several times per
    step (a ``lax.scan`` body over GAS micro-batches): the record carries the
    per-execution payload and the summary multiplies count/bytes by
    ``repeats``, so per-step volume comparisons (per-micro vs deferred
    reduction) stay honest.

    ``prof_all``/``prof_ops`` mirror the reference comms-logger config
    (``utils/comms_logging.py``): with ``prof_all`` off, only ops whose name
    starts with an entry of ``prof_ops`` are recorded."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)
    records: List[Dict[str, Any]] = field(default_factory=list)
    ring_stats: Dict[str, float] = field(default_factory=dict)

    def _profiled(self, op: str) -> bool:
        if self.prof_all:
            return True
        return any(op == p or op.startswith(p) for p in self.prof_ops)

    def record(self, op: str, axis: AxisName, x: Any,
               repeats: int = 1, fp32_equiv: Optional[float] = None) -> None:
        """``fp32_equiv``: bytes the payload would weigh uncompressed at
        fp32. Defaults to element-count × 4; quantized collectives pass the
        SOURCE element count explicitly (their int8+scales payload carries
        more elements than the fp32 tensor it encodes), so the per-op
        compression ratio fp32_equiv/bytes stays honest."""
        if not self.enabled or not self._profiled(op):
            return
        nbytes, elems, shape = _tree_bytes(x)
        world = _axis_world(axis)
        rec = {"op": op, "axis": axis, "bytes": nbytes, "shape": shape,
               "world": world, "algo_bytes": _algo_bytes(op, nbytes, world),
               "repeats": max(int(repeats), 1), "site": _trace_site(),
               "link": _link_class(axis),
               "fp32_equiv_bytes": (float(fp32_equiv)
                                    if fp32_equiv is not None
                                    else float(elems * 4))}
        self.records.append(rec)
        if self.verbose:
            logger.info(f"comm: {op} over {axis}: {nbytes} bytes "
                        f"{rec['shape']} from {rec['site']}")

    def record_ring(self, key: str, value: float,
                    accumulate: bool = True) -> None:
        """Ring-attention series (``Comm/ring/<key>`` — the closed
        ``telemetry.schema.COMM_RING_SERIES`` registry): trace-time
        hop/byte counters from ``sequence.ring``, the host-measured
        ``overlap_frac`` gauge, and the dense-fallback marker. Unlike
        ``record`` this is NOT gated on ``enabled`` — the dense-fallback
        marker must surface even when the comms logger is off."""
        v = float(value)
        if accumulate:
            self.ring_stats[key] = self.ring_stats.get(key, 0.0) + v
        else:
            self.ring_stats[key] = v

    def summary(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for r in self.records:
            s = out.setdefault(r["op"], {"count": 0, "bytes": 0,
                                         "algo_bytes": 0.0,
                                         "algo_bytes_dcn": 0.0,
                                         "algo_bytes_ici": 0.0,
                                         "fp32_equiv_bytes": 0.0,
                                         "sites": []})
            rep = max(int(r.get("repeats", 1)), 1)
            s["count"] += rep
            s["bytes"] += max(r["bytes"], 0) * rep
            algo = max(r.get("algo_bytes", 0.0), 0.0) * rep
            s["algo_bytes"] += algo
            s["algo_bytes_" + r.get("link", "ici")] += algo
            s["fp32_equiv_bytes"] += \
                max(r.get("fp32_equiv_bytes", 0.0), 0.0) * rep
            site = r.get("site")
            if site and site not in s["sites"]:
                s["sites"].append(site)
        return out

    def total_algo_bytes(self, link: Optional[str] = None) -> float:
        """Per-step algorithmic bytes across every recorded collective;
        ``link`` = "dcn" | "ici" restricts to that link class."""
        key = "algo_bytes" if link is None else f"algo_bytes_{link}"
        return sum(s[key] for s in self.summary().values())

    def log_summary(self, step_time_s: Optional[float] = None) -> None:
        """Periodic per-op rollup (reference ``log_summary()``); with a step
        time, adds the estimated algorithmic bandwidth of the compiled step."""
        for op, s in sorted(self.summary().items()):
            msg = (f"comm summary | {op}: count={s['count']} "
                   f"bytes={s['bytes']:,} algo_bytes={s['algo_bytes']:,.0f}")
            if step_time_s:
                msg += f" busbw~{s['algo_bytes'] / step_time_s / 1e9:.2f} GB/s"
            if s["sites"]:
                msg += f" sites={','.join(s['sites'][:4])}"
            logger.info(msg)

    def events(self, step: int) -> List[tuple]:
        """Monitor events (``Comm/<op>/{bytes,count,algo_bytes,
        algo_bytes_dcn,algo_bytes_ici,fp32_equiv_bytes}``) for the current
        trace records — cumulative per trace, constant across executed
        steps. The metric suffixes form the closed ``telemetry.schema.
        COMM_METRICS`` registry; a new suffix here must be registered
        there."""
        ev = []
        for op, s in sorted(self.summary().items()):
            ev.append((f"Comm/{op}/bytes", float(s["bytes"]), step))
            ev.append((f"Comm/{op}/count", float(s["count"]), step))
            ev.append((f"Comm/{op}/algo_bytes", float(s["algo_bytes"]), step))
            ev.append((f"Comm/{op}/algo_bytes_dcn",
                       float(s["algo_bytes_dcn"]), step))
            ev.append((f"Comm/{op}/algo_bytes_ici",
                       float(s["algo_bytes_ici"]), step))
            ev.append((f"Comm/{op}/fp32_equiv_bytes",
                       float(s["fp32_equiv_bytes"]), step))
        for key, val in sorted(self.ring_stats.items()):
            ev.append((f"Comm/ring/{key}", float(val), step))
        return ev

    def reset(self) -> None:
        self.records.clear()
        self.ring_stats.clear()


_telemetry = CommsTelemetry()


def get_telemetry() -> CommsTelemetry:
    return _telemetry


def configure(enabled: bool = False, verbose: bool = False,
              prof_all: bool = True, prof_ops: Optional[List[str]] = None,
              debug: bool = False) -> None:
    """Reference parity: ``dist.configure(config)`` enabling the comms logger."""
    _telemetry.enabled = enabled
    _telemetry.verbose = verbose
    _telemetry.prof_all = prof_all
    _telemetry.prof_ops = list(prof_ops or [])
    _telemetry.debug = debug


# --------------------------------------------------------------------------- #
# shard_map across jax versions
# --------------------------------------------------------------------------- #
def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma: bool = False):
    """Version-portable ``shard_map``: newer jax exposes ``jax.shard_map``
    with ``axis_names``/``check_vma``; 0.4-era jax has
    ``jax.experimental.shard_map.shard_map`` where partial-manual regions are
    spelled as ``auto=<complement>`` and the replication check is
    ``check_rep``. Every manual collective region in the framework goes
    through this one shim so a jax upgrade is a one-line change."""
    if hasattr(jax, "shard_map"):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(set(mesh.axis_names) - set(axis_names))
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=bool(check_vma), auto=auto)


# --------------------------------------------------------------------------- #
# traced collectives (use inside shard_map / jit with named axes)
# --------------------------------------------------------------------------- #
def all_reduce(x, axis: AxisName, op: str = "sum"):
    """psum/pmax/pmin/pmean over a mesh axis (reference ``dist.all_reduce``)."""
    _telemetry.record(f"all_reduce_{op}", axis, x)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op in ("mean", "avg"):
        return lax.pmean(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along ``gather_axis`` (reference ``dist.all_gather_into_tensor``)."""
    _telemetry.record("all_gather", axis, x)
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0, op: str = "sum"):
    """Sum-reduce then scatter along ``scatter_axis`` (reference
    ``dist.reduce_scatter_tensor``)."""
    _telemetry.record("reduce_scatter", axis, x)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    """Ulysses-style all-to-all (reference ``dist.all_to_all_single``,
    ``sequence/layer.py single_all_to_all``)."""
    _telemetry.record("all_to_all", axis, x)
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)


def ppermute(x, axis: AxisName, perm: Sequence[tuple]):
    """Point-to-point ring shift — the TPU replacement for the reference's
    ``runtime/pipe/p2p.py`` send/recv pairs."""
    _telemetry.record("ppermute", axis, x)
    return lax.ppermute(x, axis, perm=perm)


def ring_shift(x, axis: str, axis_size: int, shift: int = 1):
    """Shift shards around the ring by ``shift`` (ring attention building block)."""
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
    return ppermute(x, axis, perm)


def broadcast(x, axis: AxisName, src_index: int = 0):
    """Broadcast the ``src_index`` shard to all members of the axis."""
    _telemetry.record("broadcast", axis, x)
    full = lax.all_gather(x, axis, axis=0, tiled=False)
    return full[src_index]


def send_recv(x, axis: AxisName, src: int, dst: int):
    """Single point-to-point transfer (reference ``dist.send/recv``): every
    member passes its value; the ``dst`` member receives ``src``'s value,
    all others receive zeros (collective semantics of p2p under SPMD)."""
    _telemetry.record("send_recv", axis, x)
    return lax.ppermute(x, axis, perm=[(src, dst)])


def gather(x, axis: AxisName, dst: int = 0):
    """Gather all shards to the ``dst`` member, zeros elsewhere (reference
    ``dist.gather``). Under SPMD every member computes the gather; masking
    keeps only the root's copy live so XLA can DCE the rest."""
    _telemetry.record("gather", axis, x)
    full = lax.all_gather(x, axis, axis=0, tiled=False)
    keep = lax.axis_index(axis) == dst
    return jnp.where(keep, full, jnp.zeros_like(full))


def scatter(x, axis: AxisName, src: int = 0):
    """Scatter the ``src`` member's leading-dim chunks over the axis
    (reference ``dist.scatter``). x: [axis_size, ...] on src."""
    _telemetry.record("scatter", axis, x)
    from_src = broadcast(x, axis, src_index=src)
    return from_src[lax.axis_index(axis)]


def inference_all_reduce(x, axis: AxisName = "tensor"):
    """TP-forward allreduce (reference ``dist.inference_all_reduce`` — same
    wire op, separate name so comm logs can distinguish serving traffic)."""
    _telemetry.record("inference_all_reduce", axis, x)
    return lax.psum(x, axis)


def monitored_barrier(name: str = "dstpu_barrier", timeout: Optional[float] = None):
    """Reference ``dist.monitored_barrier``: a barrier that DETECTS stragglers
    — raises within ``timeout`` seconds if the barrier does not complete
    (e.g. a dead host), instead of hanging forever."""
    import threading as _threading
    import time as _time

    t0 = _time.perf_counter()
    if timeout is None:
        barrier(name)
        return _time.perf_counter() - t0
    err: list = []
    done = _threading.Event()

    def _run():
        try:
            barrier(name)
        except Exception as e:  # surfaced below
            err.append(e)
        finally:
            done.set()

    t = _threading.Thread(target=_run, daemon=True, name=f"barrier:{name}")
    t.start()
    if not done.wait(timeout):
        raise RuntimeError(f"monitored_barrier '{name}' timed out after "
                           f"{timeout}s — straggler or dead process")
    if err:
        raise err[0]
    return _time.perf_counter() - t0


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    from .mesh import get_mesh

    return get_mesh().axis_size(axis)


# --------------------------------------------------------------------------- #
# host-level runtime coordination
# --------------------------------------------------------------------------- #
_initialized = False


def resolve_process_id() -> int:
    """Rank resolution for the multi-host bootstrap: launcher env first;
    then the transport's own rank var — the MPI-family runners export its
    NAME via ``DSTPU_RANK_ENV`` (OMPI_COMM_WORLD_RANK / PMI_RANK /
    MV2_COMM_WORLD_RANK) since one mpirun command line cannot carry per-rank
    ids — and SLURM rank as the final fallback (same single-command
    limitation)."""
    pid = os.environ.get("DSTPU_PROCESS_ID")
    if pid is None and (rank_env := os.environ.get("DSTPU_RANK_ENV")):
        pid = os.environ.get(rank_env)
    if pid is None:
        pid = os.environ.get("SLURM_PROCID", 0)
    return int(pid)


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True,
                     **kwargs) -> None:
    """Multi-host bootstrap (reference ``comm.init_distributed`` ``comm/comm.py:788``).

    On TPU pods the runtime handles rendezvous natively; ``jax.distributed
    .initialize`` is only needed for multi-process CPU/GPU or explicit
    coordinator setups. Single-process: no-op.
    """
    global _initialized
    if _initialized:
        return
    env_procs = os.environ.get("DSTPU_NUM_PROCESSES")
    if coordinator_address is None:
        coordinator_address = os.environ.get("DSTPU_COORDINATOR")
    if coordinator_address is None and env_procs is None and num_processes is None:
        _initialized = True  # single-process / TPU-native bootstrap
        log_dist("init_distributed: single-process or TPU-native rendezvous")
        return
    try:
        if process_id is None:
            process_id = resolve_process_id()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes or int(env_procs or 1),
            process_id=process_id)
        _initialized = True
        log_dist(f"init_distributed: {jax.process_count()} processes")
    except Exception as e:  # already initialised by the launcher
        logger.warning(f"jax.distributed.initialize skipped: {e}")
        _initialized = True


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def barrier(name: str = "dstpu_barrier") -> None:
    """Host-level barrier across processes (reference ``dist.barrier``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_host(value, src: int = 0):
    """Broadcast host data from one process to all (ckpt tags etc.)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value, is_source=jax.process_index() == src)


def all_gather_object(obj):
    """Gather one picklable host object per process → list ordered by rank
    (reference ``dist.all_gather_object`` :247). Two phases: agree on the max
    pickle size, then gather fixed-width byte buffers."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))
    width = int(sizes.max())
    padded = np.zeros((width,), np.uint8)
    padded[:payload.size] = payload
    gathered = multihost_utils.process_allgather(padded)
    return [pickle.loads(gathered[r, :int(sizes[r, 0])].tobytes())
            for r in range(jax.process_count())]


def broadcast_object_list(object_list, src: int = 0):
    """In-place broadcast of a list of picklable objects from ``src``
    (reference ``dist.broadcast_object_list`` :229). Only ``src`` pickles —
    non-src placeholders may be unpicklable, matching the torch contract —
    and the wire carries one payload, not an all-gather."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return object_list
    from jax.experimental import multihost_utils

    is_src = jax.process_index() == src
    payload = (np.frombuffer(pickle.dumps(list(object_list)), np.uint8)
               if is_src else np.zeros((0,), np.uint8))
    size = multihost_utils.broadcast_one_to_all(
        np.asarray([payload.size], np.int64), is_source=is_src)
    width = int(size[0])
    padded = np.zeros((width,), np.uint8)
    if is_src:
        padded[:payload.size] = payload
    data = multihost_utils.broadcast_one_to_all(padded, is_source=is_src)
    for i, obj in enumerate(pickle.loads(np.asarray(data).tobytes())):
        object_list[i] = obj
    return object_list
