from .comm import (all_gather, all_reduce, all_to_all, axis_index, axis_size, barrier,
                   broadcast, broadcast_host, configure, get_rank, get_telemetry,
                   get_world_size, init_distributed, is_initialized, ppermute,
                   reduce_scatter, ring_shift)
from .mesh import (BATCH_AXES, MESH_AXES, ZERO_AXES, MeshManager, get_mesh, init_mesh,
                   set_mesh)

__all__ = [
    "all_gather", "all_reduce", "all_to_all", "axis_index", "axis_size", "barrier",
    "broadcast", "broadcast_host", "configure", "get_rank", "get_telemetry",
    "get_world_size", "init_distributed", "is_initialized", "ppermute",
    "reduce_scatter", "ring_shift", "BATCH_AXES", "MESH_AXES", "ZERO_AXES",
    "MeshManager", "get_mesh", "init_mesh", "set_mesh",
]
